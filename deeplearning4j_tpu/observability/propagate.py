"""Cross-process trace-context propagation (the ``X-DL4J-Trace`` header).

One request that traverses router -> replica -> batcher -> device used to
leave disconnected span fragments in N separate per-process trace rings.
This module is the wire half of stitching them back together: a
W3C-traceparent-style context (``trace_id``/``span_id``) that the
`FleetRouter` mints per request and every hop forwards —

- over HTTP as the ``X-DL4J-Trace`` header (format below), extracted by
  `serving/http.py` and re-attached by `serving/router.py`'s `post_json`;
- over the coordinator's JSON-line RPC as a ``trace`` field
  (`parallel/coordinator.py`);
- across threads inside one process via the `_Pending` / waiting-request
  objects (the tracer's thread-local stack does not cross the batcher /
  decode worker threads, so the context rides the queue item).

Header format (W3C traceparent with our header name)::

    X-DL4J-Trace: 00-<32 hex trace_id>-<16 hex span_id>-01

`tracing.Tracer.span(..., span_ctx=..., parent_ctx=...)` consumes these
contexts to emit spans whose events carry ``trace_id`` / ``span_id`` /
``parent_span_id`` args — `observability/federation.py` then merges the
per-process rings into one Perfetto timeline where the router span
parents replica-side spans across process (and host) boundaries.

The thread-local *current context* is installed by the inbound HTTP
handler (`bound`) and read by outbound transports (`trace_headers`) and
queue admissions (`current`) — propagation is automatic once a request
enters a traced surface. Everything here is stdlib-only and allocation-
light: minting a context is one `os.urandom` call.
"""

from __future__ import annotations

import os
import re
import threading
from typing import Dict, Optional

# The propagation header (HTTP) and RPC-document field (coordinator).
TRACE_HEADER = "X-DL4J-Trace"
TRACE_FIELD = "trace"

_HEADER_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")


class TraceContext:
    """One (trace_id, span_id) pair: the identity of a span as seen by
    its remote children. Immutable by convention."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)

    def child(self) -> "TraceContext":
        """Same trace, fresh span id — the identity of a new child span."""
        return TraceContext(self.trace_id, new_span_id())

    def to_header(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def __eq__(self, other) -> bool:
        return (isinstance(other, TraceContext)
                and other.trace_id == self.trace_id
                and other.span_id == self.span_id)

    def __hash__(self) -> int:
        return hash((self.trace_id, self.span_id))

    def __repr__(self) -> str:
        return f"TraceContext({self.trace_id!r}, {self.span_id!r})"


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def mint() -> TraceContext:
    """A brand-new trace root (the router calls this once per request)."""
    return TraceContext(new_trace_id(), new_span_id())


def parse(header: Optional[str]) -> Optional[TraceContext]:
    """Parse an ``X-DL4J-Trace`` value; None for absent/malformed input
    (an unparseable header must never fail the request it rode in on)."""
    if not header:
        return None
    m = _HEADER_RE.match(str(header).strip().lower())
    if m is None:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None  # all-zero ids are invalid per the W3C grammar
    return TraceContext(trace_id, span_id)


# ------------------------------------------------------ current context

_tls = threading.local()


def current() -> Optional[TraceContext]:
    """The context bound to this thread (None outside a traced request)."""
    return getattr(_tls, "ctx", None)


class bound:
    """``with bound(ctx): ...`` — install `ctx` as this thread's current
    context for the block (restores the previous one on exit; `ctx` may
    be None, which clears the binding for the block)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self) -> Optional[TraceContext]:
        self._prev = current()
        _tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc) -> bool:
        _tls.ctx = self._prev
        return False


def trace_headers(extra: Optional[Dict[str, str]] = None,
                  ctx: Optional[TraceContext] = None) -> Dict[str, str]:
    """HTTP headers forwarding the given (or thread-current) trace
    context — the one helper every outbound request in serving/ and
    parallel/ routes through (tpulint JX013 audits this)."""
    out = dict(extra or {})
    ctx = ctx if ctx is not None else current()
    if ctx is not None:
        out[TRACE_HEADER] = ctx.to_header()
    return out
