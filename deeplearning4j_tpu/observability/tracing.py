"""Span tracing: context manager + decorator, thread-local stack, bounded
ring buffer, Chrome trace-event JSON export.

Answers the question the metrics registry can't: not "how many / how long on
average" but "what nested inside what, when" — fit -> iteration ->
checkpoint.save, or serving.batch next to request spans on another thread.
The export is the Chrome trace-event format (`ph`/`ts`/`dur`/`pid`/`tid`),
loadable in Perfetto (ui.perfetto.dev) or `chrome://tracing`; capture it
live from a running system via the UIServer's `/api/trace` route.

The buffer is a bounded `deque` (ring): a long-running server keeps the most
recent `max_events` spans and never grows without bound. Span begin/end is a
perf_counter_ns read + a deque append — cheap enough for per-iteration spans
at training cadence; `DL4J_TPU_OBS_SAMPLE_EVERY` thins them further (see
`observability.iteration_span`).
"""

from __future__ import annotations

import functools
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


class _NoopSpan:
    """Shared reusable no-op (disabled tracer / sampled-out iteration)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attr(self, **kv):
        pass


NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("_tracer", "name", "cat", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def set_attr(self, **kv) -> None:
        self.args.update(kv)

    def __enter__(self) -> "_Span":
        tls = self._tracer._tls
        stack = getattr(tls, "stack", None)
        if stack is None:
            stack = tls.stack = []
        stack.append(self.name)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur_ns = time.perf_counter_ns() - self._t0
        tracer = self._tracer
        stack = tracer._tls.stack
        stack.pop()
        if stack:
            self.args.setdefault("parent", stack[-1])
        if exc_type is not None:
            self.args["error"] = exc_type.__name__
        tracer._events.append({
            "name": self.name,
            "cat": self.cat,
            "ph": "X",
            "ts": (self._t0 - tracer._epoch_ns) / 1000.0,  # µs
            "dur": dur_ns / 1000.0,
            "pid": tracer._pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "args": self.args,
        })
        return False


class Tracer:
    """See module docstring."""

    def __init__(self, max_events: Optional[int] = None, enabled: bool = True):
        if max_events is None:
            max_events = int(os.environ.get("DL4J_TPU_TRACE_BUFFER", "16384"))
        self.enabled = bool(enabled)
        self._events: deque = deque(maxlen=max(16, int(max_events)))
        self._tls = threading.local()
        self._epoch_ns = time.perf_counter_ns()
        self._pid = os.getpid()

    # ------------------------------------------------------------------ api

    def span(self, name: str, cat: str = "dl4j", **args):
        if not self.enabled:
            return NOOP_SPAN
        return _Span(self, name, cat, args)

    def trace(self, name: Optional[str] = None, cat: str = "dl4j"):
        """Decorator form: `@tracer.trace("checkpoint.write")`."""

        def wrap(fn):
            span_name = name or fn.__qualname__

            @functools.wraps(fn)
            def inner(*a, **kw):
                with self.span(span_name, cat=cat):
                    return fn(*a, **kw)

            return inner

        return wrap

    def instant(self, name: str, cat: str = "dl4j", **args) -> None:
        """Point-in-time marker (ph "i"), e.g. a checkpoint COMMIT."""
        if not self.enabled:
            return
        self._events.append({
            "name": name, "cat": cat, "ph": "i", "s": "t",
            "ts": (time.perf_counter_ns() - self._epoch_ns) / 1000.0,
            "pid": self._pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "args": args,
        })

    # --------------------------------------------------------------- export

    def events(self) -> List[dict]:
        return list(self._events)

    def export_chrome(self) -> Dict[str, Any]:
        """The dict form of a Chrome trace file: json.dump it and open in
        Perfetto. `displayTimeUnit` only affects the UI's default zoom."""
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def clear(self) -> None:
        self._events.clear()

    def resize(self, max_events: int) -> None:
        self._events = deque(self._events, maxlen=max(16, int(max_events)))
