"""Span tracing: context manager + decorator, thread-local stack, bounded
ring buffer, Chrome trace-event JSON export.

Answers the question the metrics registry can't: not "how many / how long on
average" but "what nested inside what, when" — fit -> iteration ->
checkpoint.save, or serving.batch next to request spans on another thread.
The export is the Chrome trace-event format (`ph`/`ts`/`dur`/`pid`/`tid`),
loadable in Perfetto (ui.perfetto.dev) or `chrome://tracing`; capture it
live from a running system via the UIServer's `/api/trace` route.

The buffer is a bounded `deque` (ring): a long-running server keeps the most
recent `max_events` spans and never grows without bound. Span begin/end is a
perf_counter_ns read + a deque append — cheap enough for per-iteration spans
at training cadence; `DL4J_TPU_OBS_SAMPLE_EVERY` thins them further (see
`observability.iteration_span`).

Cross-process spans (`observability/propagate.py`): a span opened with
``span_ctx=`` takes that context's (trace_id, span_id) as its identity; one
opened with ``parent_ctx=`` mints a fresh span id under a REMOTE parent —
the ids land in the event's ``args`` so `observability/federation.py` can
merge N processes' rings into one request tree. Each tracer also records
the wall-clock instant of its perf_counter epoch (``epochUnixUs`` in
`export_chrome`) so merged timelines align across processes.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.analysis.locktrace import named_lock
from deeplearning4j_tpu.observability import propagate as _prop


class _NoopSpan:
    """Shared reusable no-op (disabled tracer / sampled-out iteration)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attr(self, **kv):
        pass

    def ctx(self):
        return None


NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("_tracer", "name", "cat", "args", "_t0",
                 "trace_id", "span_id", "parent_span_id")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Dict[str, Any],
                 span_ctx: Optional["_prop.TraceContext"] = None,
                 parent_ctx: Optional["_prop.TraceContext"] = None):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        if span_ctx is not None:
            self.trace_id, self.span_id = span_ctx.trace_id, span_ctx.span_id
            self.parent_span_id = (parent_ctx.span_id
                                   if parent_ctx is not None else None)
        elif parent_ctx is not None:
            self.trace_id = parent_ctx.trace_id
            self.span_id = _prop.new_span_id()
            self.parent_span_id = parent_ctx.span_id
        else:
            # Plain local span: ids only if an enclosing span on this
            # thread is part of a trace (resolved at __enter__).
            self.trace_id = self.span_id = self.parent_span_id = None

    def set_attr(self, **kv) -> None:
        self.args.update(kv)

    def ctx(self) -> Optional["_prop.TraceContext"]:
        """This span's propagation context (None when it has no trace
        identity) — hand it to child threads / remote callees."""
        if self.trace_id is None:
            return None
        return _prop.TraceContext(self.trace_id, self.span_id)

    def __enter__(self) -> "_Span":
        tls = self._tracer._tls
        stack = getattr(tls, "stack", None)
        if stack is None:
            stack = tls.stack = []
        if self.trace_id is None and stack:
            encl = stack[-1]
            if encl.trace_id is not None:
                self.trace_id = encl.trace_id
                self.span_id = _prop.new_span_id()
                self.parent_span_id = encl.span_id
        stack.append(self)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur_ns = time.perf_counter_ns() - self._t0
        tracer = self._tracer
        stack = tracer._tls.stack
        stack.pop()
        if stack:
            self.args.setdefault("parent", stack[-1].name)
        if exc_type is not None:
            self.args["error"] = exc_type.__name__
        if self.trace_id is not None:
            self.args["trace_id"] = self.trace_id
            self.args["span_id"] = self.span_id
            if self.parent_span_id is not None:
                self.args["parent_span_id"] = self.parent_span_id
        tracer._record({
            "name": self.name,
            "cat": self.cat,
            "ph": "X",
            "ts": (self._t0 - tracer._epoch_ns) / 1000.0,  # µs
            "dur": dur_ns / 1000.0,
            "pid": tracer._pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "args": self.args,
        })
        return False


class Tracer:
    """See module docstring."""

    def __init__(self, max_events: Optional[int] = None, enabled: bool = True):
        if max_events is None:
            max_events = int(os.environ.get("DL4J_TPU_TRACE_BUFFER", "16384"))
        self.enabled = bool(enabled)
        self._events: deque = deque(maxlen=max(16, int(max_events)))
        self._lock = named_lock("observability.tracing")
        # Monotonic count of every event EVER recorded (not just the ones
        # still in the ring): the federation layer's incremental-export
        # cursor. The oldest ring entry's sequence number is always
        # `_seq - len(_events)`.
        self._seq = 0
        self._tls = threading.local()
        # The wall-clock instant of the perf_counter epoch: lets the
        # federation layer place this process's (monotonic) span
        # timestamps on a shared cross-process timeline.
        self._epoch_unix_us = time.time() * 1e6
        self._epoch_ns = time.perf_counter_ns()
        self._pid = os.getpid()

    # ------------------------------------------------------------------ api

    def span(self, name: str, cat: str = "dl4j",
             span_ctx: Optional["_prop.TraceContext"] = None,
             parent_ctx: Optional["_prop.TraceContext"] = None, **args):
        """Open a span. ``span_ctx`` fixes this span's (trace_id,
        span_id) identity — the ids already advertised to remote callees;
        ``parent_ctx`` parents it under a (possibly remote) context with
        a fresh span id. With neither, ids are inherited from the
        enclosing span on this thread, or omitted entirely."""
        if not self.enabled:
            return NOOP_SPAN
        return _Span(self, name, cat, args, span_ctx=span_ctx,
                     parent_ctx=parent_ctx)

    def complete(self, name: str, t0_ns: int, dur_ns: int,
                 cat: str = "dl4j",
                 span_ctx: Optional["_prop.TraceContext"] = None,
                 parent_ctx: Optional["_prop.TraceContext"] = None,
                 **args) -> None:
        """Record an already-elapsed span from explicit perf_counter_ns
        endpoints — for phases whose start lived on another thread (queue
        wait measured at batch build, device dispatch attributed to each
        coalesced request)."""
        if not self.enabled:
            return
        if span_ctx is not None:
            args["trace_id"] = span_ctx.trace_id
            args["span_id"] = span_ctx.span_id
            if parent_ctx is not None:
                args["parent_span_id"] = parent_ctx.span_id
        elif parent_ctx is not None:
            args["trace_id"] = parent_ctx.trace_id
            args["span_id"] = _prop.new_span_id()
            args["parent_span_id"] = parent_ctx.span_id
        self._record({
            "name": name, "cat": cat, "ph": "X",
            "ts": (t0_ns - self._epoch_ns) / 1000.0,
            "dur": max(0, dur_ns) / 1000.0,
            "pid": self._pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "args": args,
        })

    def trace(self, name: Optional[str] = None, cat: str = "dl4j"):
        """Decorator form: `@tracer.trace("checkpoint.write")`."""

        def wrap(fn):
            span_name = name or fn.__qualname__

            @functools.wraps(fn)
            def inner(*a, **kw):
                with self.span(span_name, cat=cat):
                    return fn(*a, **kw)

            return inner

        return wrap

    def instant(self, name: str, cat: str = "dl4j", **args) -> None:
        """Point-in-time marker (ph "i"), e.g. a checkpoint COMMIT."""
        if not self.enabled:
            return
        self._record({
            "name": name, "cat": cat, "ph": "i", "s": "t",
            "ts": (time.perf_counter_ns() - self._epoch_ns) / 1000.0,
            "pid": self._pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "args": args,
        })

    def _record(self, ev: dict) -> None:
        with self._lock:
            self._seq += 1
            self._events.append(ev)

    # --------------------------------------------------------------- export

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def export_chrome(self, since: Optional[int] = None) -> Dict[str, Any]:
        """The dict form of a Chrome trace file: json.dump it and open in
        Perfetto. `displayTimeUnit` only affects the UI's default zoom.
        ``epochUnixUs``/``pid`` are merge keys for the federation layer
        (ignored by trace viewers).

        ``since`` is the incremental-export cursor: pass the ``seq`` of a
        previous export to receive only events recorded after it — what
        keeps a steady-state federation scrape O(new events) instead of
        re-shipping the whole ring every poll. Events that aged out of
        the ring before being polled are silently gone (it's a ring)."""
        with self._lock:
            seq = self._seq
            events = list(self._events)
        if since is not None:
            oldest = seq - len(events)
            events = events[max(0, min(len(events), int(since) - oldest)):]
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "epochUnixUs": self._epoch_unix_us, "pid": self._pid,
                "seq": seq}

    def clear(self) -> None:
        # `_seq` keeps counting across clears so existing cursors stay
        # valid (they simply see an empty delta).
        with self._lock:
            self._events.clear()

    def resize(self, max_events: int) -> None:
        with self._lock:
            self._events = deque(self._events,
                                 maxlen=max(16, int(max_events)))
