"""Request-lifecycle ledger: one structured record per serving request.

Histograms answer "how slow was route X" and spans answer "what happened
inside request Y"; neither answers "which tenant spent the
device-seconds".  The ledger closes that gap: every request through the
serving tier (batcher predict path, scheduler generate path) opens one
bounded record carrying

- identity: trace id, model, tenant/adapter, route;
- lifecycle marks: admission -> queue-done -> prefill-or-prefix-hit ->
  first token -> completion, stored as seconds relative to open;
- volume: tokens in/out, speculative accept/reject counts, CoW page
  copies;
- **attributed device-seconds**: each batched dispatch's wall time split
  across its co-batched requests at the two dispatch choke points
  (`serving/batcher.py` splits by row share, `serving/scheduler.py`
  splits a decode round evenly across active slots), so per-tenant sums
  reconcile with total measured dispatch time.

Closed records land in a fixed-size ring (forensics: the flight recorder
joins it into every bundle as ``ledger.jsonl``), feed per-tenant
aggregates (the ``GET /v1/tenants`` accounting endpoint and the
``dl4j_tenant_*`` counters), and are optionally spooled to a JSONL file.

Recording is built to ride inside the serving tier's <2% observability
budget (``bench.py slo_ledger`` pins it): an open is one object + one
monotonic read; field updates are attribute ops; only close takes the
ledger lock.

Env knobs (read once at import; constructor args override for tests):

- ``DL4J_TPU_LEDGER``        — "0"/"false"/"off" disables recording
  (open() returns a shared no-op record; close() ignores it)
- ``DL4J_TPU_LEDGER_RING``   — closed-record ring capacity (default 4096)
- ``DL4J_TPU_LEDGER_SPOOL``  — JSONL spool path; empty (default) means
  ring-only, no file I/O on the serving path
- ``DL4J_TPU_LEDGER_SAMPLE`` — fraction of closed records written to the
  spool (default 1.0; 0.01 spools every 100th record, deterministically)
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.analysis.locktrace import named_lock


def _env_flag(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default).lower() not in ("0", "false", "off")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class RequestRecord:
    """One in-flight request's ledger entry. Field updates are plain
    attribute ops on purpose — each phase of a request has a single
    writer thread (HTTP handler, then batcher/scheduler loop, then the
    handler again after the completion event), so no per-record lock."""

    __slots__ = ("trace_id", "route", "model", "adapter", "t_wall",
                 "_t0_ns", "marks", "tokens_in", "tokens_out",
                 "spec_accepted", "spec_rejected", "cow_page_copies",
                 "device_seconds", "queue_wait_s", "prefix_hit", "outcome",
                 "duration_s", "_dev_child")

    def __init__(self, route: str, model: str, adapter: str,
                 trace_id: Optional[str], tokens_in: int, dev_child):
        self.trace_id = trace_id
        self.route = route
        self.model = model
        self.adapter = adapter
        self.t_wall = time.time()
        self._t0_ns = time.perf_counter_ns()
        self.marks: Dict[str, float] = {}
        self.tokens_in = int(tokens_in)
        self.tokens_out = 0
        self.spec_accepted = 0
        self.spec_rejected = 0
        self.cow_page_copies = 0
        self.device_seconds = 0.0
        self.queue_wait_s = 0.0
        self.prefix_hit: Optional[bool] = None
        self.outcome: Optional[str] = None
        self.duration_s = 0.0
        self._dev_child = dev_child

    def mark(self, name: str) -> None:
        """Lifecycle timestamp, seconds relative to open (admitted,
        queue_done, prefill, prefix_hit, first_token, done, ...)."""
        self.marks[name] = (time.perf_counter_ns() - self._t0_ns) / 1e9

    def add_device_seconds(self, s: float) -> None:
        """This request's share of one batched dispatch's wall time."""
        self.device_seconds += s
        dev = self._dev_child
        if dev is not None:
            dev.inc(s)

    def add_tokens_in(self, n: int) -> None:
        self.tokens_in += n

    def add_tokens_out(self, n: int = 1) -> None:
        self.tokens_out += n

    def add_speculative(self, accepted: int = 0, rejected: int = 0) -> None:
        self.spec_accepted += accepted
        self.spec_rejected += rejected

    def add_cow_copies(self, n: int) -> None:
        self.cow_page_copies += n

    def set_queue_wait(self, s: float) -> None:
        self.queue_wait_s = s

    def set_prefix_hit(self, hit: bool) -> None:
        self.prefix_hit = bool(hit)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "route": self.route,
            "model": self.model,
            "adapter": self.adapter,
            "t_wall": self.t_wall,
            "marks": {k: round(v, 6) for k, v in self.marks.items()},
            "tokens_in": self.tokens_in,
            "tokens_out": self.tokens_out,
            "spec_accepted": self.spec_accepted,
            "spec_rejected": self.spec_rejected,
            "cow_page_copies": self.cow_page_copies,
            "device_seconds": round(self.device_seconds, 9),
            "queue_wait_s": round(self.queue_wait_s, 6),
            "prefix_hit": self.prefix_hit,
            "outcome": self.outcome,
            "duration_s": round(self.duration_s, 6),
        }


class _NoopRecord:
    """Shared do-nothing record: disabled ledgers hand this out so call
    sites never branch (mirrors the tracer's NOOP_SPAN)."""

    __slots__ = ()

    def mark(self, name: str) -> None:
        pass

    def add_device_seconds(self, s: float) -> None:
        pass

    def add_tokens_in(self, n: int) -> None:
        pass

    def add_tokens_out(self, n: int = 1) -> None:
        pass

    def add_speculative(self, accepted: int = 0, rejected: int = 0) -> None:
        pass

    def add_cow_copies(self, n: int) -> None:
        pass

    def set_queue_wait(self, s: float) -> None:
        pass

    def set_prefix_hit(self, hit: bool) -> None:
        pass

    def to_dict(self) -> Dict[str, Any]:
        return {}


NOOP_RECORD = _NoopRecord()


class RequestLedger:
    """See module docstring. One instance (`observability.ledger.ledger`,
    re-exported as `observability.request_ledger`) is the process-global
    default; tests build their own."""

    def __init__(self, capacity: Optional[int] = None,
                 enabled: Optional[bool] = None,
                 spool_path: Optional[str] = None,
                 sample: Optional[float] = None):
        self.enabled = (_env_flag("DL4J_TPU_LEDGER")
                        if enabled is None else bool(enabled))
        if capacity is None:
            capacity = _env_int("DL4J_TPU_LEDGER_RING", 4096)
        self.spool_path = (os.environ.get("DL4J_TPU_LEDGER_SPOOL", "")
                           if spool_path is None else spool_path) or None
        if sample is None:
            sample = _env_float("DL4J_TPU_LEDGER_SAMPLE", 1.0)
        # fraction -> deterministic every-Nth stride (0 disables the spool)
        self._spool_every = (0 if sample <= 0.0
                             else max(1, int(round(1.0 / min(1.0, sample)))))
        self._ring: deque = deque(maxlen=max(16, int(capacity)))
        self._lock = named_lock("observability.ledger")
        self._closed = 0
        self._spool_file = None
        self._tenants: Dict[tuple, Dict[str, Any]] = {}
        self._dev_family = None
        self._tok_family = None

    # ------------------------------------------------------------ families

    def _families(self):
        """Tenant rollup counters, resolved lazily from the process-global
        registry (serving/metrics.py registers the same families with the
        canonical help text; the registry dedupes by name+labels)."""
        if self._dev_family is None:
            from deeplearning4j_tpu import observability as _obs

            self._dev_family = _obs.metrics.counter(
                "dl4j_tenant_device_seconds_total",
                "Attributed device-seconds per tenant",
                label_names=("model", "adapter"))
            self._tok_family = _obs.metrics.counter(
                "dl4j_tenant_tokens_total",
                "Tokens in/out per tenant",
                label_names=("model", "adapter", "direction"))
        return self._dev_family, self._tok_family

    # ----------------------------------------------------------- lifecycle

    def open(self, route: str, model: str, adapter: str = "",
             trace_id: Optional[str] = None, tokens_in: int = 0):
        """Start a record at admission. Returns NOOP_RECORD when the
        ledger is disabled so call sites stay branch-free."""
        if not self.enabled:
            return NOOP_RECORD
        try:
            if trace_id is None:
                from deeplearning4j_tpu.observability import propagate

                ctx = propagate.current()
                trace_id = ctx.trace_id if ctx is not None else None
            dev, _ = self._families()
            child = dev.labels(model=str(model), adapter=str(adapter))
            return RequestRecord(route, str(model), str(adapter), trace_id,
                                 tokens_in, child)
        except Exception:
            return NOOP_RECORD

    def close(self, rec, outcome: str = "ok") -> None:
        """Finalize a record: outcome + duration, ring append, tenant
        aggregate update, token counters, optional JSONL spool. Never
        raises (accounting must not take down serving)."""
        if rec is None or rec is NOOP_RECORD or not self.enabled:
            return
        try:
            rec.outcome = str(outcome)
            rec.duration_s = (time.perf_counter_ns() - rec._t0_ns) / 1e9
            doc = rec.to_dict()
            _, tok = self._families()
            if rec.tokens_in:
                tok.labels(model=rec.model, adapter=rec.adapter,
                           direction="in").inc(rec.tokens_in)
            if rec.tokens_out:
                tok.labels(model=rec.model, adapter=rec.adapter,
                           direction="out").inc(rec.tokens_out)
            with self._lock:
                self._closed += 1
                self._ring.append(doc)
                agg = self._tenants.setdefault(
                    (rec.model, rec.adapter), {
                        "requests": 0, "tokens_in": 0, "tokens_out": 0,
                        "device_seconds": 0.0, "queue_wait_s": 0.0,
                        "outcomes": {}})
                agg["requests"] += 1
                agg["tokens_in"] += rec.tokens_in
                agg["tokens_out"] += rec.tokens_out
                agg["device_seconds"] += rec.device_seconds
                agg["queue_wait_s"] += rec.queue_wait_s
                agg["outcomes"][rec.outcome] = (
                    agg["outcomes"].get(rec.outcome, 0) + 1)
                spool = (self._spool_every
                         and self._closed % self._spool_every == 0)
                if spool:
                    self._spool(doc)
        except Exception:
            pass

    def _spool(self, doc: Dict[str, Any]) -> None:
        """Append one JSONL line; the handle opens lazily and stays open
        (called under the ledger lock)."""
        if not self.spool_path:
            return
        try:
            if self._spool_file is None:
                d = os.path.dirname(self.spool_path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._spool_file = open(self.spool_path, "a")
            self._spool_file.write(json.dumps(doc, default=str) + "\n")
            self._spool_file.flush()
        except Exception:
            self._spool_file = None

    # ------------------------------------------------------------ plumbing

    def snapshot(self, limit: Optional[int] = None) -> List[dict]:
        """Closed records, oldest first (the flight recorder writes this
        as ledger.jsonl into every bundle)."""
        with self._lock:
            records = list(self._ring)
        if limit is not None:
            records = records[-int(limit):]
        return records

    def tenants(self) -> List[Dict[str, Any]]:
        """Per-(model, adapter) accounting rows for `GET /v1/tenants`."""
        with self._lock:
            items = [(k, dict(v, outcomes=dict(v["outcomes"])))
                     for k, v in self._tenants.items()]
        rows = []
        for (model, adapter), agg in sorted(items):
            row = {"model": model, "adapter": adapter}
            row.update(agg)
            n = agg["requests"]
            row["queue_wait_mean_s"] = (agg["queue_wait_s"] / n) if n else 0.0
            rows.append(row)
        return rows

    def status(self) -> Dict[str, Any]:
        with self._lock:
            n, closed = len(self._ring), self._closed
        return {"enabled": self.enabled, "capacity": self._ring.maxlen,
                "records": n, "closed_total": closed,
                "spool_path": self.spool_path,
                "spool_every": self._spool_every}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._tenants.clear()
            self._closed = 0


# The process-global ledger; `observability.request_ledger` re-exports it.
ledger = RequestLedger()
