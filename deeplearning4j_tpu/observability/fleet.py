"""Serving-fleet lifecycle instruments on the process-global registry.

The fleet analog of `observability/elastic.py`: the router and the
replica runtime (`serving/fleet.py`, `serving/router.py`) feed one event
counter plus the flight-recorder ring, so a post-mortem bundle shows the
failover timeline (replica joined -> lease expired -> evicted -> traffic
rerouted -> replacement warmed) next to the request-level records.

Events:

- ``replica_join``     — a replica became routable (role ``replica``)
- ``replica_warming``  — a replica registered but is still pre-warming
- ``replica_draining`` — drain started (SIGTERM or rolling update)
- ``replica_left``     — clean leave observed
- ``replica_dead``     — lease expiry evicted a replica from the table
- ``failover``         — a request was rerouted off a failed replica
- ``shed``             — the router shed a request (all replicas busy)
- ``rolling_update``   — a replica finished a drained checkpoint swap
- ``autoscale_up`` / ``autoscale_down`` — the autoscaler acted

Families are created ONCE at import (JX008); `record_event` never
raises — it runs inside signal handlers and the router's poll thread.
"""

from __future__ import annotations

from deeplearning4j_tpu import observability as _obs

EVENTS = _obs.metrics.counter(
    "dl4j_fleet_events_total",
    "Serving-fleet lifecycle events (replica_join / replica_dead / "
    "failover / shed / rolling_update / autoscale_up / ...)",
    label_names=("event",))


def record_event(event: str, **fields) -> None:
    """Count one fleet lifecycle event and mirror it into the flight
    ring. Never raises: instrumentation must not mask the fault being
    handled (same contract as `observability.elastic.record_event`)."""
    try:
        EVENTS.labels(event=event).inc()
    except Exception:
        pass
    try:
        from deeplearning4j_tpu.observability import flight

        flight.record_event(f"fleet:{event}", **fields)
    except Exception:
        pass
    try:
        # Mirror onto the trace timeline: failovers and rolling updates
        # render as instants next to request spans in a federated trace.
        _obs.tracer.instant(f"fleet:{event}", cat="fleet", **fields)
    except Exception:
        pass
