"""StepProfiler: where did this step's milliseconds go?

Wraps a live engine (`MultiLayerNetwork` / `ComputationGraph`) and splits
the time of every staged-batch dispatch into the pieces BENCH rounds have
had to eyeball from the outside:

- **compile vs execute**: XLA compile durations are captured through
  `jax.monitoring`'s event-duration hook (`/jax/core/compile/*` — the
  lowering/compile pipeline reports itself), cross-checked against the
  engines' jit-cache hit/miss counters; the first dispatch of each program
  is recorded separately from steady-state dispatches.
- **step latency**: each dispatch is (optionally) settled by fetching the
  loss scalar — the only sync that is honest over high-latency tunneled
  transports, see PERF.md §1.4 — and observed into the
  `dl4j_step_latency_seconds` histogram. `sync=False` records dispatch
  time only (does not perturb async pipelining, but under-reports).
- **host->device transfer bytes**: counted from the host-resident arrays of
  every dispatched batch (`dl4j_host_to_device_bytes_total`).
- **FLOPs + MFU**: `lower().compile().cost_analysis()` on the engine's own
  jitted train step gives FLOPs/step; divided by steady-state step time and
  the chip's peak it becomes the `dl4j_train_mfu` gauge. On CPU there is no
  peak table entry, so MFU is only reported when `DL4J_TPU_PEAK_FLOPS` /
  `BENCH_PEAK_FLOPS` is set (see PERF.md §11 caveats).

Usage::

    from deeplearning4j_tpu.observability import StepProfiler

    with StepProfiler(net) as prof:
        net.fit(iterator)
    print(prof.summary())   # and scrape /metrics for the histograms
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional


def estimate_step_flops(net, ds) -> Optional[float]:
    """XLA cost-analysis FLOPs of the engine's actual jitted train step for
    one staged batch (`bench.py` delegates here). Returns None when the
    backend does not report flops."""
    return estimate_step_cost(net, ds).get("flops")


def estimate_step_cost(net, ds) -> Dict[str, Optional[float]]:
    """XLA cost analysis of the jitted train step for one staged batch:
    ``{"flops": ..., "bytes": ...}`` where ``bytes`` is the backend's
    "bytes accessed" estimate — the HBM traffic one step moves, the
    numerator of the roofline check `bench.py` prints next to MFU. Either
    value is None when the backend does not report it."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    out: Dict[str, Optional[float]] = {"flops": None, "bytes": None}
    try:
        clock = (jnp.asarray(0.0, jnp.float32), jax.random.PRNGKey(0))
        fn = net._get_jit("train_step")
        if type(net).__name__ == "ComputationGraph":
            feats = [jnp.asarray(np.asarray(f)) for f in ds.features]
            labs = [jnp.asarray(np.asarray(l)) for l in ds.labels]
            args = (net.params_tree, net.state, net.opt_state, feats, labs,
                    None, None, clock)
        else:
            args = (net.params_tree, net.state, net.opt_state,
                    jnp.asarray(np.asarray(ds.features)),
                    jnp.asarray(np.asarray(ds.labels)), None, None, clock)
        lowered = fn.lower(*args)
        compiled = None
        try:
            compiled = lowered.compile()
            cost = compiled.cost_analysis()
        except Exception:
            cost = lowered.cost_analysis()
        if compiled is not None:
            # Piggyback: the compiled step is in hand, so its static HBM
            # footprint feeds dl4j_program_hbm_bytes for free.
            from deeplearning4j_tpu.observability import memory as _mem

            engine = ("graph" if type(net).__name__ == "ComputationGraph"
                      else "mln")
            _mem.record_program_memory(f"{engine}.train_step", compiled,
                                       net=net)
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        nbytes = float(cost.get("bytes accessed", 0.0))
        out["flops"] = flops if flops > 0 else None
        out["bytes"] = nbytes if nbytes > 0 else None
        return out
    except Exception:
        return out


def chip_peak_flops() -> Optional[float]:
    """Peak bf16 FLOPs/sec of the local accelerator (env override:
    DL4J_TPU_PEAK_FLOPS / BENCH_PEAK_FLOPS). None on CPU / unknown chips —
    callers must treat MFU as unavailable, not zero."""
    env = os.environ.get("DL4J_TPU_PEAK_FLOPS") or os.environ.get(
        "BENCH_PEAK_FLOPS")
    if env:
        return float(env)
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return None
    table = [
        ("v5 lite", 197e12), ("v5e", 197e12),
        ("v5p", 459e12), ("v5", 459e12),
        ("v6", 918e12), ("trillium", 918e12),
        ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
    ]
    for key, peak in table:
        if key in kind:
            return peak
    return None


def chip_peak_hbm_bw() -> Optional[float]:
    """Peak HBM bandwidth (bytes/sec) of the local accelerator (env
    override: DL4J_TPU_PEAK_HBM_BW / BENCH_PEAK_HBM_BW). Paired with the
    cost-analysis "bytes accessed" estimate this yields the roofline
    memory-time bound bench.py compares against compute time. None on
    CPU / unknown chips — callers must treat the roofline flag as
    unavailable, not as compute-bound."""
    env = os.environ.get("DL4J_TPU_PEAK_HBM_BW") or os.environ.get(
        "BENCH_PEAK_HBM_BW")
    if env:
        return float(env)
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return None
    table = [
        ("v5 lite", 819e9), ("v5e", 819e9),
        ("v5p", 2765e9), ("v5", 2765e9),
        ("v6", 1640e9), ("trillium", 1640e9),
        ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
    ]
    for key, bw in table:
        if key in kind:
            return bw
    return None


class StepProfiler:
    """See module docstring. Patches the engine instance's `_fit_dispatch`
    (one call per staged batch on every path: plain / tBPTT / solver) and
    `output` (inference latency) for the lifetime of the `with` block;
    restores them on exit."""

    def __init__(self, net, registry=None, tracer=None, sync: bool = True,
                 peak_flops: Optional[float] = None):
        from deeplearning4j_tpu import observability as obs

        self.net = net
        self.registry = registry or obs.metrics
        self.tracer = tracer or obs.tracer
        self.sync = bool(sync)
        self.peak_flops = peak_flops
        self.step_times: List[float] = []      # steady-state dispatches
        self.first_step_times: List[float] = []  # compile-inclusive firsts
        self.infer_times: List[float] = []
        self.h2d_bytes = 0
        self._last_ds = None
        self._patched = False
        reg = self.registry
        self._m_latency = reg.histogram(
            "dl4j_step_latency_seconds",
            "Settled train-step latency measured under StepProfiler "
            "(first compile-inclusive call excluded)")
        self._m_first = reg.histogram(
            "dl4j_step_first_call_seconds",
            "First (compile-inclusive) dispatch of each jitted program "
            "under StepProfiler", buckets=(0.1, 0.5, 1, 2.5, 5, 10, 30,
                                           60, 120, 300))
        self._m_infer = reg.histogram(
            "dl4j_infer_latency_seconds",
            "Settled output() latency measured under StepProfiler")
        self._m_compile = reg.gauge(
            "dl4j_profiler_compile_seconds",
            "XLA compile seconds attributed to the profiled window")
        self._m_execute = reg.gauge(
            "dl4j_profiler_execute_seconds_median",
            "Median steady-state step seconds in the profiled window")
        self._m_flops = reg.gauge(
            "dl4j_train_flops_per_step",
            "XLA cost-analysis FLOPs of one jitted train step")
        self._m_mfu = reg.gauge(
            "dl4j_train_mfu",
            "Model FLOPs utilization: flops/step / step_time / chip peak "
            "(absent without a known peak — see PERF.md CPU caveats)")

    # ------------------------------------------------------------ patching

    def __enter__(self) -> "StepProfiler":
        from deeplearning4j_tpu import observability as obs

        obs.install_jax_compile_hook(self.registry)
        try:
            from deeplearning4j_tpu.observability import memory as _mem

            _mem.register_tree(type(self.net).__name__, self.net)
        except Exception:
            pass
        self._compile_s0 = self._compile_seconds()
        self._cache_counts0 = self._cache_counts()
        self._input_wait0 = self._input_wait_totals()
        self._staging0 = self._staging_totals()
        self._jit_known = len(self.net._jit_cache)
        self._orig_dispatch = self.net._fit_dispatch
        self._orig_output = self.net.output
        net = self.net

        def dispatch(ds, *a, **kw):
            self._last_ds = ds
            self.h2d_bytes += _host_nbytes(ds)
            known = len(net._jit_cache)
            t0 = time.perf_counter()
            # No extra span here: the engine's own iteration span already
            # covers the dispatch, and an extra wrapper would usurp its
            # parentage in the trace.
            out = self._orig_dispatch(ds, *a, **kw)
            if self.sync:
                _settle(net)
            dt = time.perf_counter() - t0
            if len(net._jit_cache) > known:
                # This dispatch traced (and on first real call, compiled) a
                # new program: keep it out of the steady-state histogram.
                self.first_step_times.append(dt)
                self._m_first.observe(dt)
            else:
                self.step_times.append(dt)
                self._m_latency.observe(dt)
            return out

        def output(*a, **kw):
            t0 = time.perf_counter()
            result = self._orig_output(*a, **kw)
            dt = time.perf_counter() - t0
            self.infer_times.append(dt)
            self._m_infer.observe(dt)
            return result

        self.net._fit_dispatch = dispatch
        self.net.output = output
        self._patched = True
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.stop()
        return False

    def stop(self) -> None:
        if self._patched:
            self.net._fit_dispatch = self._orig_dispatch
            self.net.output = self._orig_output
            self._patched = False
        self._finalize()

    # ----------------------------------------------------------- reporting

    def _compile_seconds(self) -> float:
        fam = self.registry.get_family("dl4j_xla_compile_seconds_total")
        if fam is None:
            return 0.0
        return sum(c.get() for c in fam.children())

    def compile_seconds(self) -> float:
        """XLA compile seconds that elapsed inside the profiled window.
        A persistent-cache hit's near-zero backend_compile event still
        lands here (it is seconds spent, just tiny); the hit itself is
        reported under `summary()['compile_cache']`, not as a compile."""
        return max(0.0, self._compile_seconds() - self._compile_s0)

    def _cache_counts(self) -> Dict[str, float]:
        counts: Dict[str, float] = {}
        for kind, name in (("hits", "dl4j_compile_cache_hits_total"),
                           ("misses", "dl4j_compile_cache_misses_total")):
            fam = self.registry.get_family(name)
            if fam is None:
                continue
            for child in fam.children():
                source = child.labels.get("source", "_")
                counts[f"{kind}_{source}"] = child.get()
        return counts

    def compile_cache_deltas(self) -> Dict[str, float]:
        """Per-source compile-cache hit/miss counts inside the profiled
        window, e.g. {'hits_aot': 2, 'misses_persistent': 1}."""
        base = getattr(self, "_cache_counts0", {})
        out: Dict[str, float] = {}
        for key, val in self._cache_counts().items():
            delta = val - base.get(key, 0.0)
            if delta > 0:
                out[key] = delta
        return out

    def _input_wait_totals(self) -> tuple:
        fam = self.registry.get_family("dl4j_input_wait_seconds")
        if fam is None:
            return (0.0, 0)
        s_total, c_total = 0.0, 0
        for child in fam.children():
            _, _, s, c = child.histogram_state()
            s_total += s
            c_total += c
        return (s_total, c_total)

    def input_wait(self) -> tuple:
        """(seconds, observations) the host spent blocked in iterator-next
        inside the profiled window — starvation shows up here, not in step
        latency."""
        s0, c0 = getattr(self, "_input_wait0", (0.0, 0))
        s, c = self._input_wait_totals()
        return (max(0.0, s - s0), max(0, c - c0))

    def _staging_totals(self) -> Dict[str, float]:
        """Current totals of the datasets/staging transfer counters:
        bytes shipped by background stagers, and device_put seconds split
        by overlapped (stager-thread) vs synchronous (caller-thread)."""
        out = {"overlapped_bytes": 0.0, "overlapped_put_seconds": 0.0,
               "synchronous_put_seconds": 0.0, "staging_wait_seconds": 0.0}
        fam = self.registry.get_family("dl4j_staging_bytes_total")
        if fam is not None:
            out["overlapped_bytes"] = sum(c.get() for c in fam.children())
        fam = self.registry.get_family("dl4j_staging_put_seconds_total")
        if fam is not None:
            for child in fam.children():
                mode = child.labels.get("mode", "synchronous")
                out[f"{mode}_put_seconds"] = (
                    out.get(f"{mode}_put_seconds", 0.0) + child.get())
        fam = self.registry.get_family("dl4j_staging_wait_seconds")
        if fam is not None:
            for child in fam.children():
                _, _, s, _ = child.histogram_state()
                out["staging_wait_seconds"] += s
        return out

    def staging_deltas(self) -> Dict[str, float]:
        """Overlapped-transfer activity inside the profiled window (see
        `_staging_totals` for the keys). All zeros when no DeviceStager
        ran — the synchronous path."""
        base = getattr(self, "_staging0", {})
        return {key: max(0.0, val - base.get(key, 0.0))
                for key, val in self._staging_totals().items()}

    def execute_seconds_median(self) -> Optional[float]:
        if not self.step_times:
            return None
        return sorted(self.step_times)[len(self.step_times) // 2]

    def _finalize(self) -> None:
        compile_s = self.compile_seconds()
        if not compile_s and self.first_step_times and self.step_times:
            # No monitoring hook on this jax: fall back to first-call-minus-
            # steady-state (documented as an estimate in summary()).
            med = self.execute_seconds_median() or 0.0
            compile_s = max(0.0, sum(self.first_step_times)
                            - med * len(self.first_step_times))
        self._m_compile.set(compile_s)
        med = self.execute_seconds_median()
        if med is not None:
            self._m_execute.set(med)
        flops = None
        if self._last_ds is not None:
            flops = estimate_step_flops(self.net, self._last_ds)
        if flops:
            self._m_flops.set(flops)
            peak = self.peak_flops or chip_peak_flops()
            if peak and med:
                self._m_mfu.set(flops / med / peak)

    def summary(self) -> Dict[str, Any]:
        med = self.execute_seconds_median()
        staging = self.staging_deltas()
        out: Dict[str, Any] = {
            "steps": len(self.step_times) + len(self.first_step_times),
            "first_call_steps": len(self.first_step_times),
            "compile_seconds": self.compile_seconds() or self._m_compile.get(),
            "execute_seconds_median": med,
            # Dispatch-visible host bytes plus what background stagers
            # shipped (staged batches reach dispatch device-resident, so
            # the dispatch-side count alone would read ~0 under overlap).
            "host_to_device_bytes": (self.h2d_bytes
                                     + int(staging["overlapped_bytes"])),
        }
        if any(staging.values()):
            out["transfer"] = {
                "overlapped_bytes": int(staging["overlapped_bytes"]),
                "synchronous_bytes": self.h2d_bytes,
                "overlapped_put_seconds": staging["overlapped_put_seconds"],
                "synchronous_put_seconds": staging["synchronous_put_seconds"],
                "staging_wait_seconds": staging["staging_wait_seconds"],
            }
        cache = self.compile_cache_deltas()
        if cache:
            out["compile_cache"] = cache
        wait_s, wait_n = self.input_wait()
        if wait_n:
            out["input_wait"] = {"seconds": wait_s, "observations": wait_n,
                                 "mean": wait_s / wait_n}
        if self.step_times:
            s = sorted(self.step_times)
            out["step_latency"] = {
                "mean": sum(s) / len(s), "p50": s[len(s) // 2],
                "min": s[0], "max": s[-1],
                "sync": self.sync,
            }
        if self.infer_times:
            s = sorted(self.infer_times)
            out["infer_latency"] = {"mean": sum(s) / len(s),
                                    "p50": s[len(s) // 2], "count": len(s)}
        flops = self._m_flops.get()
        if flops:
            out["flops_per_step"] = flops
            if med:
                out["flops_per_sec"] = flops / med
        mfu = self._m_mfu.get()
        if mfu:
            out["mfu"] = mfu
        return out


def _settle(net) -> None:
    """Force completion of the dispatched step. Fetching the loss scalar is
    the sync that works over every transport (block_until_ready does not
    reliably wait on the tunneled TPU path — PERF.md §1.4); params are a
    fallback for solver paths that leave `_score` as a host float."""
    score = getattr(net, "_score", None)
    try:
        float(score)
        return
    except Exception:
        pass
    try:
        import jax

        jax.block_until_ready(net.params_tree)
    except Exception:
        pass


def _host_nbytes(ds) -> int:
    """Bytes of host-resident (numpy) arrays in a DataSet / MultiDataSet —
    the batch's host->device transfer cost; device-resident arrays count 0."""
    import numpy as np

    total = 0
    for name in ("features", "labels", "features_mask", "labels_mask",
                 "features_masks", "labels_masks"):
        part = getattr(ds, name, None)
        if part is None:
            continue
        arrays = part if isinstance(part, (list, tuple)) else [part]
        for a in arrays:
            if isinstance(a, np.ndarray):
                total += a.nbytes
    return total
