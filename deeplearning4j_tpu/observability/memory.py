"""Per-program HBM accounting + live-buffer attribution.

Answers "where did the HBM go" with two complementary views:

- **Static, per program**: every executable that materializes in
  `compilation/program.py` (AOT-store hit, live compile, or the
  profiler's cost-analysis probe) reports `compiled.memory_analysis()`
  — XLA's own accounting of argument / output / temp / generated-code
  bytes — into the `dl4j_program_hbm_bytes{program,kind}` gauges. This
  is the number that explains an OOM *before* it happens: temp bytes are
  the scratch high-water mark the program will ask the allocator for.
- **Dynamic, per owner**: `live_buffer_report()` walks
  `jax.live_arrays()` and attributes every buffer to a registered model
  tree (params / state / opt_state, grouped by top-level leaf prefix,
  e.g. `layer_3`), with the remainder reported as unattributed. Models
  register via `register_tree(name, net)` (the serving host and
  `StepProfiler` do this automatically); registration holds only a
  weakref, so it never extends a model's lifetime.

`measured_model_bytes(net)` combines both for the serving tier: the
summed bytes of the net's *actual device-resident* array leaves plus the
largest transient (temp + output) footprint recorded for one of its
programs — the measured eviction cost `serving/host.py` budgets with
(falling back to the leaf-`nbytes` estimate when nothing device-resident
exists yet).

Everything here runs at compile time or scrape time — never in the
training hot loop.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Dict, Optional

from deeplearning4j_tpu import observability as _obs
from deeplearning4j_tpu.analysis.locktrace import named_lock

# Byte categories reported by XLA's CompiledMemoryStats -> gauge `kind`.
_STAT_KINDS = (
    ("argument", "argument_size_in_bytes"),
    ("output", "output_size_in_bytes"),
    ("temp", "temp_size_in_bytes"),
    ("generated_code", "generated_code_size_in_bytes"),
    ("alias", "alias_size_in_bytes"),
)

_M_PROGRAM_HBM = _obs.metrics.gauge(
    "dl4j_program_hbm_bytes",
    "Static per-program device memory from XLA's memory_analysis(): "
    "argument/output/temp/generated_code/alias bytes plus their total "
    "(aliased bytes counted once)",
    label_names=("program", "kind"))

_lock = named_lock("observability.memory")
_programs: Dict[str, Dict[str, Any]] = {}   # label -> {bytes, net_ref}
_trees: Dict[str, Any] = {}                 # name -> weakref to a net


def program_label(kind: str, static: Optional[dict] = None) -> str:
    """Stable `program` label for a compiled executable: the program kind
    plus its static config, e.g. `solver_step[algo=LBFGS]`."""
    if not static:
        return kind
    inner = ",".join(f"{k}={static[k]}" for k in sorted(static))
    return f"{kind}[{inner}]"


def record_program_memory(program: str, compiled, net=None) -> Optional[dict]:
    """Capture `compiled.memory_analysis()` into the per-program gauges.
    Safe on every backend: returns the byte dict, or None when the
    executable does not expose memory stats. Never raises."""
    try:
        analysis = compiled.memory_analysis()
        if analysis is None:
            return None
        stats = {name: int(getattr(analysis, attr, 0) or 0)
                 for name, attr in _STAT_KINDS}
    except Exception:
        return None
    stats["total"] = max(0, stats["argument"] + stats["output"]
                         + stats["temp"] + stats["generated_code"]
                         - stats["alias"])
    for kind, v in stats.items():
        _M_PROGRAM_HBM.labels(program=program, kind=kind).set(v)
    with _lock:
        _programs[program] = {
            "bytes": stats,
            "net_ref": None if net is None else weakref.ref(net),
        }
    return stats


def program_memory_snapshot() -> Dict[str, Dict[str, int]]:
    """{program: {kind: bytes}} for every recorded executable."""
    with _lock:
        return {label: dict(rec["bytes"]) for label, rec in _programs.items()}


# --------------------------------------------------- live-buffer attribution


def register_tree(name: str, net) -> None:
    """Register a model for live-buffer attribution (weakref only)."""
    with _lock:
        _trees[str(name)] = weakref.ref(net)


def unregister_tree(name: str) -> None:
    with _lock:
        _trees.pop(str(name), None)


def _leaf_prefix(path) -> str:
    if not path:
        return "_"
    entry = path[0]
    for attr in ("key", "name", "idx"):
        v = getattr(entry, attr, None)
        if v is not None:
            return str(v)
    return str(entry)


def _owned_leaves(net):
    """(leaf, group) pairs for a net's device-facing trees, where group is
    `attr/top-level-prefix` (e.g. `params_tree/layer_0`)."""
    import jax

    for attr in ("params_tree", "state", "opt_state"):
        tree = getattr(net, attr, None)
        if tree is None:
            continue
        try:
            flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        except Exception:
            continue
        for path, leaf in flat:
            if hasattr(leaf, "nbytes"):
                yield leaf, f"{attr}/{_leaf_prefix(path)}"


def live_buffer_report() -> Dict[str, Any]:
    """Attribute `jax.live_arrays()` bytes to registered model trees,
    grouped per model by param-leaf prefix. Buffers owned by nothing
    registered land in `unattributed_bytes`."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:  # never import jax just to report an empty process
        return {"total_bytes": 0, "models": {}, "unattributed_bytes": 0}

    owners: Dict[int, tuple] = {}
    with _lock:
        registered = list(_trees.items())
    for name, ref in registered:
        net = ref()
        if net is None:
            unregister_tree(name)
            continue
        for leaf, group in _owned_leaves(net):
            owners[id(leaf)] = (name, group)

    models: Dict[str, Dict[str, Any]] = {}
    total = unattributed = 0
    try:
        arrays = jax.live_arrays()
    except Exception:
        arrays = []
    for a in arrays:
        nb = int(getattr(a, "nbytes", 0) or 0)
        total += nb
        who = owners.get(id(a))
        if who is None:
            unattributed += nb
            continue
        name, group = who
        m = models.setdefault(name, {"bytes": 0, "groups": {}})
        m["bytes"] += nb
        m["groups"][group] = m["groups"].get(group, 0) + nb
    return {"total_bytes": total, "models": models,
            "unattributed_bytes": unattributed}


# ------------------------------------------------------- serving integration


def measured_model_bytes(net) -> Optional[int]:
    """Measured device footprint of a loaded model: summed bytes of its
    jax.Array leaves (the buffers actually committed to the device, not a
    host-side nbytes guess) plus the largest transient temp+output
    footprint among this net's recorded programs. None when the net holds
    no device arrays yet — callers keep the estimate."""
    try:
        import jax
    except Exception:
        return None
    total = 0
    found = False
    for attr in ("params_tree", "state", "opt_state"):
        tree = getattr(net, attr, None)
        if tree is None:
            continue
        for leaf in jax.tree_util.tree_leaves(tree):
            if isinstance(leaf, jax.Array):
                total += int(getattr(leaf, "nbytes", 0) or 0)
                found = True
    if not found:
        return None
    transient = 0
    with _lock:
        for rec in _programs.values():
            ref = rec.get("net_ref")
            if ref is not None and ref() is net:
                b = rec["bytes"]
                transient = max(transient,
                                b.get("temp", 0) + b.get("output", 0))
    return total + transient


def report() -> Dict[str, Any]:
    """The `/api/memory` payload: static per-program accounting + live
    attribution in one document."""
    return {"programs": program_memory_snapshot(),
            "live": live_buffer_report()}
