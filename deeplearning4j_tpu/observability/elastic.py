"""Elastic-training instruments on the process-global registry.

The reference stack surfaced cluster health through the Spark UI's
training-master listeners; here the `ElasticTrainer` (parallel/elastic.py)
feeds three families plus the flight-recorder ring so a post-mortem
bundle shows the full preemption timeline next to the per-step records:

- ``dl4j_elastic_events_total{event}`` — the recovery state machine's
  transitions: ``preempt`` (SIGTERM observed), ``host_lost`` (heartbeat /
  step-barrier timeout evicted a member), ``restart`` (supervisor
  re-entered the join loop), ``restore`` (committed checkpoint loaded
  onto the re-formed mesh), ``restore_fallback`` (newest step failed
  corruption checks, previous committed step used), ``coordinator_retry``
  (a coordinator RPC needed a backoff retry).
- ``dl4j_elastic_recovery_seconds`` — fault detected -> training resumed
  (WIDE buckets: recoveries sit in the 1s..600s band, not microseconds).
- ``dl4j_elastic_restarts_total`` — restarts this run (alert threshold:
  a run burning its `DL4J_TPU_ELASTIC_MAX_RESTARTS` budget is churning).

Families are created ONCE at import (JX008: never in a loop or step
path); `record_event` is a counter bump + ring append, safe to call from
signal handlers and the heartbeat thread.
"""

from __future__ import annotations

import time

from deeplearning4j_tpu import observability as _obs

EVENTS = _obs.metrics.counter(
    "dl4j_elastic_events_total",
    "Elastic-training lifecycle events (preempt / host_lost / restart / "
    "restore / restore_fallback / coordinator_retry)",
    label_names=("event",))
RECOVERY_SECONDS = _obs.metrics.histogram(
    "dl4j_elastic_recovery_seconds",
    "Time-to-recover: fault detected -> training step resumed",
    buckets=_obs.WIDE_BUCKETS)
RESTARTS = _obs.metrics.counter(
    "dl4j_elastic_restarts_total",
    "ElasticTrainer supervisor restarts (join-loop re-entries) this run")


def record_event(event: str, **fields) -> None:
    """Count one lifecycle event and mirror it into the flight ring.

    Never raises: this is called from signal handlers and monitor
    threads where an instrumentation failure must not mask the fault
    being handled.
    """
    try:
        EVENTS.labels(event=event).inc()
    except Exception:
        pass
    try:
        # `observability.flight` is re-exported as the recorder INSTANCE.
        from deeplearning4j_tpu.observability import flight

        flight.record_event(f"elastic:{event}", **fields)
    except Exception:
        pass
    try:
        # Mirror onto the trace timeline: recovery events render as
        # instants next to the request spans in a federated /api/trace.
        _obs.tracer.instant(f"elastic:{event}", cat="elastic", **fields)
    except Exception:
        pass


def observe_recovery(seconds: float) -> None:
    try:
        RECOVERY_SECONDS.observe(float(seconds))
    except Exception:
        pass


class RecoveryTimer:
    """Context helper: ``with RecoveryTimer() as t: ...`` then
    ``t.seconds``; observes into the histogram on clean exit."""

    def __enter__(self):
        self.start = time.monotonic()
        self.seconds = 0.0
        return self

    def __exit__(self, exc_type, exc, tb):
        self.seconds = time.monotonic() - self.start
        if exc_type is None:
            observe_recovery(self.seconds)
        return False
