"""Fleet-wide metrics federation and trace merging.

The per-process observability core (`metrics.py`, `tracing.py`) answers
questions about ONE process; a serving fleet is N replicas + a router +
a coordinator, each with its own `/metrics` and its own span ring. This
module is the aggregation half of the cross-process plane
(`propagate.py` is the wire half):

- `merge_prometheus` — merge N processes' Prometheus text expositions
  into one, every sample gaining a ``worker_id`` label (exactly what a
  Prometheus federation endpoint does), HELP/TYPE kept once per family.
- `merge_traces` — merge N processes' Chrome trace rings (the
  `Tracer.export_chrome` dicts) onto ONE timeline: per-process
  monotonic timestamps are aligned via each ring's ``epochUnixUs``
  wall-clock anchor, processes are named with ``process_name`` metadata
  events, and every event keeps its ``trace_id``/``span_id`` args — so
  a request propagated with `propagate.py` renders in Perfetto as one
  parent-child tree spanning the router, two failover replicas, and the
  coordinator.
- `FleetAggregator` — discovers live members from the coordinator's
  `status` op (the same membership the router routes on), scrapes each
  member's `/metrics` and `/api/trace`, and serves the merged results
  (`serve()`) as fleet-wide ``GET /metrics`` / ``GET /api/trace``.

Member discovery rides the worker-id convention the serving fleet
already uses (``name@host:port`` with an HTTP server at ``host:port``);
the coordinator itself is discovered via the ``metrics_url`` it
advertises in `status`. A member that fails to answer within
`scrape_timeout_s` is skipped and reported as
``dl4j_federation_up{worker_id=...} 0`` — one dead replica must never
take down the fleet view.

The scrape loop here is the intentional JX013 allowlist: federation
scrapes are trace ROOTS, not request hops — they forward no context.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
import urllib.parse
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu import observability as _obs
from deeplearning4j_tpu.analysis.locktrace import named_lock
from deeplearning4j_tpu.util.retry import Backoff

#: Synthetic family reporting per-member scrape health in the federated
#: exposition (1 = answered within the timeout, 0 = skipped).
UP_FAMILY = "dl4j_federation_up"


# ------------------------------------------------------- prometheus merge


def _merged_sample(line: str, worker_id: str) -> str:
    """Rewrite one sample line so ``worker_id`` is its first label."""
    # `name{labels} value`  |  `name value`
    brace = line.find("{")
    if brace != -1:
        return (line[:brace] + '{worker_id="' + worker_id + '",'
                + line[brace + 1:])
    name, _, rest = line.partition(" ")
    return f'{name}{{worker_id="{worker_id}"}} {rest}'


def merge_prometheus(texts: Dict[str, str]) -> str:
    """Merge per-worker Prometheus text expositions into one, injecting
    ``worker_id`` into every sample. Families keep first-seen order and
    ONE HELP/TYPE header (exposition validity requires all of a family's
    samples grouped under a single TYPE line)."""
    order: List[str] = []
    helps: Dict[str, str] = {}
    types: Dict[str, str] = {}
    samples: Dict[str, List[str]] = {}
    for worker_id, text in texts.items():
        fam = None
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith("# TYPE "):
                parts = line.split(None, 3)
                if len(parts) >= 4:
                    fam = parts[2]
                    if fam not in types:
                        types[fam] = parts[3]
                        order.append(fam)
                        samples.setdefault(fam, [])
                continue
            if line.startswith("# HELP "):
                parts = line.split(None, 3)
                if len(parts) >= 3:
                    helps.setdefault(parts[2],
                                     parts[3] if len(parts) > 3 else "")
                continue
            if line.startswith("#"):
                continue
            if fam is None:
                # Headerless sample (foreign exposition): family = the
                # metric name itself, typed as untyped.
                name = line.split("{", 1)[0].split(" ", 1)[0]
                fam = name
                if fam not in types:
                    types[fam] = "untyped"
                    order.append(fam)
                    samples.setdefault(fam, [])
            samples[fam].append(_merged_sample(line, worker_id))
    out: List[str] = []
    for fam in order:
        if not samples.get(fam):
            continue
        if helps.get(fam):
            out.append(f"# HELP {fam} {helps[fam]}")
        out.append(f"# TYPE {fam} {types[fam]}")
        out.extend(samples[fam])
    return "\n".join(out) + "\n"


# ------------------------------------------------------------ trace merge


def merge_traces(docs: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-worker `Tracer.export_chrome` dicts onto one timeline.

    Each ring's ``ts`` values are relative to its own perf_counter
    epoch; the ``epochUnixUs`` anchor shifts them onto a shared clock
    (earliest epoch = 0). Every event gains ``args.worker_id`` and a
    ``process_name`` metadata row labels the pid in Perfetto's track
    list. The result is a standard Chrome trace: json.dump and load it
    at ui.perfetto.dev."""
    epochs = {wid: float(doc.get("epochUnixUs", 0.0))
              for wid, doc in docs.items()}
    base = min(epochs.values()) if epochs else 0.0
    events: List[dict] = []
    meta: List[dict] = []
    for wid, doc in docs.items():
        shift = epochs[wid] - base
        pid = doc.get("pid", 0)
        seen_pids = set()
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["ts"] = float(ev.get("ts", 0.0)) + shift
            ev.setdefault("pid", pid)
            args = dict(ev.get("args") or {})
            args.setdefault("worker_id", wid)
            ev["args"] = args
            seen_pids.add(ev["pid"])
            events.append(ev)
        for p in sorted(seen_pids) or [pid]:
            meta.append({"name": "process_name", "ph": "M", "pid": p,
                         "tid": 0, "args": {"name": wid}})
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


# ------------------------------------------------------------- aggregator


class FleetAggregator:
    """Scrape every live fleet member and serve the merged view.

    Membership comes from the coordinator's `status` op — the same
    table the router routes on — so the aggregator tracks joins,
    drains, and evictions with zero extra registration machinery.
    Replicas are scraped at the HTTP address embedded in their
    ``name@host:port`` worker id; the coordinator at the
    ``metrics_url`` it advertises. The local process (typically the
    router hosting the aggregator) is merged directly from the
    in-process registry/tracer under ``local_worker_id``."""

    def __init__(self, coordinator_address: str,
                 scrape_timeout_s: float = 1.0,
                 local_worker_id: Optional[str] = None,
                 registry=None, tracer=None,
                 retention_events: int = 16384):
        from deeplearning4j_tpu.parallel.coordinator import CoordinatorClient

        self.scrape_timeout_s = float(scrape_timeout_s)
        self.local_worker_id = local_worker_id
        self._registry = registry or _obs.metrics
        self._tracer = tracer or _obs.tracer
        # Per-worker accumulated trace state for incremental scraping
        # (`/api/trace?since=<seq>`): steady-state polls ship only the
        # delta, and a member that is momentarily unreachable (hung,
        # draining) keeps its already-collected spans on the timeline.
        # {wid: {"events": deque, "epoch": float, "pid": int,
        #        "cursor": Optional[int]}}
        self._retention_events = max(16, int(retention_events))
        self._trace_state: Dict[str, Dict[str, Any]] = {}
        self._trace_lock = named_lock("observability.federation.trace")
        self._trace_inflight: set = set()  # wids being scraped right now
        # Persistent keep-alive connections, one per member netloc: a
        # scrape cycle is 2 GETs x N members — re-dialing TCP for each
        # is the dominant per-poll cost on loopback. Guarded by a lock
        # (http.client connections are not thread-safe).
        self._conns: Dict[str, Any] = {}
        self._conn_lock = named_lock("observability.federation.conn")
        # One membership lookup serves a whole metrics+trace cycle.
        self._members_ttl_s = 0.5
        self._members_cache: Tuple[float, Dict[str, str]] = (0.0, {})
        # Members whose coordinator lease has expired (lease_age_s past
        # lost_after_s) but who haven't been evicted from `status` yet:
        # never scraped (their numbers are stale by definition), surfaced
        # as dl4j_federation_up 0 so one poll flags the staleness.
        self._stale_members: Dict[str, str] = {}
        # Status-only client: never joins, tight backoff — a dead
        # coordinator should fail the fleet view fast, not hang it.
        self._client = CoordinatorClient(
            coordinator_address, worker_id="fleet-aggregator",
            rpc_timeout_s=self.scrape_timeout_s,
            backoff=Backoff(base_s=0.05, max_s=0.2, tries=2))
        self._http = None
        self.url: Optional[str] = None

    # ---------------------------------------------------------- discovery

    def members(self) -> Dict[str, str]:
        """``{worker_id: base_url}`` for every scrapeable member.
        Cached briefly (`_members_ttl_s`) so one status RPC serves a
        whole metrics+trace scrape cycle."""
        now = time.monotonic()
        stamp, cached = self._members_cache
        if cached and now - stamp < self._members_ttl_s:
            return dict(cached)
        doc = self._client.status()
        out: Dict[str, str] = {}
        stale: Dict[str, str] = {}
        lost_after = doc.get("lost_after_s")
        for wid, d in doc.get("detail", {}).items():
            role = str(d.get("role", ""))
            if not role.startswith("replica") or "@" not in wid:
                continue
            addr = wid.rsplit("@", 1)[1]
            lease_age = d.get("lease_age_s")
            if (lost_after is not None and lease_age is not None
                    and float(lease_age) >= float(lost_after)):
                # Lease expired but not yet evicted from `status`: its
                # counters are from before the silence began — dropping
                # the scrape beats federating stale numbers as fresh.
                stale[wid] = f"http://{addr}"
                continue
            out[wid] = f"http://{addr}"
        self._stale_members = stale
        murl = doc.get("metrics_url")
        if murl:
            out[f"coordinator@{self._client.host}:{self._client.port}"] = \
                str(murl)
        if self.local_worker_id is not None:
            out.pop(self.local_worker_id, None)  # merged in-process
        self._members_cache = (now, dict(out))
        return out

    # ------------------------------------------------------------ scraping

    def _scrape_text(self, url: str) -> str:
        """GET over a persistent per-member connection; one silent
        re-dial absorbs a server-side keep-alive close or a member
        restart on the same address. The connection is CHECKED OUT of
        the pool for the request's duration: http.client connections
        are not thread-safe, but holding the pool lock across the GET
        serialized every member's scrape behind one socket (JX018).
        Concurrent scrapes of the same netloc each dial their own
        connection; check-in keeps the latest and closes the evicted
        one (idle, by construction — a checked-out conn is not in the
        pool)."""
        u = urllib.parse.urlsplit(url)
        path = u.path + (f"?{u.query}" if u.query else "")
        for attempt in (0, 1):
            with self._conn_lock:
                conn = self._conns.pop(u.netloc, None)
            if conn is None:
                conn = http.client.HTTPConnection(
                    u.hostname, u.port, timeout=self.scrape_timeout_s)
            try:
                conn.request("GET", path)
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    raise OSError(f"HTTP {resp.status} from {url}")
            except Exception:
                conn.close()
                if attempt:
                    raise
                continue
            with self._conn_lock:
                evicted = self._conns.get(u.netloc)
                self._conns[u.netloc] = conn
            if evicted is not None:
                evicted.close()
            return body.decode("utf-8")
        raise OSError(f"unreachable: {url}")  # not reached

    def federate_metrics(self) -> str:
        """One fleet-wide Prometheus exposition: every member's families
        merged under ``worker_id``, plus `UP_FAMILY` marking members
        that failed to answer."""
        texts: Dict[str, str] = {}
        up: List[Tuple[str, int]] = []
        if self.local_worker_id is not None:
            texts[self.local_worker_id] = self._registry.to_prometheus()
            up.append((self.local_worker_id, 1))
        for wid, base in self.members().items():
            try:
                texts[wid] = self._scrape_text(base + "/metrics")
                up.append((wid, 1))
            except Exception:
                up.append((wid, 0))
        for wid in self._stale_members:
            up.append((wid, 0))
        merged = merge_prometheus(texts)
        lines = [f"# TYPE {UP_FAMILY} gauge"]
        lines += [f'{UP_FAMILY}{{worker_id="{w}"}} {v}' for w, v in up]
        return merged + "\n".join(lines) + "\n"

    def _ingest_trace(self, wid: str, doc: Dict[str, Any]) -> None:
        """Fold one `/api/trace` response into the accumulated per-worker
        state. A response carrying ``seq`` is an incremental ring export:
        its events append behind the stored ones. A response without
        ``seq`` (foreign exporter) replaces the state wholesale. A
        changed (epoch, pid) means the worker restarted — the old
        incarnation's ring is gone, so start over.

        Ingest does ALL per-event work (epoch alignment onto absolute
        wall-clock microseconds, ``worker_id``/``pid`` tagging) exactly
        once, so a federate_trace poll is concat + sort over ready
        events — O(new events) of real work, not O(everything retained)
        re-merged on every poll."""
        epoch = float(doc.get("epochUnixUs", 0.0))
        pid = doc.get("pid", 0)
        seq = doc.get("seq")
        st = self._trace_state.get(wid)
        if (st is None or st["epoch"] != epoch or st["pid"] != pid
                or seq is None):
            st = {"events": deque(maxlen=self._retention_events),
                  "epoch": epoch, "pid": pid, "cursor": None,
                  "pids": set()}
            self._trace_state[wid] = st
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["ts"] = float(ev.get("ts", 0.0)) + epoch
            ev.setdefault("pid", pid)
            args = dict(ev.get("args") or {})
            args.setdefault("worker_id", wid)
            ev["args"] = args
            st["pids"].add(ev["pid"])
            st["events"].append(ev)
        st["pids"].add(pid)
        st["cursor"] = seq

    def _scrape_trace(self, wid: str, base: str) -> None:
        """Incremental trace scrape of one member. The HTTP GET runs
        with `_trace_lock` RELEASED (JX018: holding it across member
        I/O stalled every concurrent /api/trace poll); the per-wid
        in-flight marker keeps the cursor-read -> scrape -> ingest
        cycle single-flight, so two concurrent polls can't both fetch
        `?since=<cursor>` and ingest the same delta twice."""
        with self._trace_lock:
            if wid in self._trace_inflight:
                return  # another poll is already fetching this member
            self._trace_inflight.add(wid)
            st = self._trace_state.get(wid)
            cursor = st["cursor"] if st else None
        try:
            url = base + "/api/trace"
            if cursor is not None:
                url += f"?since={cursor}"
            doc = json.loads(self._scrape_text(url))
            if isinstance(doc, dict):
                with self._trace_lock:
                    self._ingest_trace(wid, doc)
        finally:
            with self._trace_lock:
                self._trace_inflight.discard(wid)

    def federate_trace(self) -> Dict[str, Any]:
        """One fleet-wide Chrome trace on one wall-clock timeline (``ts``
        in absolute unix microseconds — Perfetto-loadable like the
        `merge_traces` output). Scrapes are incremental
        (``?since=<seq>`` cursors), so a steady-state poll ships only
        events recorded since the previous poll. Members that fail to
        answer keep whatever spans were already collected — a hung
        replica's history stays on the timeline and its late spans
        appear once it answers again."""
        with self._trace_lock:
            if self.local_worker_id is not None:
                st = self._trace_state.get(self.local_worker_id)
                self._ingest_trace(
                    self.local_worker_id,
                    self._tracer.export_chrome(
                        since=st["cursor"] if st else None))
        # Membership RPC + member scrapes run without the trace lock:
        # only the state reads/merges above and below hold it.
        for wid, base in self.members().items():
            try:
                self._scrape_trace(wid, base)
            except Exception:
                continue
        with self._trace_lock:
            meta: List[dict] = []
            events: List[dict] = []
            for wid, st in self._trace_state.items():
                for p in sorted(st["pids"]):
                    meta.append({"name": "process_name", "ph": "M",
                                 "pid": p, "tid": 0, "args": {"name": wid}})
                events.extend(st["events"])
        events.sort(key=lambda e: e.get("ts", 0.0))
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    # -------------------------------------------------------------- serve

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> str:
        """Mount the fleet view on its own HTTP port:

        - ``GET /metrics``   federated Prometheus exposition
        - ``GET /api/trace`` merged Chrome trace (Perfetto-loadable)
        - ``GET /members``   current scrape targets
        - ``GET /health``    aggregator liveness

        Returns the base URL; `close()` stops it."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        agg = self

        class Handler(BaseHTTPRequestHandler):
            # Keep-alive for the dashboards polling the fleet view.
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _send(self, body: bytes, ctype: str, code: int = 200):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    if self.path.startswith("/metrics"):
                        self._send(agg.federate_metrics().encode(),
                                   "text/plain; version=0.0.4")
                    elif self.path.startswith("/api/trace"):
                        self._send(
                            json.dumps(agg.federate_trace()).encode(),
                            "application/json")
                    elif self.path.startswith("/members"):
                        self._send(json.dumps(agg.members()).encode(),
                                   "application/json")
                    elif self.path.startswith("/health"):
                        self._send(b'{"status": "ok"}', "application/json")
                    else:
                        self._send(b'{"error": "not found"}',
                                   "application/json", 404)
                except Exception as e:
                    self._send(json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(),
                        "application/json", 502)

        class Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._http = Server((host, int(port)), Handler)
        h, p = self._http.server_address[:2]
        self.url = f"http://{h}:{p}"
        threading.Thread(target=self._http.serve_forever,
                         name="dl4j-fleet-aggregator", daemon=True).start()
        return self.url

    def close(self) -> None:
        with self._conn_lock:
            for conn in self._conns.values():
                conn.close()
            self._conns.clear()
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
            self._http = None
