"""Thread-safe metrics registry: labeled counters / gauges / histograms.

The production observability core the reference never had (its telemetry is
the listener -> StatsStorage -> Play UI pipeline, which answers "how is
training going", not "where did this step's milliseconds go on a live
serving box"). Design constraints, in order:

1. Near-zero cost when disabled: every mutator checks one bool before doing
   anything else, so `DL4J_TPU_OBS=0` leaves sub-microsecond no-ops in the
   hot loops (enforced by the overhead test in `tests/test_observability.py`).
2. Hot-loop friendly when enabled: callers resolve `.labels(...)` children
   ONCE at module import; `inc()`/`observe()` on a child is a bool check,
   one lock, one float op.
3. Standard exposition: the Prometheus text format 0.0.4 (label escaping,
   histogram `_bucket`/`_sum`/`_count` triplets, cumulative `le` buckets)
   so any scraper works, plus a JSON snapshot for embedding in
   BENCH_out.json.

Collectors (process RSS, JAX live device buffers) run at scrape time only —
they never touch the training path.
"""

from __future__ import annotations

import bisect
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.analysis.locktrace import named_rlock

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Latency-shaped default buckets (seconds): spans µs-level dispatches to
# multi-second cold XLA compiles.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

# Wide ladder for families whose observations routinely run multi-second
# to multi-minute (XLA compiles, serving requests riding a cold model
# reload, TTFT behind a long prefill). The default ladder tops out at 30s,
# which would clamp such a family's p99 into `+Inf` — the acceptance smoke
# asserts no scraped family has a majority of observations there.
WIDE_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0)


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(labels: Dict[str, str], extra: Optional[Tuple[str, str]] = None) -> str:
    items = list(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label(v)}"' for k, v in items) + "}"


class _Child:
    """One labeled series. All mutators fast-path the registry's enabled
    flag before taking the lock."""

    __slots__ = ("_reg", "labels", "_value", "_sum", "_count", "_bucket_counts",
                 "_buckets", "_fn")

    def __init__(self, reg: "MetricsRegistry", labels: Dict[str, str],
                 buckets: Optional[Sequence[float]] = None):
        self._reg = reg
        self.labels = labels
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None
        self._buckets = None if buckets is None else tuple(buckets)
        if self._buckets is not None:
            self._bucket_counts = [0] * (len(self._buckets) + 1)  # + +Inf
            self._sum = 0.0
            self._count = 0

    # counter / gauge
    def inc(self, v: float = 1.0) -> None:
        if not self._reg._enabled:
            return
        with self._reg._lock:
            self._value += v

    def set(self, v: float) -> None:
        if not self._reg._enabled:
            return
        with self._reg._lock:
            self._value = float(v)

    def set_function(self, fn: Optional[Callable[[], float]]) -> None:
        """Scrape-time gauge: `fn()` is called at exposition (queue depths,
        live-buffer counts — things that have a current value, not a path
        through the hot loop)."""
        self._fn = fn

    def get(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return float("nan")
        return self._value

    # histogram
    def observe(self, v: float) -> None:
        if not self._reg._enabled:
            return
        with self._reg._lock:
            self._bucket_counts[bisect.bisect_left(self._buckets, v)] += 1
            self._sum += v
            self._count += 1

    def histogram_state(self):
        """(buckets, cumulative_counts_incl_inf, sum, count) snapshot."""
        with self._reg._lock:
            raw = list(self._bucket_counts)
            s, c = self._sum, self._count
        cum, running = [], 0
        for n in raw:
            running += n
            cum.append(running)
        return self._buckets, cum, s, c

    def summarize(self, quantiles=(0.5, 0.9, 0.99)) -> Dict[str, float]:
        """Bucket-interpolated quantile summary (for BENCH_out.json)."""
        buckets, cum, s, c = self.histogram_state()
        out: Dict[str, float] = {"count": c, "sum": s}
        if not c:
            return out
        out["mean"] = s / c
        edges = list(buckets) + [float("inf")]
        for q in quantiles:
            target = q * c
            prev_cum, lo = 0, 0.0
            val = edges[-2] if len(edges) > 1 else 0.0
            for i, cm in enumerate(cum):
                if cm >= target:
                    hi = edges[i]
                    if hi == float("inf"):
                        hi = edges[i - 1] if i else 0.0
                    inbucket = cm - prev_cum
                    frac = ((target - prev_cum) / inbucket) if inbucket else 1.0
                    val = lo + (hi - lo) * frac
                    break
                prev_cum, lo = cm, edges[i]
            out[f"p{int(q * 100)}"] = val
        return out


class _Family:
    __slots__ = ("_reg", "name", "help", "kind", "label_names", "_children",
                 "_buckets", "_default")

    def __init__(self, reg, name, help_, kind, label_names, buckets=None):
        self._reg = reg
        self.name = name
        self.help = help_
        self.kind = kind
        self.label_names = tuple(label_names)
        self._buckets = buckets
        self._children: Dict[Tuple[str, ...], _Child] = {}
        self._default = None if self.label_names else self.labels()

    def labels(self, **kv: str) -> _Child:
        if tuple(sorted(kv)) != tuple(sorted(self.label_names)):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got "
                f"{tuple(kv)}")
        key = tuple(str(kv[n]) for n in self.label_names)
        with self._reg._lock:
            child = self._children.get(key)
            if child is None:
                child = _Child(self._reg, dict(zip(self.label_names, key)),
                               buckets=self._buckets)
                self._children[key] = child
        return child

    # unlabeled convenience: family acts as its own single child
    def _only(self) -> _Child:
        if self._default is None:
            raise ValueError(f"{self.name} is labeled; call .labels(...)")
        return self._default

    def inc(self, v: float = 1.0) -> None:
        self._only().inc(v)

    def set(self, v: float) -> None:
        self._only().set(v)

    def set_function(self, fn) -> None:
        self._only().set_function(fn)

    def get(self) -> float:
        return self._only().get()

    def observe(self, v: float) -> None:
        self._only().observe(v)

    def summarize(self, **kw):
        return self._only().summarize(**kw)

    def children(self) -> List[_Child]:
        with self._reg._lock:
            return list(self._children.values())


class MetricsRegistry:
    """See module docstring. One instance (`deeplearning4j_tpu.observability
    .metrics`) is the process-global default; tests build their own."""

    def __init__(self, enabled: bool = True):
        self._enabled = bool(enabled)
        self._lock = named_rlock("observability.metrics")
        self._families: Dict[str, _Family] = {}
        self._collectors: List[Callable[["MetricsRegistry"], None]] = []

    # ------------------------------------------------------------ lifecycle

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def reset(self) -> None:
        """Drop all recorded values (families and collectors survive)."""
        with self._lock:
            for fam in self._families.values():
                for child in fam._children.values():
                    child._value = 0.0
                    if child._buckets is not None:
                        child._bucket_counts = [0] * (len(child._buckets) + 1)
                        child._sum = 0.0
                        child._count = 0

    # ------------------------------------------------------------- creation

    def _family(self, name, help_, kind, label_names, buckets=None) -> _Family:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in label_names:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name} already registered as {fam.kind}"
                        f"{fam.label_names}, cannot re-register as {kind}"
                        f"{tuple(label_names)}")
                return fam
            fam = _Family(self, name, help_, kind, label_names, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                label_names: Sequence[str] = ()) -> _Family:
        return self._family(name, help, "counter", label_names)

    def gauge(self, name: str, help: str = "",
              label_names: Sequence[str] = ()) -> _Family:
        return self._family(name, help, "gauge", label_names)

    def histogram(self, name: str, help: str = "",
                  label_names: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Family:
        return self._family(name, help, "histogram", label_names,
                            buckets=tuple(sorted(buckets)))

    def register_collector(self, fn: Callable[["MetricsRegistry"], None]) -> None:
        """`fn(registry)` runs at every scrape; failures are swallowed (a
        broken collector must not take down /metrics)."""
        self._collectors.append(fn)

    def get_family(self, name: str) -> Optional[_Family]:
        return self._families.get(name)

    # ----------------------------------------------------------- exposition

    def _run_collectors(self) -> None:
        for fn in list(self._collectors):
            try:
                fn(self)
            except Exception:
                pass

    def to_prometheus(self, names: Optional[Sequence[str]] = None) -> str:
        """Prometheus text format 0.0.4. `names` narrows the exposition
        to the listed families — a needle scrape (the fleet router's load
        poll) then costs O(requested families), not O(all families), and
        skips the scrape-time collectors entirely."""
        if names is None:
            self._run_collectors()
        lines: List[str] = []
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        if names is not None:
            wanted = frozenset(names)
            fams = [f for f in fams if f.name in wanted]
        for fam in fams:
            children = fam.children()
            if not children:
                continue
            if fam.help:
                lines.append(f"# HELP {fam.name} {_escape_label(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for child in children:
                if fam.kind == "histogram":
                    buckets, cum, s, c = child.histogram_state()
                    for le, cm in zip(buckets, cum[:-1]):
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{_label_str(child.labels, ('le', _fmt(le)))}"
                            f" {cm}")
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_label_str(child.labels, ('le', '+Inf'))} {c}")
                    lines.append(
                        f"{fam.name}_sum{_label_str(child.labels)} {repr(float(s))}")
                    lines.append(
                        f"{fam.name}_count{_label_str(child.labels)} {c}")
                else:
                    lines.append(
                        f"{fam.name}{_label_str(child.labels)} "
                        f"{_fmt(child.get())}")
        return "\n".join(lines) + "\n"

    def to_json(self, names: Optional[Sequence[str]] = None) -> Dict[str, Any]:
        """Structured snapshot (BENCH_out.json embedding, /metrics?format=json).
        `names` narrows to the listed families and skips collectors (see
        `to_prometheus`)."""
        if names is None:
            self._run_collectors()
        out: Dict[str, Any] = {}
        with self._lock:
            fams = list(self._families.values())
        if names is not None:
            wanted = frozenset(names)
            fams = [f for f in fams if f.name in wanted]
        for fam in fams:
            series = []
            for child in fam.children():
                if fam.kind == "histogram":
                    buckets, cum, s, c = child.histogram_state()
                    series.append({
                        "labels": child.labels,
                        "count": c, "sum": s,
                        "buckets": {_fmt(le): cm
                                    for le, cm in zip(buckets, cum[:-1])},
                        "summary": child.summarize(),
                    })
                else:
                    series.append({"labels": child.labels,
                                   "value": child.get()})
            if series:
                out[fam.name] = {"type": fam.kind, "help": fam.help,
                                 "series": series}
        return out


# -------------------------------------------------------- built-in collectors


def _host_rss_bytes() -> Optional[float]:
    try:
        import os

        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except Exception:
        return None


def install_builtin_collectors(reg: MetricsRegistry) -> None:
    """Process RSS + JAX live device buffers, sampled at scrape time."""
    rss = reg.gauge("dl4j_process_resident_memory_bytes",
                    "Resident set size of this process")
    live = reg.gauge("dl4j_jax_live_buffers",
                     "Live jax.Array buffers held by this process")
    live_bytes = reg.gauge("dl4j_jax_live_buffer_bytes",
                           "Total bytes of live jax.Array buffers")

    def collect(_reg: MetricsRegistry) -> None:
        v = _host_rss_bytes()
        if v is not None:
            rss.set(v)
        try:
            import sys

            jax = sys.modules.get("jax")
            if jax is None:  # never import jax just to report zero
                return
            arrays = jax.live_arrays()
            live.set(len(arrays))
            live_bytes.set(sum(getattr(a, "nbytes", 0) for a in arrays))
        except Exception:
            pass

    reg.register_collector(collect)
