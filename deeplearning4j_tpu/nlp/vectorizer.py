"""Bag-of-words / TF-IDF text vectorizers.

Reference: `deeplearning4j-nlp/.../bagofwords/vectorizer/BagOfWordsVectorizer.java`
(raw per-document word counts) and `TfidfVectorizer.java:113-134` with
`util/MathUtils.java:257-283` semantics: tf = count/docLength,
idf = log10(totalDocs/docFreq), weight = tf*idf. `vectorize(text, label)`
returns a DataSet of (feature vector, one-hot label) exactly like the
reference's `TextVectorizer.vectorize`.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nlp.tokenization import TokenizerFactory


class BagOfWordsVectorizer:
    """Count vectorizer (reference: `BagOfWordsVectorizer.java`)."""

    def __init__(self, *, min_word_frequency: int = 1,
                 labels: Optional[Sequence[str]] = None,
                 tokenizer_factory: Optional[TokenizerFactory] = None):
        self.min_word_frequency = min_word_frequency
        self.labels = list(labels) if labels else []
        self.tf = tokenizer_factory or TokenizerFactory()
        self.vocab: List[str] = []
        self._index: dict = {}
        self._doc_freq: Optional[np.ndarray] = None
        self.n_docs = 0

    # ------------------------------------------------------------------ fit

    def _tokens(self, text: str) -> List[str]:
        return self.tf.create(text).get_tokens()

    def fit(self, docs: Iterable[str]) -> "BagOfWordsVectorizer":
        """Build the vocabulary (+ document frequencies) over the corpus."""
        counts: dict = {}
        doc_sets: List[set] = []
        self.n_docs = 0
        for text in docs:
            toks = self._tokens(text)
            self.n_docs += 1
            doc_sets.append(set(toks))
            for t in toks:
                counts[t] = counts.get(t, 0) + 1
        self.vocab = sorted(w for w, c in counts.items()
                            if c >= self.min_word_frequency)
        self._index = {w: i for i, w in enumerate(self.vocab)}
        df = np.zeros(len(self.vocab), np.float64)
        for s in doc_sets:
            for w in s:
                i = self._index.get(w)
                if i is not None:
                    df[i] += 1
        self._doc_freq = df
        return self

    # ------------------------------------------------------------ transform

    def _counts(self, text: str):
        v = np.zeros(len(self.vocab), np.float64)
        toks = self._tokens(text)
        for t in toks:
            i = self._index.get(t)
            if i is not None:
                v[i] += 1
        return v, len(toks)

    def transform(self, text: str) -> np.ndarray:
        """Feature vector for one document (raw counts)."""
        return self._counts(text)[0]

    def fit_transform(self, docs: Sequence[str]) -> np.ndarray:
        self.fit(docs)
        return np.stack([self.transform(d) for d in docs])

    def vectorize(self, text: str, label: str) -> DataSet:
        """(features, one-hot label) pair (reference
        `TextVectorizer.vectorize`); `label` must be in `self.labels`."""
        if label not in self.labels:
            raise ValueError(f"unknown label {label!r} (labels={self.labels})")
        y = np.zeros((1, len(self.labels)), np.float64)
        y[0, self.labels.index(label)] = 1.0
        return DataSet(self.transform(text)[None], y)


class TfidfVectorizer(BagOfWordsVectorizer):
    """TF-IDF vectorizer (reference: `TfidfVectorizer.java` +
    `MathUtils.tfidf`): tf = count/docLength, idf = log10(nDocs/docFreq)."""

    def transform(self, text: str) -> np.ndarray:
        counts, doc_len = self._counts(text)
        if doc_len == 0 or self.n_docs == 0:
            return counts
        tf = counts / doc_len
        with np.errstate(divide="ignore"):
            idf = np.where(self._doc_freq > 0,
                           np.log10(self.n_docs / np.maximum(self._doc_freq, 1)),
                           0.0)
        return tf * idf
