"""Word2Vec / SequenceVectors.

Equivalent of the reference's `models/word2vec/Word2Vec.java` +
`models/sequencevectors/SequenceVectors.java` (builder API, vocab
construction, subsampling, dynamic windows, linear LR decay) and
`models/embeddings/inmemory/InMemoryLookupTable.java` (syn0/syn1/syn1neg +
negative table). Training is batched jitted updates (`ops/skipgram.py`)
instead of the reference's Hogwild `VectorCalculationsThread`s
(`SequenceVectors.java:265-330`) — same objective, deterministic, TPU-resident.
"""

from __future__ import annotations

import math
from typing import Iterable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import TokenizerFactory, tokenize_corpus
from deeplearning4j_tpu.nlp.vocab import (
    VocabCache,
    VocabConstructor,
    vocab_from_arrays,
    build_huffman,
    make_unigram_table,
)
from deeplearning4j_tpu.ops import skipgram as kernels


class WordVectors:
    """Query API over trained vectors (reference: `wordvectors/WordVectors.java`)."""

    def __init__(self, vocab: VocabCache, syn0: np.ndarray):
        self.vocab = vocab
        self.syn0 = np.asarray(syn0)
        norms = np.linalg.norm(self.syn0, axis=1, keepdims=True)
        self._unit = self.syn0 / np.maximum(norms, 1e-12)

    def has_word(self, word: str) -> bool:
        return self.vocab.contains_word(word)

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return self.syn0[i] if i >= 0 else None

    def similarity(self, a: str, b: str) -> float:
        ia, ib = self.vocab.index_of(a), self.vocab.index_of(b)
        if ia < 0 or ib < 0:
            return float("nan")
        return float(self._unit[ia] @ self._unit[ib])

    def words_nearest(self, word_or_vec, top: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            i = self.vocab.index_of(word_or_vec)
            if i < 0:
                return []
            v = self._unit[i]
            exclude = {i}
        else:
            v = np.asarray(word_or_vec, np.float64)
            v = v / max(np.linalg.norm(v), 1e-12)
            exclude = set()
        sims = self._unit @ v
        order = np.argsort(-sims)
        out = []
        for j in order:
            if int(j) in exclude:
                continue
            out.append(self.vocab.word_at_index(int(j)).word)
            if len(out) >= top:
                break
        return out


class Word2Vec(WordVectors):
    """Skip-gram / CBOW embedding trainer (see module docstring).

    Builder-parameter parity with the reference's `Word2Vec.Builder`:
    min_word_frequency, layer_size, window_size, iterations/epochs, seed,
    learning_rate/min_learning_rate, negative (0 = hierarchical softmax),
    sample (subsampling threshold), cbow flag (reference uses separate
    SkipGram/CBOW learning algorithms).
    """

    def __init__(
        self,
        sentences: Optional[Iterable] = None,
        *,
        min_word_frequency: int = 1,
        layer_size: int = 100,
        window_size: int = 5,
        iterations: int = 1,
        epochs: int = 1,
        seed: int = 12345,
        learning_rate: float = 0.025,
        min_learning_rate: float = 1e-4,
        negative: int = 0,
        sample: float = 0.0,
        cbow: bool = False,
        batch_size: int = 2048,
        tokenizer_factory: Optional[TokenizerFactory] = None,
        mesh=None,
    ):
        self.min_word_frequency = min_word_frequency
        self.layer_size = layer_size
        self.window_size = window_size
        self.iterations = iterations
        self.epochs = epochs
        self.seed = seed
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.negative = negative
        self.sample = sample
        self.cbow = cbow
        self.batch_size = batch_size
        self.tokenizer_factory = tokenizer_factory or TokenizerFactory()
        # Optional jax.sharding.Mesh: flush batches shard over the mesh's
        # data axis and GSPMD all-reduces the scatter-added table updates —
        # the distributed-embedding-training analog of the reference's Spark
        # Word2Vec (`spark/models/embeddings/word2vec/Word2Vec.java`), with
        # per-batch gradient aggregation in place of parameter averaging.
        self.mesh = mesh
        self._sentences = sentences
        self.vocab: Optional[VocabCache] = None
        self.syn0 = None
        self.syn1 = None
        self.syn1neg = None

    # ------------------------------------------------------------------ fit

    def fit(self, sentences: Optional[Iterable] = None) -> "Word2Vec":
        if sentences is not None:
            self._sentences = sentences
        if self._sentences is None:
            raise ValueError(
                "no sentences to train on — pass them to the constructor or "
                "to fit(sentences=...)")
        sentences = (self._sentences
                     if isinstance(self._sentences, (list, tuple))
                     else list(self._sentences))
        rng = np.random.RandomState(self.seed)
        # RESUME path (reference `loadFullModel` + continued training): a
        # model restored by `nlp/serializer.load_full_model` arrives with
        # vocab + weights populated — keep them and train further on the
        # new corpus (restricted to the existing vocab) instead of
        # rebuilding/re-initializing.
        resume = self.vocab is not None and self.syn0 is not None
        # Native fast path: tokenize + count + encode in C++
        # (`native/fastvocab.cpp`), guaranteed Python-identical or refused
        # (PERF.md §5's host string-handling cost).
        fast = None
        if not resume:
            from deeplearning4j_tpu import native as native_mod

            fast = native_mod.build_vocab_corpus(
                sentences, self.min_word_frequency, self.tokenizer_factory)
        corpus = (None if fast is not None
                  else tokenize_corpus(sentences, self.tokenizer_factory))
        if not resume:
            if fast is not None:
                words, counts, fast_seqs = fast
                self.vocab = vocab_from_arrays(words, counts)
            else:
                self.vocab = VocabConstructor(
                    self.min_word_frequency).build(corpus)
            n_inner = build_huffman(self.vocab)
            V, D = self.vocab.num_words(), self.layer_size
            # Reference init: syn0 ~ U(-0.5/D, 0.5/D), syn1 zeros.
            syn0 = ((rng.rand(V, D) - 0.5) / D).astype(np.float32)
            self.syn0 = jnp.asarray(syn0)
            if self.negative > 0:
                self.syn1neg = jnp.zeros((V, D), jnp.float32)
            else:
                self.syn1 = jnp.zeros((max(n_inner, 1), D), jnp.float32)
        else:
            V, D = self.vocab.num_words(), self.layer_size
            self.syn0 = jnp.asarray(np.asarray(self.syn0, np.float32))
            if self.negative > 0:
                if self.syn1neg is None:
                    self.syn1neg = jnp.zeros_like(self.syn0)
                else:
                    self.syn1neg = jnp.asarray(
                        np.asarray(self.syn1neg, np.float32))
            else:
                if self.syn1 is None:
                    raise ValueError(
                        "resumed HS model has no syn1 table (was it trained "
                        "with negative sampling?)")
                self.syn1 = jnp.asarray(np.asarray(self.syn1, np.float32))
        if self.negative > 0:
            self._neg_table = make_unigram_table(self.vocab)
            # Constant labels (positive first): device-resident, uploaded
            # once instead of [K, B, 1+neg] per scan dispatch.
            labels_dev = jnp.zeros(
                (self.batch_size, 1 + self.negative),
                jnp.float32).at[:, 0].set(1.0)

        max_code = max((len(w.codes) for w in self.vocab._by_index), default=1) or 1
        if fast is not None:
            seqs = fast_seqs  # already index-encoded with OOV dropped
        else:
            seqs = [
                np.asarray([self.vocab.index_of(t) for t in seq
                            if self.vocab.contains_word(t)], np.int32)
                for seq in corpus
            ]
        seqs = [s for s in seqs if len(s) >= 1]
        total_words = sum(len(s) for s in seqs) * self.epochs * self.iterations
        words_done = 0

        codes_tbl = np.zeros((V, max_code), np.int32)
        points_tbl = np.zeros((V, max_code), np.int32)
        cmask_tbl = np.zeros((V, max_code), np.float32)
        for w in self.vocab._by_index:
            L = len(w.codes)
            codes_tbl[w.index, :L] = w.codes
            points_tbl[w.index, :L] = w.points
            cmask_tbl[w.index, :L] = 1.0
        # Device-resident copies: HS flushes gather paths on device and ship
        # only [B] indices per batch (kernels.hs_*_step_tbl).
        codes_dev = jnp.asarray(codes_tbl)
        points_dev = jnp.asarray(points_tbl)
        cmask_dev = jnp.asarray(cmask_tbl)

        freqs = np.array([w.frequency for w in self.vocab._by_index], np.float64)
        total_count = freqs.sum()
        if self.sample > 0:
            # Reference subsampling: keep probability per word occurrence.
            ratio = self.sample * total_count / np.maximum(freqs, 1)
            keep_prob = np.minimum(np.sqrt(ratio) + ratio, 1.0)
        else:
            keep_prob = np.ones(V)

        B = self.batch_size
        W = 2 * self.window_size
        if self.mesh is not None:
            from deeplearning4j_tpu.parallel import mesh as mesh_mod

            data_axis = self.mesh.axis_names[0]
            n_data = int(self.mesh.shape[data_axis])
            if B % n_data:
                raise ValueError(
                    f"batch_size {B} not divisible by the mesh data axis "
                    f"'{data_axis}' ({n_data})")

            def put(a):
                return None if a is None else jax.device_put(
                    np.asarray(a),
                    mesh_mod.data_sharding(self.mesh, np.ndim(a),
                                           axis=data_axis))
        else:
            def put(a):
                return None if a is None else jnp.asarray(a)

        def flush(buf_center, buf_word, buf_ctx, buf_ctx_mask, fill, lr):
            if fill == 0:
                return
            pm = np.zeros(B, np.float32)
            pm[:fill] = 1.0
            if self.negative > 0:
                # Shared negative-sampling batch: positive word first, then
                # K unigram-table draws (both CBOW and skip-gram NS modes);
                # the 1/0 labels are the device-resident constant.
                K = self.negative
                targets = np.zeros((B, 1 + K), np.int32)
                targets[:, 0] = buf_word
                targets[:, 1:] = self._neg_table[
                    rng.randint(0, len(self._neg_table), (B, K))]
                if self.mesh is None:
                    # Single-chip: queue and scan-dispatch like the HS path.
                    scan_queue.add((buf_ctx if self.cbow else buf_center,
                                    buf_ctx_mask, targets, pm,
                                    np.float32(lr)))
                else:
                    ns_step_single(buf_ctx if self.cbow else buf_center,
                                   buf_ctx_mask, targets, pm, lr, put)
            elif self.mesh is None:
                # HS single-chip: queue K flushes and dispatch them as ONE
                # jitted scan — per-dispatch host cost dominates otherwise
                # (PERF.md §5); the scan applies them in the same order, so
                # results are identical to per-flush dispatch.
                scan_queue.add((buf_ctx if self.cbow else buf_center,
                                buf_ctx_mask, buf_word, pm, np.float32(lr)))
            else:
                # HS on a mesh: per-flush dispatch with sharded buffers.
                hs_step_single(buf_ctx if self.cbow else buf_center,
                               buf_ctx_mask, buf_word, pm, lr, put)

        # Vectorized training-example assembly (the per-position Python loop
        # it replaces was the measured bottleneck — ~8 k words/s host-bound
        # vs the jitted kernels' capacity). Same algorithm as the reference
        # (`SkipGram.java`/`CBOW.java` via word2vec.c): per-position dynamic
        # window b ~ U[0, window), half-window = window - b, linear lr decay
        # by words consumed — computed for a whole sequence at once.
        offsets = np.concatenate([np.arange(-self.window_size, 0),
                                  np.arange(1, self.window_size + 1)])
        pend: List = []  # per-mode tuples of example arrays awaiting flush
        n_pend = 0

        def lr_now():
            return max(self.min_learning_rate,
                       self.learning_rate * (1 - words_done / max(total_words, 1)))

        K_SCAN = 8

        def hs_step_single(ctx_or_c, cm, w, pm, lr, put_fn):
            """The one single-step HS call site (mesh flushes and scan-queue
            leftovers both go through here)."""
            if self.cbow:
                self.syn0, self.syn1 = kernels.hs_cbow_step_tbl(
                    self.syn0, self.syn1, put_fn(ctx_or_c), put_fn(cm),
                    put_fn(w), codes_dev, points_dev, cmask_dev, put_fn(pm),
                    jnp.float32(lr))
            else:
                self.syn0, self.syn1 = kernels.hs_skipgram_step_tbl(
                    self.syn0, self.syn1, put_fn(ctx_or_c), put_fn(w),
                    codes_dev, points_dev, cmask_dev, put_fn(pm),
                    jnp.float32(lr))

        def ns_step_single(ctx_or_c, cm, targets, pm, lr, put_fn=jnp.asarray):
            """The one single-step NS call site (mesh flushes and scan-queue
            leftovers)."""
            if self.cbow:
                self.syn0, self.syn1neg = kernels.ns_cbow_step(
                    self.syn0, self.syn1neg, put_fn(ctx_or_c),
                    put_fn(cm), put_fn(targets),
                    labels_dev, put_fn(pm), jnp.float32(lr))
            else:
                self.syn0, self.syn1neg = kernels.ns_skipgram_step(
                    self.syn0, self.syn1neg, put_fn(ctx_or_c),
                    put_fn(targets), labels_dev,
                    put_fn(pm), jnp.float32(lr))

        def _dispatch_one(q):
            if self.negative > 0:
                ns_step_single(*q)
            else:
                ctx_or_c, cm, w, pm, lr = q
                hs_step_single(ctx_or_c, cm, w, pm, lr, jnp.asarray)

        def _dispatch_many(qs):
            stacked_ctx = np.stack([q[0] for q in qs])
            lrs = np.asarray([q[-1] for q in qs], np.float32)
            if self.negative > 0:
                tgts = np.stack([q[2] for q in qs])
                pms = np.stack([q[3] for q in qs])
                if self.cbow:
                    cms = np.stack([q[1] for q in qs])
                    self.syn0, self.syn1neg = kernels.ns_cbow_scan(
                        self.syn0, self.syn1neg, jnp.asarray(stacked_ctx),
                        jnp.asarray(cms), jnp.asarray(tgts),
                        labels_dev, jnp.asarray(pms),
                        jnp.asarray(lrs))
                else:
                    self.syn0, self.syn1neg = kernels.ns_skipgram_scan(
                        self.syn0, self.syn1neg, jnp.asarray(stacked_ctx),
                        jnp.asarray(tgts), labels_dev,
                        jnp.asarray(pms), jnp.asarray(lrs))
                return
            words_s = np.stack([q[2] for q in qs])
            pms = np.stack([q[3] for q in qs])
            if self.cbow:
                cms = np.stack([q[1] for q in qs])
                self.syn0, self.syn1 = kernels.hs_cbow_scan_tbl(
                    self.syn0, self.syn1, jnp.asarray(stacked_ctx),
                    jnp.asarray(cms), jnp.asarray(words_s), codes_dev,
                    points_dev, cmask_dev, jnp.asarray(pms),
                    jnp.asarray(lrs))
            else:
                self.syn0, self.syn1 = kernels.hs_skipgram_scan_tbl(
                    self.syn0, self.syn1, jnp.asarray(stacked_ctx),
                    jnp.asarray(words_s), codes_dev, points_dev, cmask_dev,
                    jnp.asarray(pms), jnp.asarray(lrs))

        scan_queue = kernels.ScanDispatchQueue(K_SCAN, _dispatch_many,
                                               _dispatch_one)

        def flush_slice(cols, k, count, lr):
            """Pad examples [k:k+count] into fixed-B buffers and flush."""
            if self.cbow:
                ctx, cmask, word = (c[k:k + count] for c in cols)
                buf_ctx = np.zeros((B, W), np.int32)
                buf_cm = np.zeros((B, W), np.float32)
                buf_word = np.zeros(B, np.int32)
                buf_ctx[:count, :ctx.shape[1]] = ctx
                buf_cm[:count, :cmask.shape[1]] = cmask
                buf_word[:count] = word
                flush(None, buf_word, buf_ctx, buf_cm, count, lr)
            else:
                center, word = (c[k:k + count] for c in cols)
                buf_center = np.zeros(B, np.int32)
                buf_word = np.zeros(B, np.int32)
                buf_center[:count] = center
                buf_word[:count] = word
                flush(buf_center, buf_word, None, None, count, lr)

        def drain(final=False):
            """Flush pending examples in exact B-sized kernel batches."""
            nonlocal pend, n_pend
            if not pend or (not final and n_pend < B):
                return  # defer concatenation until a full batch exists
            cols = [np.concatenate(c) for c in zip(*pend)]
            k = 0
            while n_pend - k >= B:
                flush_slice(cols, k, B, lr_now())
                k += B
            if final and n_pend > k:
                flush_slice(cols, k, n_pend - k, lr_now())
                k = n_pend
            pend = [tuple(c[k:] for c in cols)] if n_pend > k else []
            n_pend -= k

        for _ in range(self.epochs * self.iterations):
            for seq in seqs:
                if self.sample > 0:
                    keep = rng.rand(len(seq)) < keep_prob[seq]
                    seq = seq[keep]
                n = len(seq)
                if n == 0:
                    continue
                b = rng.randint(0, self.window_size, n)
                half = self.window_size - b  # dynamic half-window, 1..window
                ctx_pos = np.arange(n)[:, None] + offsets[None, :]  # [n, W]
                valid = ((np.abs(offsets)[None, :] <= half[:, None])
                         & (ctx_pos >= 0) & (ctx_pos < n))
                ctx_ids = seq[np.clip(ctx_pos, 0, n - 1)]
                if self.cbow:
                    rows = valid.any(axis=1)
                    pend.append((
                        np.ascontiguousarray(
                            np.where(valid, ctx_ids, 0)[rows], np.int32),
                        valid[rows].astype(np.float32),
                        seq[rows].astype(np.int32),
                    ))
                    n_pend += int(rows.sum())
                else:
                    # skip-gram: predict seq[pos] from each context seq[j];
                    # row-major flatten preserves the reference's (pos, j)
                    # visit order.
                    pend.append((
                        ctx_ids[valid].astype(np.int32),
                        np.broadcast_to(seq[:, None], valid.shape)[valid]
                        .astype(np.int32),
                    ))
                    n_pend += int(valid.sum())
                drain()
                words_done += n
        drain(final=True)
        scan_queue.drain()  # leftover queued flushes
        WordVectors.__init__(self, self.vocab, np.asarray(self.syn0))
        return self
