"""Word-vector serialization.

Equivalent of the reference's `models/embeddings/loader/
WordVectorSerializer.java:111-226` — Google word2vec binary format
(`loadGoogleModel`/`readBinaryModel`: ASCII "<vocab> <dim>" header, then
per word the whitespace-terminated token followed by <dim> little-endian
float32s), Google/DL4J text format (`readTextModel`/`writeWordVectors`),
and a full-model save that round-trips training state (syn0/syn1/syn1neg +
vocab with Huffman codes), analog of `writeFullModel`/`loadFullModel`.
"""

from __future__ import annotations

import json
import struct
import zipfile
from typing import Optional

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, WordVectors


def _vocab_from_words(words) -> VocabCache:
    cache = VocabCache()
    for i, w in enumerate(words):
        vw = VocabWord(word=w, frequency=1.0, index=i)
        cache._words[w] = vw
        cache._by_index.append(vw)
    cache.total_word_count = float(len(cache._by_index))
    return cache


# ------------------------------------------------------------ text format

def write_word_vectors(vectors: WordVectors, path: str,
                       header: bool = True) -> None:
    """Google text format: optional "<vocab> <dim>" header then one
    "word v1 v2 ..." line per word (the reference's `writeWordVectors`
    omits the header; `loadGoogleModel(..., binary=false)` accepts both)."""
    syn0 = np.asarray(vectors.syn0, np.float32)
    with open(path, "w", encoding="utf-8") as f:
        if header:
            f.write(f"{syn0.shape[0]} {syn0.shape[1]}\n")
        for i, row in enumerate(syn0):
            word = vectors.vocab.word_at_index(i).word
            f.write(word + " " + " ".join(f"{x:.8g}" for x in row) + "\n")


def load_txt_vectors(path: str) -> WordVectors:
    """Reads DL4J/Google text vectors, with or without the header line
    (reference: `loadTxtVectors`/`readTextModel`)."""
    words, rows = [], []
    with open(path, encoding="utf-8") as f:
        first = f.readline().rstrip("\n")
        parts = first.split(" ")
        if not (len(parts) == 2 and parts[0].isdigit() and parts[1].isdigit()):
            words.append(parts[0])
            rows.append(np.asarray([float(x) for x in parts[1:]], np.float32))
        for line in f:
            parts = line.rstrip("\n").split(" ")
            if len(parts) < 2:
                continue
            words.append(parts[0])
            rows.append(np.asarray([float(x) for x in parts[1:]], np.float32))
    return WordVectors(_vocab_from_words(words), np.stack(rows))


# ---------------------------------------------------------- binary format

def write_google_binary(vectors: WordVectors, path: str) -> None:
    """Google word2vec .bin layout (reference `readBinaryModel` reads this
    back: header "<vocab> <dim>\\n", then per word the UTF-8 token, a
    space, <dim> LE float32s, and a trailing newline)."""
    syn0 = np.asarray(vectors.syn0, np.float32)
    V, D = syn0.shape
    with open(path, "wb") as f:
        f.write(f"{V} {D}\n".encode("utf-8"))
        for i in range(V):
            word = vectors.vocab.word_at_index(i).word
            f.write(word.encode("utf-8") + b" ")
            f.write(struct.pack(f"<{D}f", *syn0[i]))
            f.write(b"\n")


def load_google_binary(path: str) -> WordVectors:
    """Reference: `WordVectorSerializer.readBinaryModel` — tolerate both
    "word<SP>floats<NL>" and "word<SP>floats" packing."""
    words, rows = [], []
    with open(path, "rb") as f:
        header = b""
        while not header.endswith(b"\n"):
            c = f.read(1)
            if not c:
                raise ValueError("truncated binary word-vector file")
            header += c
        V, D = (int(x) for x in header.decode("utf-8").split())
        for _ in range(V):
            token = b""
            while True:
                c = f.read(1)
                if not c:
                    raise ValueError("truncated binary word-vector file")
                if c == b" ":
                    break
                if c != b"\n":  # skip the previous entry's trailing newline
                    token += c
            vec = np.frombuffer(f.read(4 * D), np.float32).copy()
            words.append(token.decode("utf-8"))
            rows.append(vec)
    return WordVectors(_vocab_from_words(words), np.stack(rows))


def load_google_model(path: str, binary: bool = True) -> WordVectors:
    """Reference dispatch `loadGoogleModel(file, binary)`."""
    return load_google_binary(path) if binary else load_txt_vectors(path)


# ------------------------------------------------------------- full model

def write_full_model(model: Word2Vec, path: str) -> None:
    """Round-trips TRAINING state, not just vectors (reference
    `writeFullModel`: config + vocab incl. Huffman codes + syn0/syn1).
    Zip of config.json, vocab.json, and arrays.npz."""
    config = {
        "layer_size": model.layer_size,
        "window_size": model.window_size,
        "min_word_frequency": model.min_word_frequency,
        "negative": model.negative,
        "sample": model.sample,
        "cbow": model.cbow,
        "learning_rate": model.learning_rate,
        "min_learning_rate": model.min_learning_rate,
        "seed": model.seed,
    }
    vocab = [
        {"word": w.word, "frequency": w.frequency, "index": w.index,
         "codes": list(w.codes), "points": list(w.points)}
        for w in model.vocab._by_index
    ]
    arrays = {"syn0": np.asarray(model.syn0, np.float32)}
    if model.syn1 is not None:
        arrays["syn1"] = np.asarray(model.syn1, np.float32)
    if model.syn1neg is not None:
        arrays["syn1neg"] = np.asarray(model.syn1neg, np.float32)
    import io
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("config.json", json.dumps(config))
        z.writestr("vocab.json", json.dumps(vocab))
        z.writestr("arrays.npz", buf.getvalue())


def load_full_model(path: str) -> Word2Vec:
    import io
    with zipfile.ZipFile(path) as z:
        config = json.loads(z.read("config.json"))
        vocab_entries = json.loads(z.read("vocab.json"))
        arrays = np.load(io.BytesIO(z.read("arrays.npz")))
        model = Word2Vec(**{k: v for k, v in config.items()})
        cache = VocabCache()
        for e in vocab_entries:
            vw = VocabWord(word=e["word"], frequency=e["frequency"],
                           index=e["index"], codes=list(e["codes"]),
                           points=list(e["points"]))
            cache._words[vw.word] = vw
            cache._by_index.append(vw)
        cache.total_word_count = sum(w.frequency for w in cache._by_index)
        model.vocab = cache
        model.syn0 = arrays["syn0"]
        model.syn1 = arrays["syn1"] if "syn1" in arrays else None
        model.syn1neg = arrays["syn1neg"] if "syn1neg" in arrays else None
        WordVectors.__init__(model, cache, model.syn0)
    return model
