"""ParagraphVectors (doc2vec).

Equivalent of the reference's `models/paragraphvectors/ParagraphVectors.java`:
PV-DBOW (label vector predicts words — like skip-gram with the doc label as
the context) and PV-DM (label + context mean predicts the word — CBOW with an
extra label slot), plus `infer_vector` for unseen documents (freeze
word/softmax weights, fit a fresh doc vector).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.sentence_iterator import LabelledDocument
from deeplearning4j_tpu.nlp.tokenization import TokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor, build_huffman
from deeplearning4j_tpu.ops import skipgram as kernels


class ParagraphVectors:
    def __init__(
        self,
        documents: Iterable,
        *,
        dm: bool = False,  # False = DBOW (reference default DBOW for labels)
        min_word_frequency: int = 1,
        layer_size: int = 100,
        window_size: int = 5,
        epochs: int = 1,
        seed: int = 12345,
        learning_rate: float = 0.025,
        min_learning_rate: float = 1e-4,
        batch_size: int = 1024,
        tokenizer_factory: Optional[TokenizerFactory] = None,
    ):
        self.dm = dm
        self.min_word_frequency = min_word_frequency
        self.layer_size = layer_size
        self.window_size = window_size
        self.epochs = epochs
        self.seed = seed
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.batch_size = batch_size
        self.tf = tokenizer_factory or TokenizerFactory()
        self._docs: List[LabelledDocument] = [
            d if isinstance(d, LabelledDocument) else LabelledDocument(content=d)
            for d in documents
        ]
        for i, d in enumerate(self._docs):
            if not d.labels:
                d.labels = [f"DOC_{i}"]

    # ------------------------------------------------------------------ fit

    def fit(self) -> "ParagraphVectors":
        # Native tokenize+count+encode fast path (exactness-guarded,
        # `native/fastvocab.cpp`); Python fallback keeps identical results.
        from deeplearning4j_tpu import native as native_mod
        from deeplearning4j_tpu.nlp.vocab import vocab_from_arrays

        fast = native_mod.build_vocab_corpus(
            [d.content for d in self._docs], self.min_word_frequency, self.tf)
        if fast is not None:
            words, counts, fast_seqs = fast
            self.vocab = vocab_from_arrays(words, counts)
            corpus = None
        else:
            corpus = [self.tf.create(d.content).get_tokens()
                      for d in self._docs]
            self.vocab = VocabConstructor(self.min_word_frequency).build(corpus)
        n_inner = build_huffman(self.vocab)
        V, D = self.vocab.num_words(), self.layer_size

        self.labels = sorted({l for d in self._docs for l in d.labels})
        self._label_index = {l: i for i, l in enumerate(self.labels)}
        L = len(self.labels)

        rng = np.random.RandomState(self.seed)
        self.syn0 = jnp.asarray(((rng.rand(V, D) - 0.5) / D).astype(np.float32))
        self.doc_vectors = jnp.asarray(((rng.rand(L, D) - 0.5) / D).astype(np.float32))
        self.syn1 = jnp.zeros((max(n_inner, 1), D), jnp.float32)

        max_code = max((len(w.codes) for w in self.vocab._by_index), default=1) or 1
        self._codes_tbl = np.zeros((V, max_code), np.int32)
        self._points_tbl = np.zeros((V, max_code), np.int32)
        self._cmask_tbl = np.zeros((V, max_code), np.float32)
        for w in self.vocab._by_index:
            n = len(w.codes)
            self._codes_tbl[w.index, :n] = w.codes
            self._points_tbl[w.index, :n] = w.points
            self._cmask_tbl[w.index, :n] = 1.0

        if fast is not None:
            seqs = [(s, [self._label_index[l] for l in d.labels])
                    for s, d in zip(fast_seqs, self._docs)]
        else:
            seqs = [
                (np.asarray([self.vocab.index_of(t) for t in toks
                             if self.vocab.contains_word(t)], np.int32),
                 [self._label_index[l] for l in d.labels])
                for toks, d in zip(corpus, self._docs)
            ]
        # Train doc vectors jointly with words: treat doc ids as rows of a
        # combined embedding table [L + V, D]; doc rows use DBOW/DM pairing.
        combined = jnp.concatenate([self.doc_vectors, self.syn0], axis=0)
        B = self.batch_size
        # Device-resident Huffman tables + vectorized example assembly —
        # same host-bottleneck fixes as Word2Vec.fit (PERF.md §5).
        codes_dev = jnp.asarray(self._codes_tbl)
        points_dev = jnp.asarray(self._points_tbl)
        cmask_dev = jnp.asarray(self._cmask_tbl)
        total = sum(len(s) for s, _ in seqs) * self.epochs
        done = 0

        # K flushes per dispatch via the shared scan-queue protocol
        # (kernels.ScanDispatchQueue, PERF.md §5).
        def _one(q):
            nonlocal combined
            c, w, pm, lr = q
            combined, self.syn1 = kernels.hs_skipgram_step_tbl(
                combined, self.syn1, jnp.asarray(c), jnp.asarray(w),
                codes_dev, points_dev, cmask_dev, jnp.asarray(pm),
                jnp.float32(lr))

        def _many(qs):
            nonlocal combined
            combined, self.syn1 = kernels.hs_skipgram_scan_tbl(
                combined, self.syn1,
                jnp.asarray(np.stack([q[0] for q in qs])),
                jnp.asarray(np.stack([q[1] for q in qs])),
                codes_dev, points_dev, cmask_dev,
                jnp.asarray(np.stack([q[2] for q in qs])),
                jnp.asarray(np.asarray([q[3] for q in qs], np.float32)))

        queue = kernels.ScanDispatchQueue(8, _many, _one)

        def flush(centers, words, count, lr):
            buf_center = np.zeros(B, np.int32)
            buf_word = np.zeros(B, np.int32)
            pm = np.zeros(B, np.float32)
            buf_center[:count] = centers
            buf_word[:count] = words
            pm[:count] = 1.0
            queue.add((buf_center, buf_word, pm, np.float32(lr)))

        pend: List = []
        n_pend = 0

        def drain(final=False):
            nonlocal pend, n_pend
            if not pend or (not final and n_pend < B):
                return
            c = np.concatenate([p[0] for p in pend])
            w = np.concatenate([p[1] for p in pend])
            k = 0
            while n_pend - k >= B:
                flush(c[k:k + B], w[k:k + B], B, self._lr(done, total))
                k += B
            if final and n_pend > k:
                flush(c[k:], w[k:], n_pend - k, self._lr(done, total))
                k = n_pend
            pend = [(c[k:], w[k:])] if n_pend > k else []
            n_pend -= k

        W = self.window_size
        offsets = np.concatenate([np.arange(-W, 0), np.arange(1, W + 1)])
        for _ in range(self.epochs):
            for seq, label_ids in seqs:
                n = len(seq)
                if n == 0 or not label_ids:
                    done += n
                    continue
                lids = np.asarray(label_ids, np.int32)
                # DBOW: every doc label predicts every word (pos-major, as
                # the reference's per-position loop visits them).
                pend.append((np.tile(lids, n),
                             np.repeat(seq, len(lids)).astype(np.int32)))
                n_pend += n * len(lids)
                if self.dm:
                    # DM-ish: context words (offset rows into the combined
                    # table) predict the word too.
                    ctx_pos = np.arange(n)[:, None] + offsets[None, :]
                    valid = (ctx_pos >= 0) & (ctx_pos < n)
                    centers = (L + seq[np.clip(ctx_pos, 0, n - 1)])[valid]
                    words = np.broadcast_to(seq[:, None], valid.shape)[valid]
                    pend.append((centers.astype(np.int32),
                                 words.astype(np.int32)))
                    n_pend += int(valid.sum())
                drain()
                done += n
        drain(final=True)
        queue.drain()  # leftover queued flushes
        self.doc_vectors = combined[:L]
        self.syn0 = combined[L:]
        dv = np.asarray(self.doc_vectors)
        self._doc_unit = dv / np.maximum(np.linalg.norm(dv, axis=1, keepdims=True), 1e-12)
        return self

    def _lr(self, done, total):
        return max(self.min_learning_rate,
                   self.learning_rate * (1 - done / max(total, 1)))

    # ---------------------------------------------------------------- query

    def get_doc_vector(self, label: str) -> np.ndarray:
        return np.asarray(self.doc_vectors)[self._label_index[label]]

    def similarity(self, a: str, b: str) -> float:
        ia, ib = self._label_index[a], self._label_index[b]
        return float(self._doc_unit[ia] @ self._doc_unit[ib])

    def nearest_labels(self, vec_or_label, top: int = 5) -> List[str]:
        if isinstance(vec_or_label, str):
            v = self._doc_unit[self._label_index[vec_or_label]]
        else:
            v = np.asarray(vec_or_label, np.float64)
            v = v / max(np.linalg.norm(v), 1e-12)
        sims = self._doc_unit @ v
        return [self.labels[i] for i in np.argsort(-sims)[:top]]

    def infer_vector(self, text: str, steps: int = 20,
                     learning_rate: float = 0.025) -> np.ndarray:
        """Fit a fresh doc vector against frozen word/softmax weights
        (reference: `ParagraphVectors.inferVector`)."""
        toks = [self.vocab.index_of(t) for t in self.tf.create(text).get_tokens()
                if self.vocab.contains_word(t)]
        if not toks:
            return np.zeros(self.layer_size, np.float32)
        rng = np.random.RandomState(abs(hash(text)) % (2 ** 31))
        vec = jnp.asarray(((rng.rand(1, self.layer_size) - 0.5) / self.layer_size)
                          .astype(np.float32))
        words = np.asarray(toks, np.int32)
        B = len(words)
        for _ in range(steps):
            # One HS step where the only trainable row is the doc vector.
            # The kernel donates its table args, so hand it a COPY of syn1 to
            # keep the model's softmax weights intact (frozen inference).
            vec, _ = kernels.hs_skipgram_step(
                vec, jnp.copy(self.syn1), jnp.zeros(B, jnp.int32),
                jnp.asarray(self._codes_tbl[words]),
                jnp.asarray(self._points_tbl[words]),
                jnp.asarray(self._cmask_tbl[words]),
                jnp.ones(B, jnp.float32), jnp.float32(learning_rate))
        return np.asarray(vec)[0]
