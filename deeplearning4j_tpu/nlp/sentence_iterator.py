"""Sentence / document iterators.

Equivalent of the reference's `text/sentenceiterator/` (BasicLineIterator,
CollectionSentenceIterator, FileSentenceIterator) and the labelled document
iterators used by ParagraphVectors (`text/documentiterator/LabelledDocument`).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Iterable, Iterator, List, Optional


class SentenceIterator:
    def __iter__(self) -> Iterator[str]:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: Iterable[str]):
        self._sentences = list(sentences)

    def __iter__(self):
        return iter(self._sentences)


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a text file (reference: `BasicLineIterator.java`)."""

    def __init__(self, path: str):
        self.path = path

    def __iter__(self):
        with open(self.path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


class FileSentenceIterator(SentenceIterator):
    """All lines of all files under a directory (reference: `FileSentenceIterator.java`)."""

    def __init__(self, directory: str):
        self.directory = directory

    def __iter__(self):
        for root, _, files in os.walk(self.directory):
            for name in sorted(files):
                yield from BasicLineIterator(os.path.join(root, name))


@dataclass
class LabelledDocument:
    """Document with labels (reference: `text/documentiterator/LabelledDocument.java`)."""

    content: str = ""
    labels: List[str] = field(default_factory=list)


class LabelAwareIterator:
    def __iter__(self) -> Iterator[LabelledDocument]:
        raise NotImplementedError

    def reset(self):
        pass


class SimpleLabelAwareIterator(LabelAwareIterator):
    def __init__(self, documents: Iterable[LabelledDocument]):
        self._docs = list(documents)

    def __iter__(self):
        return iter(self._docs)
