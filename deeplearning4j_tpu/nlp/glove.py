"""GloVe — Global Vectors for word representation.

Equivalent of the reference's `models/glove/Glove.java:41` (standalone GloVe
on the SequenceVectors chassis) with `models/glove/AbstractCoOccurrences.java`
(windowed cooccurrence counting with 1/distance weighting, symmetric option)
and `models/embeddings/learning/impl/elements/GloVe.java` (AdaGrad regression
on log-cooccurrence, xMax=100, alpha=0.75). The reference spills cooccurrence
shards to disk and trains pair-at-a-time under Hogwild threads
(`models/glove/count/`); here counting is one host-side hash pass producing a
COO triple array, and training is shuffled fixed-size batches through the
jitted `ops/glove.glove_step` kernel — same objective, deterministic,
device-resident.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nlp.tokenization import TokenizerFactory, tokenize_corpus
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabConstructor
from deeplearning4j_tpu.nlp.word2vec import WordVectors
from deeplearning4j_tpu.ops.glove import glove_step


class CoOccurrences:
    """Windowed cooccurrence counter (reference:
    `AbstractCoOccurrences.java:321-372` — weight 1/distance within the
    window; `symmetric` also credits the mirrored pair)."""

    def __init__(self, window_size: int = 5, symmetric: bool = True):
        self.window_size = window_size
        self.symmetric = symmetric

    def count(self, sequences: Iterable[np.ndarray]
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Returns DIRECTED COO arrays (rows, cols, weights): each in-window
        pair is credited (x, j); `symmetric` also credits the mirrored
        (j, x) entry, exactly the reference's storage
        (`AbstractCoOccurrences.java:364-372`)."""
        counts: Dict[Tuple[int, int], float] = {}
        for seq in sequences:
            n = len(seq)
            for x in range(n):
                wx = int(seq[x])
                stop = min(x + self.window_size + 1, n)
                for j in range(x + 1, stop):
                    wj = int(seq[j])
                    w = 1.0 / (j - x)
                    counts[(wx, wj)] = counts.get((wx, wj), 0.0) + w
                    if self.symmetric:
                        counts[(wj, wx)] = counts.get((wj, wx), 0.0) + w
        if not counts:
            return (np.zeros(0, np.int32), np.zeros(0, np.int32),
                    np.zeros(0, np.float32))
        rows = np.fromiter((k[0] for k in counts), np.int32, len(counts))
        cols = np.fromiter((k[1] for k in counts), np.int32, len(counts))
        vals = np.fromiter(counts.values(), np.float32, len(counts))
        return rows, cols, vals


class Glove(WordVectors):
    """GloVe trainer (builder-parameter parity with `Glove.Builder`:
    min_word_frequency, layer_size/vector length, window_size, epochs
    (`iterations()` aliases epochs in the reference builder), xMax, alpha,
    learning_rate, shuffle, symmetric, seed, batch_size)."""

    def __init__(
        self,
        sentences: Optional[Iterable] = None,
        *,
        min_word_frequency: int = 1,
        layer_size: int = 100,
        window_size: int = 5,
        epochs: int = 5,
        seed: int = 12345,
        learning_rate: float = 0.05,
        x_max: float = 100.0,
        alpha: float = 0.75,
        shuffle: bool = True,
        symmetric: bool = True,
        batch_size: int = 4096,
        tokenizer_factory: Optional[TokenizerFactory] = None,
    ):
        self.min_word_frequency = min_word_frequency
        self.layer_size = layer_size
        self.window_size = window_size
        self.epochs = epochs
        self.seed = seed
        self.learning_rate = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.shuffle = shuffle
        self.symmetric = symmetric
        self.batch_size = batch_size
        self.tokenizer_factory = tokenizer_factory or TokenizerFactory()
        self._sentences = sentences
        self.vocab: Optional[VocabCache] = None
        self.syn0 = None
        self.bias = None
        self.error_per_epoch: List[float] = []

    def fit(self) -> "Glove":
        sentences = (self._sentences
                     if isinstance(self._sentences, (list, tuple))
                     else list(self._sentences))
        # Native tokenize+count+encode fast path (exactness-guarded; see
        # native/fastvocab.cpp), Python fallback below.
        from deeplearning4j_tpu import native as native_mod
        from deeplearning4j_tpu.nlp.vocab import vocab_from_arrays

        fast = native_mod.build_vocab_corpus(
            sentences, self.min_word_frequency, self.tokenizer_factory)
        if fast is not None:
            words, counts, seqs = fast
            self.vocab = vocab_from_arrays(words, counts)
        else:
            corpus = tokenize_corpus(sentences, self.tokenizer_factory)
            self.vocab = VocabConstructor(
                self.min_word_frequency).build(corpus)
            seqs = [
                np.asarray([self.vocab.index_of(t) for t in seq
                            if self.vocab.contains_word(t)], np.int32)
                for seq in corpus
            ]
        V, D = self.vocab.num_words(), self.layer_size
        rng = np.random.RandomState(self.seed)
        rows, cols, vals = CoOccurrences(
            self.window_size, self.symmetric).count(seqs)
        if len(rows) == 0:
            raise ValueError("empty cooccurrence matrix — corpus too small")

        # Reference init (GloveWeightLookupTable.resetWeights): syn0 uniform
        # scaled by layer size, bias zero; AdaGrad history zero.
        syn0 = jnp.asarray(((rng.rand(V, D) - 0.5) / D).astype(np.float32))
        bias = jnp.zeros((V,), jnp.float32)
        hist_w = jnp.zeros((V, D), jnp.float32)
        hist_b = jnp.zeros((V,), jnp.float32)

        B = min(self.batch_size, max(len(rows), 1))
        n_pairs = len(rows)
        lr = jnp.float32(self.learning_rate)
        x_max = jnp.float32(self.x_max)
        alpha = jnp.float32(self.alpha)

        for _ in range(self.epochs):
            order = rng.permutation(n_pairs) if self.shuffle else np.arange(n_pairs)
            # Losses stay device-side until epoch end so batch dispatches
            # pipeline instead of syncing per batch.
            batch_losses = []
            for start in range(0, n_pairs, B):
                take = order[start:start + B]
                fill = len(take)
                br = np.zeros(B, np.int32)
                bc = np.zeros(B, np.int32)
                bv = np.ones(B, np.float32)
                pm = np.zeros(B, np.float32)
                br[:fill] = rows[take]
                bc[:fill] = cols[take]
                bv[:fill] = vals[take]
                pm[:fill] = 1.0
                syn0, bias, hist_w, hist_b, loss = glove_step(
                    syn0, bias, hist_w, hist_b,
                    jnp.asarray(br), jnp.asarray(bc), jnp.asarray(bv),
                    jnp.asarray(pm), lr, x_max, alpha)
                batch_losses.append(loss)
            epoch_err = float(jnp.sum(jnp.stack(batch_losses)))
            self.error_per_epoch.append(epoch_err / max(n_pairs, 1))

        self.bias = np.asarray(bias)
        WordVectors.__init__(self, self.vocab, np.asarray(syn0))
        return self
