"""Distributed corpus pipeline: multi-host tokenization, vocab build, and
cooccurrence counting.

Reference analog: `dl4j-spark-nlp`'s `TextPipeline.java` (map-reduce word
counting over corpus partitions) and
`spark/models/embeddings/glove/cooccurrences/` (partitioned cooccurrence
counting). The Spark machinery maps to the TPU-native stack as: each
PROCESS counts its own corpus shard with the native tokenizer/counter
(`native/fastvocab.cpp`), and the partial results merge through the
jax.distributed collective fabric (`multihost_utils.process_allgather`
over the same Gloo/ICI transport the trainers use) — no extra cluster
runtime, same determinism guarantees as the single-host path:

- `distributed_vocab(shard)` returns the IDENTICAL VocabCache on every
  process — counts are summed globally before the min-frequency filter
  and the (-freq, word) finalize ordering — plus the local shard encoded
  against that global vocab (per-token work stays native/vectorized: the
  local encoding is remapped local-id -> global-id with one gather).
- `distributed_cooccurrences(seqs_shard)` merges per-shard windowed
  COO counts (1/distance weighting, `nlp/glove.py` semantics) into the
  same (rows, cols, weights) every process would get counting the whole
  corpus alone.

Single-process degenerates to the local path (process_allgather of one
shard), so the same code runs everywhere — tested 2-process in
`tests/test_distributed.py`.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import TokenizerFactory, tokenize_corpus
from deeplearning4j_tpu.nlp.vocab import (
    VocabCache,
    build_huffman,
    vocab_from_arrays,
)


def _allgather_bytes(buf: bytes) -> List[bytes]:
    """Gather one variable-length byte string from every process."""
    from jax.experimental import multihost_utils

    lens = np.atleast_1d(np.asarray(
        multihost_utils.process_allgather(np.asarray(len(buf), np.int64))))
    L = max(1, int(lens.max()))
    padded = np.zeros((L,), np.uint8)
    if buf:
        padded[: len(buf)] = np.frombuffer(buf, np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    gathered = gathered.reshape(len(lens), L)
    return [gathered[i, : int(lens[i])].tobytes() for i in range(len(lens))]


def _local_counts(sentences, tokenizer_factory):
    """(words, counts, local_seqs) for THIS shard, unfiltered (min_freq=1 —
    the global filter applies after the merge). Native when eligible."""
    from deeplearning4j_tpu import native as native_mod

    sentences = (sentences if isinstance(sentences, (list, tuple))
                 else list(sentences))
    fast = native_mod.build_vocab_corpus(sentences, 1.0, tokenizer_factory)
    if fast is not None:
        words, counts, seqs = fast
        return list(words), np.asarray(counts, np.float64), seqs
    corpus = tokenize_corpus(sentences,
                             tokenizer_factory or TokenizerFactory())
    order: List[str] = []
    idx = {}
    counts: List[float] = []
    seqs = []
    for seq in corpus:
        enc = np.empty(len(seq), np.int32)
        for i, tok in enumerate(seq):
            j = idx.get(tok)
            if j is None:
                j = len(order)
                idx[tok] = j
                order.append(tok)
                counts.append(0.0)
            counts[j] += 1.0
            enc[i] = j
        seqs.append(enc)
    # Match the native path's output convention (first-seen local ids).
    return order, np.asarray(counts, np.float64), seqs


def distributed_vocab(
    sentences_shard,
    min_word_frequency: float = 1.0,
    tokenizer_factory: Optional[TokenizerFactory] = None,
    huffman: bool = True,
) -> Tuple[VocabCache, List[np.ndarray]]:
    """Build ONE global vocab from every process's corpus shard and encode
    this process's shard against it.

    Returns (vocab, encoded_seqs): `vocab` is identical on all processes
    (globally summed counts, global min-frequency filter, finalize_vocab
    ordering, Huffman codes when `huffman`); `encoded_seqs` are THIS
    shard's sentences as int32 global-vocab indices with OOV dropped.
    """
    words, counts, local_seqs = _local_counts(sentences_shard,
                                              tokenizer_factory)
    payload = "\n".join(words).encode("utf-8")
    gathered_words = _allgather_bytes(payload)
    gathered_counts = _allgather_bytes(counts.tobytes())

    merged = {}
    for wbuf, cbuf in zip(gathered_words, gathered_counts):
        ws = wbuf.decode("utf-8").split("\n") if wbuf else []
        cs = np.frombuffer(cbuf, np.float64)
        for w, c in zip(ws, cs):
            merged[w] = merged.get(w, 0.0) + float(c)
    kept = [(w, c) for w, c in merged.items() if c >= min_word_frequency]
    kept.sort(key=lambda t: (-t[1], t[0]))
    vocab = vocab_from_arrays([w for w, _ in kept], [c for _, c in kept])
    if huffman:
        build_huffman(vocab)

    # Remap the shard's local-id encoding to global ids with ONE gather:
    # per-VOCAB-WORD Python, per-TOKEN numpy.
    remap = np.asarray([vocab.index_of(w) for w in words], np.int32)
    out = []
    for s in local_seqs:
        g = remap[s] if len(s) else np.zeros((0,), np.int32)
        out.append(g[g >= 0].astype(np.int32))
    return vocab, out


def distributed_cooccurrences(
    seqs_shard: Iterable[np.ndarray],
    window_size: int = 5,
    symmetric: bool = True,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Merge per-shard windowed cooccurrence counts into the global COO
    (rows, cols, weights) — `nlp/glove.py::CoOccurrences` semantics, every
    process receiving the same merged result."""
    from deeplearning4j_tpu.nlp.glove import CoOccurrences

    rows, cols, vals = CoOccurrences(window_size, symmetric).count(seqs_shard)
    payload = np.concatenate([
        rows.astype(np.int64), cols.astype(np.int64),
    ]).tobytes() + vals.astype(np.float64).tobytes()
    header = np.asarray([len(rows)], np.int64).tobytes()
    gathered = _allgather_bytes(header + payload)

    all_r, all_c, all_v = [], [], []
    for buf in gathered:
        n = int(np.frombuffer(buf[:8], np.int64)[0])
        ints = np.frombuffer(buf[8: 8 + 16 * n], np.int64)
        all_r.append(ints[:n])
        all_c.append(ints[n: 2 * n])
        all_v.append(np.frombuffer(buf[8 + 16 * n:], np.float64))
    r = np.concatenate(all_r) if all_r else np.zeros(0, np.int64)
    if r.size == 0:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32),
                np.zeros(0, np.float32))
    c = np.concatenate(all_c)
    v = np.concatenate(all_v)
    # Vectorized merge (GloVe-scale shards carry millions of pairs): one
    # composite sort key, np.unique for the deterministic merged order,
    # np.add.at to sum duplicate pairs.
    V = int(max(r.max(), c.max())) + 1
    key = r * V + c
    uniq, inverse = np.unique(key, return_inverse=True)
    out_v = np.zeros(len(uniq), np.float64)
    np.add.at(out_v, inverse, v)
    return ((uniq // V).astype(np.int32), (uniq % V).astype(np.int32),
            out_v.astype(np.float32))
