"""Vocabulary construction + Huffman coding.

Equivalent of the reference's `models/word2vec/wordstore/` — `VocabWord`,
`VocabCache`, `VocabConstructor.buildJointVocabulary`
(`VocabConstructor.java:161`) and the `Huffman` tree builder whose codes/points
drive hierarchical softmax.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.util.huffman import huffman_codes


@dataclass
class VocabWord:
    word: str
    frequency: float = 0.0
    index: int = -1
    codes: List[int] = field(default_factory=list)  # Huffman code bits
    points: List[int] = field(default_factory=list)  # inner-node indices


class VocabCache:
    """In-memory vocab (reference: `InMemoryLookupCache`/`AbstractCache`)."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._by_index: List[VocabWord] = []
        self.total_word_count = 0.0

    def add_token(self, word: str, count: float = 1.0):
        vw = self._words.get(word)
        if vw is None:
            vw = VocabWord(word=word, frequency=0.0)
            self._words[word] = vw
        vw.frequency += count
        self.total_word_count += count

    def finalize_vocab(self, min_word_frequency: int = 1):
        kept = [w for w in self._words.values() if w.frequency >= min_word_frequency]
        kept.sort(key=lambda w: (-w.frequency, w.word))
        self._words = {w.word: w for w in kept}
        self._by_index = kept
        for i, w in enumerate(kept):
            w.index = i
        self.total_word_count = sum(w.frequency for w in kept)

    def contains_word(self, word: str) -> bool:
        return word in self._words

    def word_for(self, word: str) -> Optional[VocabWord]:
        return self._words.get(word)

    def word_at_index(self, index: int) -> VocabWord:
        return self._by_index[index]

    def index_of(self, word: str) -> int:
        vw = self._words.get(word)
        return vw.index if vw else -1

    def num_words(self) -> int:
        return len(self._by_index)

    def words(self) -> List[str]:
        return [w.word for w in self._by_index]


def build_huffman(cache: VocabCache, max_code_length: int = 40) -> int:
    """Assign Huffman codes/points (reference: `Huffman.java`, MAX_CODE_LENGTH
    40). Returns the number of inner nodes (= syn1 rows needed). The tree
    itself comes from the shared `util/huffman.py` core (also used
    degree-keyed by DeepWalk's GraphHuffman equivalent)."""
    n = cache.num_words()
    if n == 0:
        return 0
    freqs = [w.frequency for w in cache._by_index]
    codes, points, n_inner = huffman_codes(freqs, max_code_length)
    for w, c, p in zip(cache._by_index, codes, points):
        w.codes = c
        w.points = p
    return n_inner


def vocab_from_arrays(words: List[str], counts) -> VocabCache:
    """Assemble a finalized VocabCache from pre-sorted (word, count) arrays
    — the native `fastvocab` builder's output (already in finalize_vocab
    order). Huffman codes are NOT assigned; call `build_huffman`."""
    cache = VocabCache()
    total = 0.0
    for i, (w, c) in enumerate(zip(words, counts)):
        vw = VocabWord(word=w, frequency=float(c), index=i)
        cache._words[w] = vw
        cache._by_index.append(vw)
        total += float(c)
    cache.total_word_count = total
    return cache


class VocabConstructor:
    """Build a vocab from token-sequence sources (reference:
    `VocabConstructor.buildJointVocabulary`)."""

    def __init__(self, min_word_frequency: int = 1):
        self.min_word_frequency = min_word_frequency

    def build(self, sequences: Iterable[List[str]]) -> VocabCache:
        cache = VocabCache()
        for seq in sequences:
            for tok in seq:
                cache.add_token(tok)
        cache.finalize_vocab(self.min_word_frequency)
        build_huffman(cache)
        return cache


def make_unigram_table(cache: VocabCache, table_size: int = 100_000,
                       power: float = 0.75) -> np.ndarray:
    """Negative-sampling table (reference: `InMemoryLookupTable.resetWeights`
    negative table): word index drawn proportional to freq^0.75."""
    n = cache.num_words()
    freqs = np.array([w.frequency for w in cache._by_index], np.float64) ** power
    probs = freqs / freqs.sum()
    return np.repeat(np.arange(n), np.maximum((probs * table_size).astype(np.int64), 1))
