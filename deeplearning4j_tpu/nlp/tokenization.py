"""Tokenization SPI.

Equivalent of the reference's `text/tokenization/` (TokenizerFactory/Tokenizer
SPI + CommonPreprocessor/EndingPreProcessor). The reference ships UIMA/Kuromoji
language packs as separate modules; here the SPI accepts any callable so
language-specific tokenizers plug in the same way.
"""

from __future__ import annotations

import re
from typing import Callable, Iterable, List, Optional


class TokenPreProcess:
    """Token normalizer SPI (reference: `CommonPreprocessor.java`)."""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    _strip = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._strip.sub("", token).lower()


class EndingPreProcessor(TokenPreProcess):
    """Crude stemmer (reference: `EndingPreProcessor.java`)."""

    def pre_process(self, token: str) -> str:
        for suffix in ("sses", "ies", "ing", "ed", "s"):
            if token.endswith(suffix) and len(token) > len(suffix) + 2:
                return token[: -len(suffix)]
        return token


class Tokenizer:
    def __init__(self, tokens: List[str]):
        self._tokens = tokens

    def get_tokens(self) -> List[str]:
        return list(self._tokens)

    def count_tokens(self) -> int:
        return len(self._tokens)


class TokenizerFactory:
    """Default whitespace tokenizer factory (reference:
    `DefaultTokenizerFactory.java`)."""

    def __init__(self, preprocessor: Optional[TokenPreProcess] = None):
        self.preprocessor = preprocessor

    def create(self, text: str) -> Tokenizer:
        toks = text.split()
        if self.preprocessor is not None:
            toks = [self.preprocessor.pre_process(t) for t in toks]
        return Tokenizer([t for t in toks if t])


DefaultTokenizerFactory = TokenizerFactory


def tokenize_corpus(sentences, tokenizer_factory: "TokenizerFactory") -> List[List[str]]:
    """Tokenize a corpus of raw strings and/or pre-split token lists (the
    shared sentence-ingest step of every embedding trainer — reference:
    `SentenceTransformer` feeding `SequenceVectors`)."""
    corpus = []
    for s in sentences:
        if isinstance(s, str):
            corpus.append(tokenizer_factory.create(s).get_tokens())
        else:
            corpus.append(list(s))
    return corpus
