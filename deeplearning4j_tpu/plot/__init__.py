from deeplearning4j_tpu.plot.tsne import Tsne, BarnesHutTsne

__all__ = ["Tsne", "BarnesHutTsne"]
