"""t-SNE dimensionality reduction.

Equivalent of the reference's `plot/Tsne.java:36` (exact/dense t-SNE) and
`plot/BarnesHutTsne.java:64` (the θ-approximated quad-tree variant that
implements `Model`). Defaults mirror the reference: maxIter=1000,
perplexity=30, initial momentum 0.5 switching to 0.8 at iteration 100,
early exaggeration 4 dropped at stopLyingIteration=250 (`Tsne.java:163-166`
P.divi(4)). learning_rate defaults to "auto" (N/exaggeration/4, floor 50)
instead of the reference's fixed 500, which diverges for small N; pass
learning_rate=500.0 for exact reference behavior.

TPU-native design note: Barnes-Hut exists to cut the O(N²) repulsion to
O(N log N) via a HOST-side quad/SP-tree — pointer-chasing that is exactly
what the MXU cannot run. Here the full [N, N] affinity and repulsion
matrices are computed densely inside one jitted `lax.fori_loop` (beta
calibration = vectorized bisection, gradient loop = momentum + per-element
gains on device). For the N ≲ 20k regime t-SNE plots live in, the dense
matmul formulation on the MXU is faster than a serial tree walk, so the
Barnes-Hut machinery is deliberately subsumed rather than ported
(`BarnesHutTsne` is an alias that accepts and ignores `theta`, the way the
reference itself falls back to dense `Tsne` when theta == 0,
`BarnesHutTsne.java:444-449`).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnums=(2,))
def _x2p(D2, log_perp, bisect_iters=50):
    """Per-row conditional affinities via bisection on precision beta
    (reference: `Tsne.x2p` / `computeGaussianPerplexity` — same tolerance
    search expressed as a fixed-iteration vectorized bisection)."""
    N = D2.shape[0]
    eye = jnp.eye(N, dtype=bool)

    def entropy_probs(beta):
        logits = -D2 * beta[:, None]
        logits = jnp.where(eye, -jnp.inf, logits)
        P = jax.nn.softmax(logits, axis=1)
        # Shannon entropy H = -sum p log p, computed stably from logits.
        logP = jax.nn.log_softmax(logits, axis=1)
        H = -jnp.sum(jnp.where(P > 0, P * logP, 0.0), axis=1)
        return H, P

    def body(_, carry):
        lo, hi, beta = carry
        H, _ = entropy_probs(beta)
        too_high = H > log_perp          # entropy too high -> raise beta
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(jnp.isinf(hi), beta * 2.0, (lo + hi) / 2.0)
        return lo, hi, beta

    lo = jnp.zeros((N,))
    hi = jnp.full((N,), jnp.inf)
    beta = jnp.ones((N,))
    lo, hi, beta = jax.lax.fori_loop(0, bisect_iters, body, (lo, hi, beta))
    _, P = entropy_probs(beta)
    return P


@partial(jax.jit, static_argnums=(2, 3, 4))
def _tsne_loop(P, Y0, max_iter, switch_momentum_iteration, stop_lying_iteration,
               learning_rate, initial_momentum, final_momentum, min_gain,
               exaggeration):
    """The gradient loop of `Tsne.calculate` (`Tsne.java:109-170`): student-t
    Q, (P-Q) gradient, per-element gains, momentum switch, early
    exaggeration — one `lax.scan` on device."""
    N, no_dims = Y0.shape
    eye = jnp.eye(N, dtype=bool)

    def grad(P_eff, Y):
        D2 = (jnp.sum(Y * Y, axis=1)[:, None] - 2.0 * Y @ Y.T
              + jnp.sum(Y * Y, axis=1)[None, :])
        num = 1.0 / (1.0 + D2)
        num = jnp.where(eye, 0.0, num)
        Q = jnp.maximum(num / jnp.sum(num), 1e-12)
        PQ = (P_eff - Q) * num                      # [N, N]
        dY = 4.0 * ((jnp.diag(jnp.sum(PQ, axis=1)) - PQ) @ Y)
        kl = jnp.sum(jnp.where(P_eff > 0, P_eff * jnp.log(P_eff / Q), 0.0))
        return dY, kl

    def step(carry, i):
        Y, iY, gains = carry
        # Exaggeration ends at min(stop_lying_iteration, max_iter/2 + 1):
        # the reference stops lying when `i > maxIter / 2 ||
        # i >= stopLyingIteration` (`Tsne.java:163`), so a stop_lying value
        # beyond half the run is cut short there too.
        lying = i < jnp.minimum(stop_lying_iteration, max_iter // 2 + 1)
        P_eff = jnp.where(lying, P * exaggeration, P)
        dY, kl = grad(P_eff, Y)
        momentum = jnp.where(i < switch_momentum_iteration,
                             initial_momentum, final_momentum)
        gains = jnp.where(jnp.sign(dY) != jnp.sign(iY),
                          gains + 0.2, gains * 0.8)
        gains = jnp.maximum(gains, min_gain)
        iY = momentum * iY - learning_rate * gains * dY
        Y = Y + iY
        Y = Y - jnp.mean(Y, axis=0, keepdims=True)  # re-center each step
        return (Y, iY, gains), kl

    init = (Y0, jnp.zeros_like(Y0), jnp.ones_like(Y0))
    (Y, _, _), kls = jax.lax.scan(step, init, jnp.arange(max_iter))
    return Y, kls


class Tsne:
    """Dense t-SNE with reference-default hyperparameters (see module
    docstring). `fit_transform(X)` returns the [N, n_components] embedding;
    `Y` and `kl_divergences` are kept on the instance afterwards."""

    def __init__(self, *, n_components: int = 2, max_iter: int = 1000,
                 perplexity: float = 30.0, learning_rate="auto",
                 initial_momentum: float = 0.5, final_momentum: float = 0.8,
                 switch_momentum_iteration: int = 100,
                 stop_lying_iteration: int = 250, exaggeration: float = 4.0,
                 min_gain: float = 0.01, normalize: bool = True,
                 seed: int = 12345, max_points: int = 20_000):
        self.n_components = n_components
        self.max_iter = max_iter
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.initial_momentum = initial_momentum
        self.final_momentum = final_momentum
        self.switch_momentum_iteration = switch_momentum_iteration
        self.stop_lying_iteration = stop_lying_iteration
        self.exaggeration = exaggeration
        self.min_gain = min_gain
        self.normalize = normalize
        self.seed = seed
        self.max_points = max_points
        self.Y: Optional[np.ndarray] = None
        self.kl_divergences: Optional[np.ndarray] = None

    def fit_transform(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, np.float64)
        N = len(X)
        if N <= self.n_components:
            raise ValueError("need more points than output dimensions")
        if N > self.max_points:
            # The documented dense-on-MXU trade (module docstring) is only a
            # win in the plotting regime; make it explicit at runtime rather
            # than silently allocating an [N, N] affinity matrix.
            gb = 3 * N * N * 8 / 1e9  # P, Q, D2 fp64 resident together
            raise ValueError(
                f"N={N} exceeds max_points={self.max_points}: the dense "
                f"formulation would allocate ~{gb:.0f} GB of [N, N] "
                "matrices. Subsample the data, or pass max_points=N to "
                "override explicitly")
        if self.normalize:
            # Reference normalization path: zero-mean, scaled by max |x|.
            X = X - X.mean(axis=0)
            X = X / max(np.abs(X).max(), 1e-12)
        D2 = (np.sum(X ** 2, axis=1)[:, None] - 2.0 * X @ X.T
              + np.sum(X ** 2, axis=1)[None, :])
        np.fill_diagonal(D2, 0.0)
        D2 = np.maximum(D2, 0.0)

        P = _x2p(jnp.asarray(D2), float(np.log(self.perplexity)))
        P = P + P.T
        P = P / jnp.sum(P)
        P = jnp.maximum(P, 1e-12)

        # The reference fixes learningRate=500 (tuned for N in the
        # thousands); "auto" = max(N / exaggeration / 4, 50) (Belkina et
        # al. 2019, sklearn's default) keeps small embeddings from
        # diverging while matching 500-scale rates at reference-scale N.
        lr = (max(N / self.exaggeration / 4.0, 50.0)
              if self.learning_rate == "auto" else float(self.learning_rate))
        rng = np.random.RandomState(self.seed)
        Y0 = jnp.asarray(rng.randn(N, self.n_components) * 1e-4)
        Y, kls = _tsne_loop(
            P, Y0, self.max_iter, self.switch_momentum_iteration,
            self.stop_lying_iteration, lr,
            self.initial_momentum, self.final_momentum, self.min_gain,
            self.exaggeration)
        self.Y = np.asarray(Y)
        self.kl_divergences = np.asarray(kls)
        return self.Y

    # Reference `BarnesHutTsne` implements Model.fit(data)
    def fit(self, X: np.ndarray) -> "Tsne":
        self.fit_transform(X)
        return self


class BarnesHutTsne(Tsne):
    """API-compat alias: accepts the reference's `theta` and ignores it —
    the dense jitted path subsumes the Barnes-Hut approximation on TPU
    (see module docstring; reference falls back to dense when theta==0)."""

    def __init__(self, *, theta: float = 0.5, **kwargs):
        self.theta = theta
        super().__init__(**kwargs)
