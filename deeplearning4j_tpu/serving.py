"""Inference serving.

Equivalent capability of the reference's streaming serving route
(`dl4j-streaming/.../routes/DL4jServeRouteBuilder.java:1` — a Camel route
that deserializes records from Kafka, calls `output()`, and publishes
predictions). The TPU-era transport is a plain HTTP endpoint; Kafka/Camel
plumbing is not reproduced (SURVEY.md §2.1 "Streaming"), the serving
semantics are:

- `POST /predict` `{"data": [[...], ...]}` -> `{"predictions": [[...]]}`
- request MICRO-BATCHING: concurrent requests are coalesced and padded to
  one fixed `max_batch_size` so the jitted forward compiles exactly once
  and the MXU sees full batches (the TPU reason to batch at all);
- `GET /health` liveness probe;
- `GET /healthz` readiness probe: `{"status": "warming"|"ready"}` — with
  `warmup=True` the server pushes one synthetic padded batch through the
  model on a background thread at `start()` so the first real request pays
  no XLA compile; while warming, `POST /predict` answers 503 +
  `Retry-After` instead of stalling the caller behind the compile;
- `GET /metrics` Prometheus scrape of the process-global registry
  (request-latency + batch-size histograms, queue-depth gauge — PERF.md §11).
"""

from __future__ import annotations

import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu import observability as _obs

_M_REQUESTS = _obs.metrics.counter(
    "dl4j_serving_requests_total", "predict() requests",
    label_names=("outcome",))
_M_REQ_LATENCY = _obs.metrics.histogram(
    "dl4j_request_latency_seconds",
    "End-to-end predict() latency (queue wait + batch + forward)")
_M_BATCH_SIZE = _obs.metrics.histogram(
    "dl4j_serving_batch_size",
    "Real (pre-padding) rows per coalesced inference batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
_M_QUEUE_DEPTH = _obs.metrics.gauge(
    "dl4j_serving_queue_depth",
    "Requests waiting in the batcher queue (scrape-time)")


class _Pending:
    __slots__ = ("array", "event", "result", "error")

    def __init__(self, array: np.ndarray):
        self.array = array
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[str] = None


class InferenceServer:
    """HTTP predict server over a trained engine (MultiLayerNetwork or
    ComputationGraph — anything with `output(x)`).

    `max_batch_size` bounds the padded compile shape; `max_delay_ms` is how
    long the batcher waits to coalesce concurrent requests before running a
    partial (still padded) batch. With `warmup=True`, `start()` returns
    immediately but compiles the serving program on a background thread by
    pushing one synthetic `max_batch_size` batch through the model
    (`warmup_shape` overrides the per-example feature shape when the model
    config doesn't declare one); poll `GET /healthz` or call
    `wait_ready()` before sending traffic.
    """

    def __init__(self, net, port: int = 0, host: str = "127.0.0.1",
                 max_batch_size: int = 32, max_delay_ms: float = 5.0,
                 predict_timeout_s: Optional[float] = 300.0,
                 warmup: bool = False,
                 warmup_shape: Optional[Tuple[int, ...]] = None):
        self.net = net
        self.host = host
        self.port = port
        # How long predict() waits for its batch; the first request after a
        # model/shape change pays a fresh XLA compile, so the default is
        # generous. None waits indefinitely.
        self.predict_timeout_s = predict_timeout_s
        self.max_batch_size = int(max_batch_size)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.warmup = bool(warmup)
        self.warmup_shape = None if warmup_shape is None else tuple(warmup_shape)
        self._ready = threading.Event()
        self._ready.set()
        self._queue: "queue.Queue[Optional[_Pending]]" = queue.Queue()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._batcher: Optional[threading.Thread] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._warmup_thread: Optional[threading.Thread] = None

    @classmethod
    def from_checkpoint(cls, path, **kwargs) -> "InferenceServer":
        """Serve straight from a checkpoint on disk: a sharded checkpoint
        directory (a committed step or a `CheckpointManager` root — latest
        committed step wins) or a legacy model ZIP. The deploy path is one
        call: train anywhere, point the server at the checkpoint store —
        with `warmup=True` the checkpointed model is pre-compiled before
        the first request arrives (watch `GET /healthz` for "ready")."""
        from deeplearning4j_tpu.checkpoint import load_any

        return cls(load_any(path), **kwargs)

    # -------------------------------------------------------------- warmup

    @property
    def _status(self) -> str:
        # Derived from the Event (its own lock) so the warmup thread and
        # the HTTP handlers never race on a plain attribute.
        return "ready" if self._ready.is_set() else "warming"

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until warmup finished (immediately True without warmup)."""
        return self._ready.wait(timeout)

    def _warmup_run(self) -> None:
        """Push one synthetic padded batch through the model so the serving
        program (and, with the compile cache enabled, the AOT/persistent
        store) is hot before real traffic. Failures flip to "ready" anyway —
        the first real request then pays the compile, exactly the
        no-warmup behavior."""
        try:
            from deeplearning4j_tpu.compilation.warmup import (
                infer_feature_shape)

            shape = self.warmup_shape or infer_feature_shape(self.net)
            if shape is None:
                raise ValueError(
                    "cannot infer the model's input shape; pass "
                    "warmup_shape=(...) to InferenceServer")
            x = np.zeros((self.max_batch_size,) + tuple(shape), np.float32)
            with _obs.tracer.span("serving.warmup", cat="serving",
                                  padded_to=self.max_batch_size):
                np.asarray(self.net.output(x))
        except Exception as e:
            import warnings

            warnings.warn(f"serving warmup failed ({type(e).__name__}: {e}); "
                          "the first request will pay the compile")
        finally:
            self._ready.set()

    # ------------------------------------------------------------- batching

    def _run_batch(self, pending: List[_Pending]) -> None:
        rows = [p.array for p in pending]
        counts = [r.shape[0] for r in rows]
        x = np.concatenate(rows, axis=0)
        n = x.shape[0]
        _M_BATCH_SIZE.observe(n)
        if n < self.max_batch_size:
            # Pad to the fixed compile shape; padded rows are discarded.
            pad = np.zeros((self.max_batch_size - n,) + x.shape[1:], x.dtype)
            x = np.concatenate([x, pad], axis=0)
        with _obs.tracer.span("serving.batch", cat="serving",
                              requests=len(pending), rows=n,
                              padded_to=int(x.shape[0])):
            try:
                preds = np.asarray(self.net.output(x))[:n]
                off = 0
                for p, c in zip(pending, counts):
                    p.result = preds[off:off + c]
                    off += c
            except Exception as e:  # surface the failure to every caller
                for p in pending:
                    p.error = f"{type(e).__name__}: {e}"
        for p in pending:
            p.event.set()

    def _batch_loop(self) -> None:
        holdover: Optional[_Pending] = None
        while True:
            first = holdover if holdover is not None else self._queue.get()
            holdover = None
            if first is None:
                return
            batch = [first]
            total = first.array.shape[0]
            # Coalesce whatever arrives within the delay window, up to the
            # fixed batch size. A request that would overflow the fixed
            # compile shape is held for the NEXT batch — the padded shape
            # is the whole point (one jit compile, ever).
            import time as _time
            end = _time.monotonic() + self.max_delay_s
            while total < self.max_batch_size:
                remaining = end - _time.monotonic()
                if remaining <= 0:
                    break
                try:
                    item = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if item is None:
                    self._run_batch(batch)
                    return
                if total + item.array.shape[0] > self.max_batch_size:
                    holdover = item
                    break
                batch.append(item)
                total += item.array.shape[0]
            self._run_batch(batch)

    def predict(self, data) -> np.ndarray:
        """In-process entry (the HTTP handler calls this too). Observed once
        per caller request into `dl4j_request_latency_seconds`, however many
        server-sized chunks it splits into."""
        t0 = time.perf_counter()
        try:
            result = self._predict_rows(np.asarray(data, np.float32))
        except Exception:
            _M_REQUESTS.labels(outcome="error").inc()
            raise
        _M_REQUESTS.labels(outcome="ok").inc()
        _M_REQ_LATENCY.observe(time.perf_counter() - t0)
        return result

    def _predict_rows(self, arr: np.ndarray) -> np.ndarray:
        if arr.shape[0] > self.max_batch_size:
            # Split oversized requests into server-sized chunks.
            return np.concatenate([
                self._predict_rows(arr[i:i + self.max_batch_size])
                for i in range(0, arr.shape[0], self.max_batch_size)])
        p = _Pending(arr)
        self._queue.put(p)
        p.event.wait(timeout=self.predict_timeout_s)
        if p.error is not None:
            raise RuntimeError(p.error)
        if p.result is None:
            raise TimeoutError(
                f"prediction timed out after {self.predict_timeout_s}s "
                "(cold XLA compiles can be slow; raise predict_timeout_s "
                "or pass None to wait indefinitely)")
        return p.result

    # --------------------------------------------------------------- http

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, obj, code=200, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._json({"status": "ok",
                                "model": type(server.net).__name__})
                elif self.path == "/healthz":
                    self._json({"status": server._status})
                elif self.path == "/metrics":
                    body = _obs.metrics.to_prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._json({"error": "not found",
                                "routes": ["/health", "/healthz",
                                           "/metrics", "/predict"]}, 404)

            def do_POST(self):
                if self.path != "/predict":
                    return self._json({"error": "not found"}, 404)
                if server._status != "ready":
                    # Don't park callers behind the warmup compile: tell
                    # them to retry once /healthz flips to "ready".
                    return self._json({"error": "warming up",
                                       "status": server._status},
                                      503, headers={"Retry-After": "1"})
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length))
                    preds = server.predict(payload["data"])
                except (KeyError, ValueError, json.JSONDecodeError) as e:
                    return self._json({"error": f"bad request: {e}"}, 400)
                except Exception as e:
                    return self._json({"error": str(e)}, 500)
                self._json({"predictions": preds.tolist()})

        return Handler

    def start(self) -> "InferenceServer":
        _M_QUEUE_DEPTH.set_function(self._queue.qsize)
        self._batcher = threading.Thread(target=self._batch_loop, daemon=True)
        self._batcher.start()
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._serve_thread.start()
        if self.warmup:
            # The port is already bound and /healthz answers "warming", so
            # orchestrators can watch readiness while the model compiles.
            self._ready.clear()
            self._warmup_thread = threading.Thread(
                target=self._warmup_run, name="dl4j-serving-warmup",
                daemon=True)
            self._warmup_thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        _M_QUEUE_DEPTH.set_function(None)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self._queue.put(None)
