"""Host-side paged KV-cache pool + prompt prefix cache.

The dense `DecodeStepper` pins `slots x capacity` KV rows per attention
layer whether a slot is two tokens deep or two hundred — HBM spent on
padding directly caps slots-per-replica, and N slots decoding from the
same system prompt hold N copies of its KV. This module is the
bookkeeping half of the paged replacement (vLLM's PagedAttention, Kwon
et al., SOSP 2023): KV lives in fixed-size PAGES shared by all slots,
each sequence maps logical page indices to physical pages through a
per-slot int32 row of `table`, and pages are REFCOUNTED so a shared
prefix is resident once.

Division of labor:

- this module is pure host-side metadata — refcounts, the free list,
  per-slot page lists, the `[slots, pages_per_seq]` page table, and
  copy-on-write PLANNING (`plan_appends` returns the `(src, dst)` page
  copies the device must perform before the next append);
- the device arrays (`k_pages`/`v_pages` per attention layer) and the
  jitted scatter/gather live in `models.zoo.PagedDecodeStepper` and
  `nn/layers/attention.py`; the attention read goes through the
  `flash_attention_paged` kernel seam.

Invariants:

- physical page 0 is the reserved ZERO page: unmapped table entries
  point at it, so free slots riding a decode dispatch scatter their
  dummy-token KV there and never corrupt a live page. It is never
  allocated and never freed.
- a page in any slot's WRITE RANGE has refcount 1 at dispatch time:
  `plan_appends` copies-on-write every shared page an append would
  touch, so concurrent slots can never scatter into the same physical
  row. Garbage rows (pad tails, CoW'd tails, rejected speculative
  tokens) sit at key positions >= the cursor, where the attention
  mask's `exp(-1e30 - m)` underflows to exactly 0.0 — which is why the
  paged read is bit-identical to the dense one.
- `PrefixCache` holds +1 ref on every page of an admitted prompt, so a
  cached prefix survives its slot's retirement; a hit re-refs the pages
  and replays the STORED next-token distribution (zero dispatches —
  TTFT on a repeat prompt is pure sampling). The first divergent append
  CoWs the tail page because its refcount is >= 2.
"""

from __future__ import annotations

import collections
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class PoolExhaustedError(RuntimeError):
    """No free page and the reclaim hook (prefix-cache eviction) could
    not surrender one. The default pool sizing (`slots * capacity /
    page_size + 1`) can never hit this even with zero sharing."""


class KVPagePool:
    """Refcounted fixed-size-page allocator for the paged decode path.

    `table` is the host-authoritative `[slots, pages_per_seq]` int32
    page table the stepper ships to the device before every dispatch;
    unmapped entries are 0 (the zero page).
    """

    def __init__(self, slots: int, capacity: int, page_size: int,
                 pages: Optional[int] = None,
                 reclaim: Optional[Callable[[], bool]] = None):
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        if capacity % page_size:
            raise ValueError(
                f"decode cache capacity {capacity} must be a multiple of "
                f"page_size {page_size}")
        self.slots = int(slots)
        self.capacity = int(capacity)
        self.page_size = int(page_size)
        self.pages_per_seq = self.capacity // self.page_size
        if pages is None:
            # Worst case (zero sharing): every slot fully deep, + page 0.
            pages = self.slots * self.pages_per_seq + 1
        self.num_pages = int(pages)
        if self.num_pages < 2:
            raise ValueError("pool needs >= 2 pages (page 0 is reserved)")
        # LIFO free list keeps recently-freed (cache-warm) pages hot.
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._ref = np.zeros(self.num_pages, np.int64)
        self._seq: Dict[int, List[int]] = {}   # slot -> physical pages
        self._len: Dict[int, int] = {}         # slot -> token length
        self._cow: Dict[int, int] = {}         # slot -> CoW copies since install
        self.table = np.zeros((self.slots, self.pages_per_seq), np.int32)
        # Called when the free list runs dry; returns True if it freed
        # >= 1 page (the scheduler wires PrefixCache.evict_one here).
        self.reclaim = reclaim

    # ------------------------------------------------------------ queries

    @property
    def free_count(self) -> int:
        return len(self._free)

    def counts(self) -> Dict[str, int]:
        """Page states for the `dl4j_kv_pages` gauges: free / used
        (refcount 1) / shared (refcount >= 2). Page 0 is none of them."""
        return {
            "free": len(self._free),
            "used": int(np.count_nonzero(self._ref == 1)),
            "shared": int(np.count_nonzero(self._ref >= 2)),
        }

    def tracked(self) -> Tuple[int, ...]:
        return tuple(sorted(self._seq))

    def length_of(self, slot: int) -> int:
        return self._len.get(slot, 0)

    def pages_of(self, slot: int) -> Tuple[int, ...]:
        return tuple(self._seq.get(slot, ()))

    def cow_count(self, slot: int) -> int:
        """Copy-on-write page copies this slot has forced since its
        install (the request-ledger's per-request CoW cost field)."""
        return self._cow.get(slot, 0)

    # --------------------------------------------------------- refcounting

    def _alloc_one(self) -> int:
        while not self._free:
            if self.reclaim is None or not self.reclaim():
                raise PoolExhaustedError(
                    f"KV page pool exhausted ({self.num_pages - 1} usable "
                    f"pages of {self.page_size} tokens; "
                    f"{len(self._seq)} resident sequences)")
        p = self._free.pop()
        self._ref[p] = 1
        return p

    def _reserve(self, need: int) -> None:
        """Fail-before-mutate: make sure `need` pages are allocatable,
        reclaiming from the prefix cache if necessary."""
        while len(self._free) < need:
            if self.reclaim is None or not self.reclaim():
                raise PoolExhaustedError(
                    f"KV page pool exhausted: need {need} pages, "
                    f"{len(self._free)} free of {self.num_pages - 1} usable")

    def ref(self, pages: Sequence[int]) -> None:
        for p in pages:
            if p == 0:
                raise ValueError("page 0 is the reserved zero page")
            self._ref[p] += 1

    def unref(self, pages: Sequence[int]) -> None:
        for p in pages:
            if self._ref[p] <= 0:
                raise ValueError(f"unref of unallocated page {p}")
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free.append(p)

    # ---------------------------------------------------- slot lifecycle

    def install_slot(self, slot: int, length: int) -> List[int]:
        """Allocate fresh pages covering `length` tokens for `slot`
        (prefill-miss install). Returns the physical page list."""
        self.free_slot(slot)
        need = -(-int(length) // self.page_size)  # ceil
        if need > self.pages_per_seq:
            raise ValueError(
                f"sequence length {length} exceeds capacity {self.capacity}")
        self._reserve(need)
        pages = [self._alloc_one() for _ in range(need)]
        self._seq[slot] = pages
        self._len[slot] = int(length)
        self.table[slot, :] = 0
        self.table[slot, :need] = pages
        return pages

    def install_shared(self, slot: int, pages: Sequence[int],
                       length: int) -> None:
        """Point `slot` at already-resident pages (prefix-cache hit):
        +1 ref each, no allocation, no device writes needed."""
        self.free_slot(slot)
        pages = list(pages)
        self.ref(pages)
        self._seq[slot] = pages
        self._len[slot] = int(length)
        self.table[slot, :] = 0
        self.table[slot, :len(pages)] = pages

    def free_slot(self, slot: int) -> None:
        """Retire a slot: unref its pages (freed at refcount 0 — a
        prefix-cache ref keeps shared prefix pages resident) and zero
        its table row so future rides write to the zero page."""
        pages = self._seq.pop(slot, None)
        self._len.pop(slot, None)
        self._cow.pop(slot, None)
        self.table[slot, :] = 0
        if pages:
            self.unref(pages)

    def rewind(self, slot: int, length: int) -> None:
        """Truncate a slot to `length` tokens (speculative-decoding
        rejection): pages wholly beyond the new length are unref'd.
        No-op for untracked slots."""
        if slot not in self._seq:
            return
        length = int(length)
        keep = -(-length // self.page_size)
        pages = self._seq[slot]
        drop = pages[keep:]
        if drop:
            self._seq[slot] = pages[:keep]
            self.table[slot, keep:len(pages)] = 0
            self.unref(drop)
        self._len[slot] = length

    # ------------------------------------------------------------ appends

    def plan_appends(self, t: int) -> List[Tuple[int, int]]:
        """Advance every tracked slot's length by `t` tokens, allocating
        pages the append crosses into and copy-on-writing shared pages in
        the write range. Returns the `(src, dst)` physical page copies the
        device must perform BEFORE the dispatch. Atomic: page need is
        counted (and reclaimed) up front, so exhaustion raises before any
        state mutates."""
        t = int(t)
        plans = []  # (slot, [page indices to fix])
        need = 0
        for slot, pages in self._seq.items():
            n = self._len[slot]
            first, last = n // self.page_size, (n + t - 1) // self.page_size
            todo = []
            for pi in range(first, min(last, self.pages_per_seq - 1) + 1):
                if pi >= len(pages) or self._ref[pages[pi]] >= 2:
                    todo.append(pi)
                    need += 1
            plans.append((slot, todo))
        self._reserve(need)
        copies: List[Tuple[int, int]] = []
        for slot, todo in plans:
            pages = self._seq[slot]
            for pi in todo:
                new = self._alloc_one()
                if pi < len(pages):
                    copies.append((pages[pi], new))   # CoW: shared page
                    self._cow[slot] = self._cow.get(slot, 0) + 1
                    self.unref([pages[pi]])
                    pages[pi] = new
                else:
                    pages.append(new)
                self.table[slot, pi] = new
            self._len[slot] += t
        return copies


class PrefixCache:
    """LRU prompt -> primed-KV cache over pool pages.

    Keyed on the exact prompt token tuple (the dict hash IS the
    prompt-token hash; exact-match lookup, so a collision can never
    serve the wrong prefix). An entry holds the prompt's physical
    pages (+1 pool ref each, so they survive slot retirement), the
    prompt length, and the next-token distribution the prefill
    produced — a hit installs the pages by reference and replays the
    stored distribution, skipping prefill entirely.
    """

    def __init__(self, pool: KVPagePool, max_entries: int = 32):
        self.pool = pool
        self.max_entries = int(max_entries)
        # key -> (pages, length, probs)
        self._entries: "collections.OrderedDict[Tuple[int, ...], tuple]" = \
            collections.OrderedDict()
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, prompt: Sequence[int], namespace=None):
        """`(pages, length, probs)` for an exact prompt match (LRU
        refresh), else None. Counts hits/misses. `namespace` partitions
        the key space — the SAME prompt prefilled through different
        param trees (per-adapter serving) has different KV, so a hit must
        never cross adapters."""
        key = (namespace,) + tuple(int(i) for i in prompt)
        ent = self._entries.get(key)
        if ent is None:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return ent

    def admit(self, prompt: Sequence[int], pages: Sequence[int],
              length: int, probs, namespace=None) -> None:
        """Cache a freshly-prefilled prompt: +1 ref on its pages, store
        the next-token distribution, LRU-evict beyond `max_entries`.
        `namespace` must match the `get` that missed (see there)."""
        key = (namespace,) + tuple(int(i) for i in prompt)
        if key in self._entries or not pages:
            return
        self.pool.ref(pages)
        self._entries[key] = (tuple(int(p) for p in pages), int(length),
                              np.array(probs, copy=True))
        while len(self._entries) > self.max_entries:
            self.evict_one()

    def evict_one(self) -> bool:
        """Drop the least-recently-used entry (the pool's reclaim hook
        under page pressure). Returns True when something was evicted."""
        if not self._entries:
            return False
        _, (pages, _, _) = self._entries.popitem(last=False)
        self.pool.unref(pages)
        return True

    def clear(self) -> None:
        while self.evict_one():
            pass
