"""ResNet-50 as a ComputationGraph (the BASELINE.md flagship config).

The reference trains ResNet-50 as a ComputationGraph exercising the
conv/batchnorm cuDNN helper path; here every conv/BN lowers to XLA
(`deeplearning4j-cuda/.../CudnnConvolutionHelper.java` has no equivalent —
SURVEY.md §7). Built via the public GraphBuilder DSL with bottleneck residual
blocks (ElementWiseVertex add = the reference's residual merge).
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf.enums import Updater
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    BatchNormalization,
    BottleneckBlock,
    ConvolutionLayer,
    GlobalPoolingLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.neural_net import (
    ComputationGraphConfiguration,
    NeuralNetConfiguration,
)


def _conv_bn(b, name, inp, n_out, kernel, stride, activation="relu", mode="same"):
    b.add_layer(
        f"{name}_conv",
        ConvolutionLayer(kernel_size=kernel, stride=stride, n_out=n_out,
                         convolution_mode=mode, activation="identity", has_bias=False),
        inp,
    )
    b.add_layer(
        f"{name}_bn",
        BatchNormalization(activation=activation),
        f"{name}_conv",
    )
    return f"{name}_bn"


def _bottleneck(b, name, inp, filters, stride, project: bool):
    """Bottleneck residual block: 1x1 -> 3x3 -> 1x1 (+ projection shortcut)."""
    f1, f2, f3 = filters, filters, filters * 4
    x = _conv_bn(b, f"{name}_a", inp, f1, (1, 1), stride)
    x = _conv_bn(b, f"{name}_b", x, f2, (3, 3), (1, 1))
    x = _conv_bn(b, f"{name}_c", x, f3, (1, 1), (1, 1), activation="identity")
    if project:
        shortcut = _conv_bn(b, f"{name}_proj", inp, f3, (1, 1), stride,
                            activation="identity")
    else:
        shortcut = inp
    b.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, shortcut)
    b.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), f"{name}_add")
    return f"{name}_relu"


def _bottleneck_fused(b, name, inp, filters, stride, project: bool):
    """The same bottleneck as ONE fused layer (kernels/bottleneck_block.py):
    the block-boundary seam the fused builder emits instead of the
    five-vertex chain. With `DL4J_TPU_KERNELS=xla` the layer's fallback is
    the unfused chain verbatim, so numerics are unchanged either way."""
    b.add_layer(
        f"{name}_block",
        BottleneckBlock(filters=filters, stride=stride, project=project,
                        activation="relu"),
        inp,
    )
    return f"{name}_block"


def resnet50(
    n_classes: int = 1000, image: int = 224, channels: int = 3,
    seed: int = 123, lr: float = 0.1, dtype: str = "bfloat16",
    fused_blocks: bool = False,
) -> ComputationGraphConfiguration:
    b = (
        NeuralNetConfiguration.builder()
        .seed(seed).learning_rate(lr).updater(Updater.NESTEROVS).momentum(0.9)
        .weight_init("relu").l2(1e-4).dtype(dtype)
        .graph_builder()
        .add_inputs("input")
    )
    x = _conv_bn(b, "stem", "input", 64, (7, 7), (2, 2))
    b.add_layer("stem_pool",
                SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                 stride=(2, 2), convolution_mode="same"),
                x)
    x = "stem_pool"
    stages = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]
    block = _bottleneck_fused if fused_blocks else _bottleneck
    for si, (filters, blocks, first_stride) in enumerate(stages):
        for bi in range(blocks):
            stride = (first_stride, first_stride) if bi == 0 else (1, 1)
            x = block(b, f"s{si}_b{bi}", x, filters, stride, project=(bi == 0))
    b.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
    b.add_layer("fc",
                OutputLayer(n_out=n_classes, activation="softmax",
                            loss_function="mcxent", weight_init="xavier"),
                "avgpool")
    return (
        b.set_outputs("fc")
        .set_input_types(InputType.convolutional(image, image, channels))
        .build()
    )
