"""Model zoo: the reference-designated benchmark configs (BASELINE.md).

- LeNet-MNIST (reference: dl4j-examples LenetMnistExample — MultiLayerNetwork)
- MLP-MNIST (the minimal end-to-end slice)
- GravesLSTM char-RNN (reference: GravesLSTMCharModellingExample)
- VGG-16 (reference: Keras-import VGG16 zoo, `keras/trainedmodels/TrainedModels.java:16-19`)
- AlexNet (reference: the LRN layer's model family, `conf/layers/LocalResponseNormalization.java`)

All built through the public config DSL, so they double as integration tests
of the builder.
"""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf.enums import Updater
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer,
    DenseLayer,
    GravesLSTM,
    LocalResponseNormalization,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.neural_net import (
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)


def mlp_mnist(seed: int = 123, lr: float = 0.006) -> MultiLayerConfiguration:
    """Two-layer MLP on flat 28x28 inputs."""
    return (
        NeuralNetConfiguration.builder()
        .seed(seed).learning_rate(lr).updater(Updater.NESTEROVS).momentum(0.9)
        .weight_init("xavier").l2(1e-4)
        .list()
        .layer(DenseLayer(n_out=1000, activation="relu"))
        .layer(OutputLayer(n_out=10, activation="softmax", loss_function="negativeloglikelihood"))
        .set_input_type(InputType.feed_forward(784))
        .build()
    )


def lenet_mnist(seed: int = 123, lr: float = 0.01, dtype: str = "float32") -> MultiLayerConfiguration:
    """LeNet: conv5x5x20 -> maxpool -> conv5x5x50 -> maxpool -> dense500 -> softmax10."""
    return (
        NeuralNetConfiguration.builder()
        .seed(seed).learning_rate(lr).updater(Updater.NESTEROVS).momentum(0.9)
        .weight_init("xavier").l2(5e-4).activation("identity").dtype(dtype)
        .list()
        .layer(ConvolutionLayer(kernel_size=(5, 5), stride=(1, 1), n_out=20, activation="identity"))
        .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
        .layer(ConvolutionLayer(kernel_size=(5, 5), stride=(1, 1), n_out=50, activation="identity"))
        .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
        .layer(DenseLayer(n_out=500, activation="relu"))
        .layer(OutputLayer(n_out=10, activation="softmax", loss_function="negativeloglikelihood"))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build()
    )


def char_rnn(
    vocab_size: int = 77, hidden: int = 200, layers: int = 2,
    tbptt_length: int = 50, seed: int = 12345, dtype: str = "float32",
) -> MultiLayerConfiguration:
    """GravesLSTM character model (reference example: 2x200 LSTM + RnnOutput)."""
    builder = (
        NeuralNetConfiguration.builder()
        .seed(seed).learning_rate(0.1).updater(Updater.RMSPROP).rms_decay(0.95)
        .weight_init("xavier").l2(0.001).dtype(dtype)
        .list()
    )
    for _ in range(layers):
        builder.layer(GravesLSTM(n_out=hidden, activation="tanh"))
    builder.layer(RnnOutputLayer(n_out=vocab_size, activation="softmax", loss_function="mcxent"))
    return (
        builder
        .backprop_type("truncatedbptt")
        .t_bptt_forward_length(tbptt_length)
        .t_bptt_backward_length(tbptt_length)
        .set_input_type(InputType.recurrent(vocab_size))
        .build()
    )


def vgg16(n_classes: int = 1000, seed: int = 123, dtype: str = "bfloat16") -> MultiLayerConfiguration:
    """VGG-16 (configuration matches the Keras VGG16 the reference imports)."""
    def conv(n):
        return ConvolutionLayer(kernel_size=(3, 3), stride=(1, 1),
                                convolution_mode="same", n_out=n, activation="relu")

    def pool():
        return SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2))

    b = (
        NeuralNetConfiguration.builder()
        .seed(seed).learning_rate(0.01).updater(Updater.NESTEROVS).momentum(0.9)
        .weight_init("relu").dtype(dtype)
        .list()
    )
    for block, (n, reps) in enumerate([(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]):
        for _ in range(reps):
            b.layer(conv(n))
        b.layer(pool())
    b.layer(DenseLayer(n_out=4096, activation="relu"))
    b.layer(DenseLayer(n_out=4096, activation="relu"))
    b.layer(OutputLayer(n_out=n_classes, activation="softmax", loss_function="mcxent"))
    return b.set_input_type(InputType.convolutional(224, 224, 3)).build()


def alexnet(n_classes: int = 1000, seed: int = 123, image: int = 224,
            dtype: str = "bfloat16") -> MultiLayerConfiguration:
    """AlexNet (Krizhevsky et al. 2012) — the model family the reference's
    LocalResponseNormalization layer exists for
    (`nn/conf/layers/LocalResponseNormalization.java` cites it) and the
    dl4j-era examples' large-image CNN: conv11x11/4 + LRN + pool,
    conv5x5 + LRN + pool, 3x conv3x3, pool, two dense-4096, softmax."""
    return (
        NeuralNetConfiguration.builder()
        .seed(seed).learning_rate(0.01).updater(Updater.NESTEROVS)
        .momentum(0.9).weight_init("xavier").l2(5e-4).dtype(dtype)
        .list()
        .layer(ConvolutionLayer(kernel_size=(11, 11), stride=(4, 4),
                                n_out=96, activation="relu",
                                convolution_mode="truncate"))
        .layer(LocalResponseNormalization())
        .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                stride=(2, 2)))
        .layer(ConvolutionLayer(kernel_size=(5, 5), stride=(1, 1),
                                n_out=256, activation="relu",
                                convolution_mode="same"))
        .layer(LocalResponseNormalization())
        .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                stride=(2, 2)))
        .layer(ConvolutionLayer(kernel_size=(3, 3), stride=(1, 1),
                                n_out=384, activation="relu",
                                convolution_mode="same"))
        .layer(ConvolutionLayer(kernel_size=(3, 3), stride=(1, 1),
                                n_out=384, activation="relu",
                                convolution_mode="same"))
        .layer(ConvolutionLayer(kernel_size=(3, 3), stride=(1, 1),
                                n_out=256, activation="relu",
                                convolution_mode="same"))
        .layer(SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                stride=(2, 2)))
        .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
        .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
        .layer(OutputLayer(n_out=n_classes, activation="softmax",
                           loss_function="negativeloglikelihood"))
        .set_input_type(InputType.convolutional(image, image, 3))
        .build()
    )



def _add_transformer_block(gb, prev, i, d_model, n_heads, *, causal,
                           moe=False, n_experts=4,
                           decode_cache_length=None):
    """One pre-LN transformer block: x + Attn(LN(x)); x + FFN(LN(x)).
    Shared by `transformer_lm` (causal, optional MoE/KV cache) and
    `transformer_classifier` (bidirectional)."""
    from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex
    from deeplearning4j_tpu.nn.conf.layers import (
        LayerNormalization, MoELayer, SelfAttentionLayer,
    )

    gb.add_layer(f"ln_a{i}", LayerNormalization(), prev)
    gb.add_layer(f"attn{i}",
                 SelfAttentionLayer(
                     n_out=d_model, n_heads=n_heads, causal=causal,
                     decode_cache_length=decode_cache_length), f"ln_a{i}")
    gb.add_vertex(f"res_a{i}", ElementWiseVertex(op="add"), prev, f"attn{i}")
    gb.add_layer(f"ln_f{i}", LayerNormalization(), f"res_a{i}")
    if moe:
        gb.add_layer(f"ffn{i}",
                     MoELayer(n_out=d_model, n_experts=n_experts,
                              expert_hidden=4 * d_model, top_k=2,
                              router_jitter=1e-2), f"ln_f{i}")
    else:
        gb.add_layer(f"ff1_{i}", DenseLayer(n_out=4 * d_model,
                                            activation="relu"), f"ln_f{i}")
        gb.add_layer(f"ffn{i}", DenseLayer(n_out=d_model,
                                           activation="identity"),
                     f"ff1_{i}")
    gb.add_vertex(f"res_f{i}", ElementWiseVertex(op="add"),
                  f"res_a{i}", f"ffn{i}")
    return f"res_f{i}"


def transformer_lm(vocab_size: int, *, t: int = 64, d_model: int = 64,
                   n_heads: int = 4, n_blocks: int = 2, moe: bool = False,
                   n_experts: int = 4, seed: int = 123, lr: float = 3e-3,
                   dtype: str = "float32", decode_cache_length=None):
    """Decoder-only transformer language model built through the config DSL
    (ComputationGraph: residual adds around causal SelfAttentionLayer and
    an FFN — DenseLayer pair, or MoELayer when `moe`).

    No reference equivalent (the reference predates attention; its
    language model is the GravesLSTM char-RNN above) — this is the
    round-5 model-family face of the SURVEY §2.3/§5 parallelism
    extensions: the same config trains sequence-sharded
    (`ParallelWrapper(..., seq_axis=...)` -> ring attention) or
    expert-parallel (`expert_axis=...`) with zero model changes.

    `decode_cache_length=N` sizes every attention layer's KV cache (and
    the positional table) for O(1)-per-token stateful generation via
    `ComputationGraph.rnn_time_step` / `generate_lm(use_cache=True)`.
    """
    from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex
    from deeplearning4j_tpu.nn.conf.layers import (
        EmbeddingLayer,
        LayerNormalization,
        MoELayer,
        PositionalEmbeddingLayer,
        SelfAttentionLayer,
    )

    gb = (NeuralNetConfiguration.builder()
          .seed(seed).learning_rate(lr).updater(Updater.ADAM).dtype(dtype)
          .weight_init("xavier")
          .graph_builder()
          .add_inputs("tokens")
          .add_layer("emb", EmbeddingLayer(n_out=d_model, has_bias=False,
                                           input_format="ids",
                                           activation="identity"), "tokens")
          .add_layer("pos", PositionalEmbeddingLayer(
              max_length=max(t, 16, decode_cache_length or 0),
              stateful=decode_cache_length is not None), "emb"))
    prev = "pos"
    for i in range(n_blocks):
        prev = _add_transformer_block(
            gb, prev, i, d_model, n_heads, causal=True, moe=moe,
            n_experts=n_experts, decode_cache_length=decode_cache_length)
    gb.add_layer("ln_out", LayerNormalization(), prev)
    gb.add_layer("out", RnnOutputLayer(n_out=vocab_size,
                                       activation="softmax",
                                       loss_function="mcxent"), "ln_out")
    gb.set_outputs("out")
    gb.set_input_types(InputType.recurrent(vocab_size, t))
    return gb.build()


def _sample_token(probs, rng, temperature: float, top_k: int, top_p: float):
    """Sample one next-token id from a [V] probability vector (greedy at
    temperature<=0; top-k / nucleus top-p restrictions compose, applied
    before temperature). Tokens excluded by top-k/top-p are masked to
    -inf in logit space so re-tempering can NEVER re-admit them."""
    import numpy as np

    probs = np.asarray(probs, np.float64)
    if temperature <= 0:
        return int(probs.argmax())
    if top_k:
        kth = np.sort(probs)[-min(top_k, len(probs))]
        probs = np.where(probs >= kth, probs, 0.0)
    if top_p:
        order = np.argsort(-probs)
        csum = np.cumsum(probs[order]) - probs[order]
        cut = order[csum >= top_p * probs.sum()]
        probs = probs.copy()
        probs[cut] = 0.0
    logits = np.log(np.maximum(probs, 1e-12)) / temperature
    logits[probs <= 0] = -np.inf
    p = np.exp(logits - logits.max())
    p /= p.sum()
    return int(rng.choice(len(p), p=p))


def _sample_tokens(probs, rng, temperature: float, top_k: int):
    """Batched `_sample_token`: [B, V] probabilities -> [B] ids, one rng
    draw per row (same draw order as a Python loop over rows, so seeded
    generations are reproducible)."""
    import numpy as np

    probs = np.asarray(probs, np.float64)
    if temperature <= 0:
        return probs.argmax(-1)
    if top_k:
        kth = np.sort(probs, axis=-1)[:, -min(top_k, probs.shape[-1])]
        probs = np.where(probs >= kth[:, None], probs, 0.0)
    logits = np.log(np.maximum(probs, 1e-12)) / temperature
    # Same exclusion mask as the single-sequence path: without it,
    # temperature > 1 re-inflates the log(1e-12) floor of excluded tokens
    # and batched top-k can sample outside the top k.
    logits[probs <= 0] = -np.inf
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.asarray([rng.choice(p.shape[-1], p=p[i])
                       for i in range(p.shape[0])])


def generate_lm(cg, prompt_ids, n_steps: int, *, window: int,
                temperature: float = 1.0, seed: int = 0,
                use_cache: bool = False, top_k: int = 0,
                top_p: float = 0.0):
    """Autoregressive sampling from a `transformer_lm` ComputationGraph
    (reference analog: GravesLSTMCharModellingExample's
    sampleCharactersFromNetwork).

    Two modes:
    - `use_cache=False`: re-read the window each token — the context is
      right-padded to `window` (one compiled shape) and the next-token
      distribution read at the last real position; O(window) attention
      per token.
    - `use_cache=True` (model built with `decode_cache_length`): stateful
      O(1)-per-token decode via `ComputationGraph.rnn_time_step` — prime
      once with the prompt, then single-token steps against the KV cache,
      exactly like the reference's RNN sampling loop.

    `temperature=0` is greedy argmax; `top_k`/`top_p` restrict sampling to
    the k most probable tokens / the smallest nucleus with cumulative
    probability >= p (composable; applied before temperature). Returns
    prompt + generated ids.
    """
    import numpy as np

    rng = np.random.RandomState(seed)
    ids = list(int(i) for i in prompt_ids)
    if not ids:
        raise ValueError("need at least one prompt token")

    def pick(probs):
        return _sample_token(probs, rng, temperature, top_k, top_p)

    if use_cache:
        cache_lens = [
            v.layer.decode_cache_length
            for v in cg.layer_vertices.values()
            if type(v.layer).__name__ == "SelfAttentionLayer"
        ]
        if not cache_lens or any(c is None for c in cache_lens):
            raise ValueError(
                "use_cache=True needs a model built with "
                "transformer_lm(..., decode_cache_length=N)")
        if len(ids) + n_steps > min(cache_lens):
            raise ValueError(
                f"prompt ({len(ids)}) + n_steps ({n_steps}) exceeds the "
                f"decode cache capacity {min(cache_lens)}")
        if n_steps == 0:
            return ids
        cg.rnn_clear_previous_state()
        out = cg.rnn_time_step(
            np.asarray(ids, np.float32)[None, :, None])[0]  # [1, Tp, V]
        ids.append(pick(out[0, -1]))
        for _ in range(n_steps - 1):
            out = cg.rnn_time_step(
                np.asarray([[[float(ids[-1])]]], np.float32))[0]
            ids.append(pick(out[0, -1] if out.ndim == 3 else out[0]))
        return ids

    for _ in range(n_steps):
        ctx = ids[-window:]
        # [1, T, 1] index layout: unambiguous for EmbeddingLayer (a 2-D
        # float [1, window] would be misread as one-hot when window
        # happens to equal vocab_size).
        x = np.zeros((1, window, 1), np.float32)
        x[0, : len(ctx), 0] = ctx
        out = cg.output_single(x)  # [1, T, V] per-step softmax
        ids.append(pick(out[0, len(ctx) - 1]))
    return ids


def transformer_classifier(vocab_size: int, n_classes: int, *, t: int = 64,
                           d_model: int = 64, n_heads: int = 4,
                           n_blocks: int = 2, seed: int = 123,
                           lr: float = 3e-3, dtype: str = "float32"):
    """Bidirectional transformer encoder + mean-pool + softmax head — the
    sequence-classification sibling of `transformer_lm` (BERT-shaped:
    non-causal attention over the whole sequence). Feature masks flow
    through attention (key masking) and the mask-aware global pooling, so
    ragged sequences classify correctly.
    """
    from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex
    from deeplearning4j_tpu.nn.conf.layers import (
        EmbeddingLayer,
        GlobalPoolingLayer,
        LayerNormalization,
        PositionalEmbeddingLayer,
        SelfAttentionLayer,
    )

    gb = (NeuralNetConfiguration.builder()
          .seed(seed).learning_rate(lr).updater(Updater.ADAM).dtype(dtype)
          .weight_init("xavier")
          .graph_builder()
          .add_inputs("tokens")
          .add_layer("emb", EmbeddingLayer(n_out=d_model, has_bias=False,
                                           input_format="ids",
                                           activation="identity"), "tokens")
          .add_layer("pos", PositionalEmbeddingLayer(max_length=max(t, 16)),
                     "emb"))
    prev = "pos"
    for i in range(n_blocks):
        prev = _add_transformer_block(gb, prev, i, d_model, n_heads,
                                      causal=False)
    gb.add_layer("ln_out", LayerNormalization(), prev)
    gb.add_layer("pool", GlobalPoolingLayer(pooling_type="avg"), "ln_out")
    gb.add_layer("out", OutputLayer(n_out=n_classes, activation="softmax",
                                    loss_function="mcxent"), "pool")
    gb.set_outputs("out")
    gb.set_input_types(InputType.recurrent(vocab_size, t))
    return gb.build()


def generate_lm_batch(cg, prompts, n_steps: int, *, temperature: float = 1.0,
                      seed: int = 0, top_k: int = 0):
    """KV-cached batched generation: `prompts` is [B, Tp] (equal-length
    int prompts); every sequence decodes in the SAME single-token steps,
    so the per-token cost is one dispatch for the whole batch — the
    serving shape of the decode path. Returns [B, Tp + n_steps] ids.

    Requires a model built with `decode_cache_length >= Tp + n_steps`.
    """
    import numpy as np

    rng = np.random.RandomState(seed)
    prompts = np.asarray(prompts, np.int64)
    if prompts.ndim != 2 or prompts.shape[1] < 1:
        raise ValueError("prompts must be [B, Tp] with Tp >= 1")
    B, Tp = prompts.shape
    cache_lens = [v.layer.decode_cache_length
                  for v in cg.layer_vertices.values()
                  if type(v.layer).__name__ == "SelfAttentionLayer"]
    if not cache_lens or any(c is None for c in cache_lens):
        raise ValueError("generate_lm_batch needs decode_cache_length")
    if Tp + n_steps > min(cache_lens):
        raise ValueError(
            f"Tp ({Tp}) + n_steps ({n_steps}) exceeds the decode cache "
            f"capacity {min(cache_lens)}")

    def pick(probs):  # probs: [B, V] -> [B]
        return _sample_tokens(probs, rng, temperature, top_k)

    out = [prompts]
    cg.rnn_clear_previous_state()
    step_out = cg.rnn_time_step(
        prompts.astype(np.float32)[:, :, None])[0]  # [B, Tp, V]
    for _ in range(n_steps):
        nxt = pick(step_out[:, -1])
        out.append(nxt[:, None])
        step_out = cg.rnn_time_step(
            nxt.astype(np.float32)[:, None, None])[0]  # [B, 1, V]
    return np.concatenate(out, axis=1)


def decode_cache_capacity(cg) -> int:
    """Smallest `decode_cache_length` across the graph's attention layers —
    the hard per-sequence step budget. Raises when the model was built
    without a KV cache.

    Both decode layouts share this budget: the dense `DecodeStepper`
    allocates it up front per slot, while `PagedDecodeStepper` backs it
    with pool pages (`models/kv_pool.py`) allocated as a sequence
    deepens — capacity must then be a multiple of the page size."""
    caps = [v.layer.decode_cache_length
            for v in cg.layer_vertices.values()
            if type(v.layer).__name__ == "SelfAttentionLayer"]
    if not caps or any(c is None for c in caps):
        raise ValueError(
            "model has no KV cache; build it with "
            "transformer_lm(..., decode_cache_length=N)")
    return min(caps)


class DecodeStepper:
    """Step-granular decode entry point for a `transformer_lm` graph — the
    seam the serving tier's continuous-batching scheduler drives.

    `generate_lm_batch` advances B sequences in lockstep from prompt to
    finish: a new request must wait for the whole batch to drain. This
    class instead owns a fixed-width batch of `slots` whose per-slot KV
    caches and cursors live in ONE batched rnn-state overlay ([slots]
    int32 cursor vectors — the vector-`kv_pos` path in
    `nn/layers/attention.py` / `nn/layers/feedforward.py`), so sequences
    at DIFFERENT depths decode in the same single dispatch and a finished
    slot is recycled at the next step boundary:

    - `prefill(ids, pad_to)` runs one prompt through a fresh batch-1
      forward (right-padded to `pad_to`, a warmable shape bucket) and
      returns the next-token distribution plus the slot's primed cache;
    - `install(slot, slot_state, length)` scatters that cache into the
      batched overlay;
    - `step(tokens)` advances ALL slots one token in one jitted dispatch
      ([slots, V] distributions out); free slots ride along on a dummy
      token and are masked by their own cursors;
    - `clear(slot)` retires a sequence (cursor back to 0; its stale cache
      rows are never attended and are overwritten by the next occupant).

    Both entry points go through `cg._get_jit`, so every shape is served
    from (and warmed into) the AOT executable store like any other
    program.
    """

    def __init__(self, cg, slots: int, context=None):
        import jax

        if slots < 1:
            raise ValueError("need at least one decode slot")
        self.cg = cg
        self.slots = int(slots)
        self.capacity = decode_cache_capacity(cg)
        self._declared = cg._declared_state()
        self._state = None  # batched rnn overlay; allocated on first install
        self._rng0 = jax.random.PRNGKey(0)
        # Tensor-parallel serving (`PERF.md §28`): a ParallelContext whose
        # model axis the caller already sharded `cg.params_tree` over
        # (`parallel/mesh.shard_params`). Every prefill/step dispatch runs
        # inside it, so the jit cache + AOT fingerprints key the sharded
        # program distinctly and the traced layers see the mesh. The
        # dispatch inputs carry explicit NamedShardings (params from
        # shard_params, KV overlay from `_alloc`), so one decode step
        # compiles to ONE GSPMD program with XLA-inserted collectives.
        self.context = context
        # Multi-tenant serving (serving/scheduler.py): an adapter-merged
        # params tree substituted for `cg.params_tree` on the next
        # dispatches. Params are jit ARGUMENTS, not statics, so swapping
        # trees of the same structure re-uses the compiled program —
        # zero serving-path compiles on adapter switches.
        self.params_override = None

    def set_params(self, params_tree) -> None:
        """Route subsequent prefill/step dispatches through `params_tree`
        (None restores the graph's own params)."""
        self.params_override = params_tree

    def _params(self):
        return (self.cg.params_tree if self.params_override is None
                else self.params_override)

    def _in_context(self):
        """Context manager active around every jitted dispatch: installs
        the stepper's ParallelContext (no-op wrapper when unsharded, so an
        externally-installed context is left alone)."""
        from contextlib import nullcontext

        from deeplearning4j_tpu.parallel.context import parallel_context

        return (parallel_context(self.context) if self.context is not None
                else nullcontext())

    # -- prompt path ------------------------------------------------------

    def prefill(self, ids, pad_to: int = None):
        """Prime one sequence from scratch. `ids` is a 1-D int prompt;
        `pad_to` right-pads the forward to a bucketed length (causal
        attention: the distribution at the last REAL position never sees
        the pad tail, and the tail's stale cache rows sit beyond the
        rewound cursor, masked until overwritten). Returns
        `(probs [V], slot_state, length)`."""
        import numpy as np
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn import rnn_state as rnn_mod

        ids = [int(i) for i in ids]
        n = len(ids)
        if not n:
            raise ValueError("need at least one prompt token")
        pad_to = int(pad_to or n)
        if pad_to < n:
            raise ValueError(f"pad_to ({pad_to}) < prompt length ({n})")
        if pad_to > self.capacity:
            raise ValueError(
                f"prompt bucket {pad_to} (prompt length {n}) exceeds the "
                f"decode cache capacity {self.capacity}")
        x = np.zeros((1, pad_to, 1), np.float32)
        x[0, :n, 0] = ids
        with self._in_context():
            fn = self.cg._get_jit("output", train=False, keep_rnn_state=True)
            outs, new_state = fn(self._params(), self.cg.state,
                                 [jnp.asarray(x)], None, self._rng0)
        rnn = rnn_mod.split_rnn_state(new_state, self._declared)
        # Rewind every cursor from pad_to to the real length.
        rnn = {layer: {k: (jnp.int32(n) if jnp.ndim(v) == 0 else v)
                       for k, v in s.items()}
               for layer, s in rnn.items()}
        probs = np.asarray(outs[0])[0, n - 1]
        return probs, rnn, n

    # -- slot management --------------------------------------------------

    def _alloc(self, template):
        import jax.numpy as jnp

        self._state = {
            layer: {k: jnp.zeros((self.slots,), jnp.int32)
                    if jnp.ndim(v) == 0
                    else jnp.zeros((self.slots,) + v.shape[1:], v.dtype)
                    for k, v in s.items()}
            for layer, s in template.items()
        }

    def install(self, slot: int, slot_state, length: int):
        """Scatter a primed batch-1 cache into the batched overlay."""
        import jax.numpy as jnp

        if self._state is None:
            self._alloc(slot_state)
        for layer, s in slot_state.items():
            dst = self._state[layer]
            for k, v in s.items():
                if jnp.ndim(v) == 0:
                    dst[k] = dst[k].at[slot].set(jnp.int32(length))
                else:
                    dst[k] = dst[k].at[slot].set(v[0])

    def clear(self, slot: int):
        """Retire a slot: cursor to 0 so the next occupant's writes start
        at row 0 and stale rows are never visible."""
        import jax.numpy as jnp

        if self._state is None:
            return
        for s in self._state.values():
            for k, v in s.items():
                if v.ndim == 1 and jnp.issubdtype(v.dtype, jnp.integer):
                    s[k] = v.at[slot].set(0)

    def warm_page_copies(self):
        """Compile any lazily-dispatched page-maintenance ops before
        traffic. The dense stepper has none; the paged stepper overrides
        this with a self-copy that traces the CoW append path."""

    # -- decode path ------------------------------------------------------

    def _before_dispatch(self, t: int):
        """Hook run before every decode dispatch with the step width.
        The paged stepper allocates/CoWs pool pages here."""

    def _dispatch(self, x):
        """One jitted decode dispatch: x is [slots, T, 1] token ids.
        Returns [slots, T, V] distributions (one per fed token)."""
        import numpy as np
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn import rnn_state as rnn_mod

        if self._state is None:
            raise RuntimeError("no sequence installed; call prefill/install")
        with self._in_context():
            fn = self.cg._get_jit("output", train=False, keep_rnn_state=True)
            state = rnn_mod.merge_rnn_state(self.cg.state, self._state)
            outs, new_state = fn(self._params(), state,
                                 [jnp.asarray(x)], None, self._rng0)
        self._state = rnn_mod.split_rnn_state(new_state, self._declared)
        out = np.asarray(outs[0])
        return out if out.ndim == 3 else out[:, None, :]

    def step(self, tokens):
        """Advance every slot one token. `tokens` is [slots] ints (free
        slots take any dummy value). Returns [slots, V] next-token
        distributions."""
        import numpy as np

        x = np.asarray(tokens, np.float32).reshape(self.slots, 1, 1)
        self._before_dispatch(1)
        return self._dispatch(x)[:, -1]

    def step_k(self, tokens):
        """Advance every slot T tokens in ONE dispatch — the speculative
        verify shape: `tokens` is [slots, T] ints and the return is
        [slots, T, V], the distribution AFTER each fed token (row j is
        conditioned on tokens[:, :j+1]). Rows whose later tokens turn out
        wrong are discarded by `rewind_all`; their cache rows sit beyond
        the rewound cursor, masked until overwritten."""
        import numpy as np

        tok = np.asarray(tokens)
        if tok.ndim != 2 or tok.shape[0] != self.slots:
            raise ValueError(
                f"tokens must be [slots={self.slots}, T]; got {tok.shape}")
        x = tok.astype(np.float32)[:, :, None]
        self._before_dispatch(tok.shape[1])
        return self._dispatch(x)

    def rewind_all(self, lengths):
        """Set EVERY slot's cursors (KV + positional) to `lengths[slot]`
        in one batched update per layer — the speculative-decoding
        truncation after a verify step: rejected rows stay in the cache
        beyond the cursor, masked until the next append overwrites them."""
        import numpy as np
        import jax.numpy as jnp

        if self._state is None:
            return
        cur = jnp.asarray(np.asarray(lengths, np.int32).reshape(self.slots))
        for s in self._state.values():
            for k, v in s.items():
                if v.ndim == 1 and jnp.issubdtype(v.dtype, jnp.integer):
                    s[k] = cur


class PagedDecodeStepper(DecodeStepper):
    """`DecodeStepper` over a paged KV pool (vLLM-style PagedAttention).

    Same contract as the dense stepper — `prefill` / `install` / `step` /
    `step_k` / `clear` — but the per-slot [capacity] KV rows are replaced
    by fixed-size pages from one shared `models.kv_pool.KVPagePool`:

    - every attention layer's overlay holds `k_pages`/`v_pages`
      ([pages, page_size, H, D]) plus the [slots] `kv_pos` cursors; the
      int32 page table ([slots, pages_per_seq], host-authoritative in the
      pool) is shipped as ONE device array shared by all layers before
      each dispatch;
    - `install` allocates pages for the prefilled prompt and scatters the
      dense batch-1 cache into them (the prefill program itself is
      unchanged — same warmable buckets);
    - `install_shared` points a slot at already-resident pages (prefix
      cache hit): +1 ref per page, cursor writes only, zero dispatches;
    - `_before_dispatch` advances the pool (page allocation + CoW of
      shared pages in the write range) and applies the planned page
      copies on device, so the in-jit scatter never collides.

    HBM: dense pins `slots * capacity` rows/layer; the pool holds
    `pages * page_size` rows/layer where shared prefixes are resident
    ONCE — the bench's slots-at-equal-HBM multiplier.
    """

    def __init__(self, cg, slots: int, page_size: int = 64,
                 pages: int = None, context=None):
        from deeplearning4j_tpu.models.kv_pool import KVPagePool

        super().__init__(cg, slots, context=context)
        self.pool = KVPagePool(slots=self.slots, capacity=self.capacity,
                               page_size=page_size, pages=pages)
        self.page_size = self.pool.page_size
        self._attn_layers = None  # discovered from the first template
        # Folded into the AOT fingerprint document
        # (compilation/store.py::build_fingerprint_doc) so warmup ships
        # the real paged program, never a dense-geometry executable.
        cg._decode_pool_geometry = {
            "kv": "paged", "page_size": self.page_size,
            "pages": self.pool.num_pages, "slots": self.slots,
        }

    def _page_sharding(self, n_heads: int):
        """NamedSharding for `[pages, page_size, H, Dh]` storage under the
        stepper's context, or None when unsharded (no context/model axis,
        or heads don't divide the axis — then pages replicate, exactly
        like the misaligned layer's params)."""
        ctx = self.context
        if ctx is None or ctx.model_axis is None:
            return None
        n = ctx.axis_size("model")
        if n <= 1 or n_heads % n:
            return None
        from deeplearning4j_tpu.parallel import mesh as mesh_mod

        return mesh_mod.kv_page_sharding(ctx.mesh, 4, ctx.model_axis)

    def _alloc(self, template):
        import jax
        import jax.numpy as jnp

        page, P = self.page_size, self.pool.num_pages
        self._state, self._attn_layers = {}, []
        repl = None
        if self.context is not None:
            from deeplearning4j_tpu.parallel import mesh as mesh_mod

            repl = mesh_mod.replicated(self.context.mesh)

        def put(a, sharding):
            # Explicit placement is the GSPMD in-spec: page storage
            # partitions on the head dim, cursors/tables replicate, and
            # the jitted step inherits the layout (computation follows
            # data). Unsharded steppers keep plain uncommitted arrays.
            if sharding is not None:
                return jax.device_put(a, sharding)
            return a if repl is None else jax.device_put(a, repl)

        for layer, s in template.items():
            if "k_cache" in s:
                k, v = s["k_cache"], s["v_cache"]
                ps = self._page_sharding(k.shape[2])
                self._state[layer] = {
                    "k_pages": put(
                        jnp.zeros((P, page) + k.shape[2:], k.dtype), ps),
                    "v_pages": put(
                        jnp.zeros((P, page) + v.shape[2:], v.dtype), ps),
                    "kv_pos": put(
                        jnp.zeros((self.slots,), jnp.int32), None),
                }
                self._attn_layers.append(layer)
            else:
                self._state[layer] = {
                    kk: put(jnp.zeros((self.slots,), jnp.int32), None)
                    if jnp.ndim(vv) == 0
                    else put(jnp.zeros((self.slots,) + vv.shape[1:],
                                       vv.dtype), None)
                    for kk, vv in s.items()
                }

    def install(self, slot: int, slot_state, length: int):
        """Allocate pages for a freshly-prefilled prompt and scatter its
        dense batch-1 cache into them. The tail page's rows beyond
        `length` carry prefill-pad garbage — masked until overwritten."""
        import numpy as np
        import jax.numpy as jnp

        if self._state is None:
            self._alloc(slot_state)
        pages = self.pool.install_slot(slot, length)
        idx = jnp.asarray(np.asarray(pages, np.int32))
        page, npg = self.page_size, len(pages)
        for layer, s in slot_state.items():
            dst = self._state[layer]
            if "k_cache" in s:
                for src_k, dst_k in (("k_cache", "k_pages"),
                                     ("v_cache", "v_pages")):
                    blk = s[src_k][0, :npg * page].reshape(
                        (npg, page) + s[src_k].shape[2:])
                    dst[dst_k] = dst[dst_k].at[idx].set(blk)
                dst["kv_pos"] = dst["kv_pos"].at[slot].set(jnp.int32(length))
            else:
                for kk, vv in s.items():
                    if jnp.ndim(vv) == 0:
                        dst[kk] = dst[kk].at[slot].set(jnp.int32(length))
                    else:
                        dst[kk] = dst[kk].at[slot].set(vv[0])

    def install_shared(self, slot: int, pages, length: int):
        """Prefix-cache hit: point `slot` at resident pages (+1 ref each)
        and set its cursors — no prefill, no KV writes. The first
        divergent append CoWs the shared tail page (refcount >= 2)."""
        import jax.numpy as jnp

        if self._state is None:
            raise RuntimeError(
                "no paged state allocated yet; the first prompt must go "
                "through prefill/install")
        self.pool.install_shared(slot, pages, length)
        for s in self._state.values():
            for kk, vv in s.items():
                if vv.ndim == 1 and jnp.issubdtype(vv.dtype, jnp.integer):
                    s[kk] = vv.at[slot].set(jnp.int32(length))

    def clear(self, slot: int):
        self.pool.free_slot(slot)
        super().clear(slot)

    def warm_page_copies(self):
        """Trace the CoW page copy (`k_pages[src]` gather + `.at[dst]`
        scatter) with a page-0 self-copy. A prefix-cache hit's first
        divergent append runs these exact eager ops in `_before_dispatch`;
        without this they compile mid-decode on the first shared-page
        write, which breaks the zero-compiles-after-warmup guarantee."""
        import numpy as np
        import jax.numpy as jnp

        if self._state is None:
            return
        idx = jnp.asarray(np.asarray([0], np.int32))
        for layer in self._attn_layers:
            s = self._state[layer]
            s["k_pages"] = s["k_pages"].at[idx].set(s["k_pages"][idx])
            s["v_pages"] = s["v_pages"].at[idx].set(s["v_pages"][idx])

    def rewind_all(self, lengths):
        import numpy as np

        for slot, n in enumerate(np.asarray(lengths).reshape(self.slots)):
            self.pool.rewind(slot, int(n))
        super().rewind_all(lengths)

    def _before_dispatch(self, t: int):
        """Advance the pool by `t` tokens for every tracked slot, apply
        the planned CoW page copies on device, and refresh the device
        page table (one array shared by every attention layer)."""
        import numpy as np
        import jax.numpy as jnp

        copies = self.pool.plan_appends(t)
        # One width-1 copy per CoW'd page, not one width-N batch: how many
        # slots diverge in the same round is scheduling-dependent, and each
        # distinct N would trace a fresh gather/scatter shape mid-decode.
        # Width 1 reuses the program `warm_page_copies` compiled.
        for src_page, dst_page in copies:
            src = jnp.asarray(np.asarray([src_page], np.int32))
            dst = jnp.asarray(np.asarray([dst_page], np.int32))
            for layer in self._attn_layers:
                s = self._state[layer]
                s["k_pages"] = s["k_pages"].at[dst].set(s["k_pages"][src])
                s["v_pages"] = s["v_pages"].at[dst].set(s["v_pages"][src])
        pt = jnp.asarray(self.pool.table)
        if self.context is not None:
            import jax

            from deeplearning4j_tpu.parallel import mesh as mesh_mod

            # Host-authoritative table, replicated on every chip: the
            # paged gather/scatter indexes it shard-locally.
            pt = jax.device_put(pt, mesh_mod.replicated(self.context.mesh))
        for layer in self._attn_layers:
            self._state[layer]["page_table"] = pt
