"""Regression evaluation.

Equivalent of the reference's `eval/RegressionEvaluation.java`: per-column
MSE, MAE, RMSE, RSE, correlation R, and R^2, accumulated incrementally and
merge-able for distributed eval.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns: Optional[int] = None,
                 column_names: Optional[Sequence[str]] = None):
        self.column_names = list(column_names) if column_names else None
        self.n = n_columns or (len(column_names) if column_names else None)
        self._initialized = False

    def _ensure(self, n: int):
        if self._initialized:
            return
        self.n = self.n or n
        z = lambda: np.zeros(self.n, np.float64)
        self.count = z()
        self.sum_abs_err = z()
        self.sum_sq_err = z()
        self.sum_label = z()
        self.sum_label_sq = z()
        self.sum_pred = z()
        self.sum_pred_sq = z()
        self.sum_label_pred = z()
        self._initialized = True

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:  # [b,t,c] -> flatten time with mask
            keep = (np.asarray(mask).reshape(-1) > 0) if mask is not None else \
                np.ones(labels.shape[0] * labels.shape[1], bool)
            labels = labels.reshape(-1, labels.shape[-1])[keep]
            predictions = predictions.reshape(-1, predictions.shape[-1])[keep]
        self._ensure(labels.shape[-1])
        err = predictions - labels
        self.count += labels.shape[0]
        self.sum_abs_err += np.abs(err).sum(0)
        self.sum_sq_err += (err ** 2).sum(0)
        self.sum_label += labels.sum(0)
        self.sum_label_sq += (labels ** 2).sum(0)
        self.sum_pred += predictions.sum(0)
        self.sum_pred_sq += (predictions ** 2).sum(0)
        self.sum_label_pred += (labels * predictions).sum(0)

    def merge(self, other: "RegressionEvaluation"):
        if not getattr(other, "_initialized", False):
            return self
        if not self._initialized:
            self._ensure(other.n)
        for f in ("count", "sum_abs_err", "sum_sq_err", "sum_label", "sum_label_sq",
                  "sum_pred", "sum_pred_sq", "sum_label_pred"):
            setattr(self, f, getattr(self, f) + getattr(other, f))
        return self

    # ------------------------------------------------------------- metrics

    def mean_squared_error(self, col: int) -> float:
        return float(self.sum_sq_err[col] / self.count[col])

    def mean_absolute_error(self, col: int) -> float:
        return float(self.sum_abs_err[col] / self.count[col])

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def relative_squared_error(self, col: int) -> float:
        n = self.count[col]
        mean_label = self.sum_label[col] / n
        denom = self.sum_label_sq[col] - 2 * mean_label * self.sum_label[col] + n * mean_label ** 2
        return float(self.sum_sq_err[col] / denom) if denom else float("nan")

    def correlation_r2(self, col: int) -> float:
        """Pearson correlation coefficient R (reference naming quirk kept)."""
        n = self.count[col]
        num = n * self.sum_label_pred[col] - self.sum_label[col] * self.sum_pred[col]
        d1 = n * self.sum_label_sq[col] - self.sum_label[col] ** 2
        d2 = n * self.sum_pred_sq[col] - self.sum_pred[col] ** 2
        den = np.sqrt(d1 * d2)
        return float(num / den) if den else float("nan")

    def r_squared(self, col: int) -> float:
        return 1.0 - self.relative_squared_error(col)

    def average_mean_squared_error(self) -> float:
        return float(np.mean([self.mean_squared_error(c) for c in range(self.n)]))

    def average_mean_absolute_error(self) -> float:
        return float(np.mean([self.mean_absolute_error(c) for c in range(self.n)]))

    def average_root_mean_squared_error(self) -> float:
        return float(np.mean([self.root_mean_squared_error(c) for c in range(self.n)]))

    def stats(self) -> str:
        names = self.column_names or [f"col{c}" for c in range(self.n)]
        lines = [f"{'Column':<12}{'MSE':>12}{'MAE':>12}{'RMSE':>12}{'RSE':>12}{'R':>10}"]
        for c in range(self.n):
            lines.append(
                f"{names[c]:<12}{self.mean_squared_error(c):>12.5g}"
                f"{self.mean_absolute_error(c):>12.5g}"
                f"{self.root_mean_squared_error(c):>12.5g}"
                f"{self.relative_squared_error(c):>12.5g}"
                f"{self.correlation_r2(c):>10.4f}"
            )
        return "\n".join(lines)
