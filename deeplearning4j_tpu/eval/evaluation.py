"""Classification evaluation.

Equivalent of the reference's `eval/Evaluation.java:55,145` — accuracy,
precision, recall, F1 via a confusion matrix; top-N accuracy; merge-able for
distributed eval (reference `IEvaluation.merge`). Counts accumulate in host
numpy — evaluation is not on the hot path.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np


class ConfusionMatrix:
    """Dense confusion matrix (reference: `eval/ConfusionMatrix.java`)."""

    def __init__(self, num_classes: int):
        self.matrix = np.zeros((num_classes, num_classes), np.int64)

    def add(self, actual: int, predicted: int, count: int = 1):
        self.matrix[actual, predicted] += count

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def merge(self, other: "ConfusionMatrix"):
        self.matrix += other.matrix


class Evaluation:
    """Accumulating classification metrics (see module docstring)."""

    def __init__(self, num_classes: Optional[int] = None, top_n: int = 1,
                 labels: Optional[Sequence[str]] = None):
        self.num_classes = num_classes
        self.label_names = list(labels) if labels else None
        self.top_n = top_n
        self.confusion: Optional[ConfusionMatrix] = None
        self.top_n_correct = 0
        self.total = 0

    def _ensure(self, n: int):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = ConfusionMatrix(self.num_classes)

    def eval(self, labels, predictions, mask=None):
        """Accumulate a batch. labels/predictions: [b, c] or [b, t, c]
        (one-hot labels, probability predictions); mask: [b, t]. Integer
        class-id labels ([b] / [b, t], the sparse-label training format)
        are accepted and one-hot-expanded against the prediction width."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        sparse = (np.issubdtype(labels.dtype, np.integer)
                  and labels.ndim == predictions.ndim - 1)
        if sparse:
            # Ids ARE the argmax — no one-hot expansion (np.eye(V) is V x V,
            # 10 GB at V=50k, the regime sparse labels exist for). Range-
            # check loudly: the jitted training path clamps silently.
            C = predictions.shape[-1]
            if labels.size and (labels.min() < 0 or labels.max() >= C):
                raise ValueError(
                    f"class ids must be in [0, {C}); got "
                    f"[{labels.min()}, {labels.max()}]")
        if predictions.ndim == 3:
            if mask is not None:
                keep = np.asarray(mask).reshape(-1) > 0
            else:
                keep = np.ones(predictions.shape[0] * predictions.shape[1],
                               bool)
            labels = (labels.reshape(-1)[keep] if sparse
                      else labels.reshape(-1, labels.shape[-1])[keep])
            predictions = predictions.reshape(-1, predictions.shape[-1])[keep]
        elif mask is not None:
            # Per-example mask on 2-D labels (e.g. padded batches): drop
            # masked rows instead of silently counting them.
            keep = np.asarray(mask).reshape(-1) > 0
            labels = labels[keep]
            predictions = predictions[keep]
        self._ensure(predictions.shape[-1])
        actual = labels.astype(np.int64) if sparse \
            else np.argmax(labels, axis=-1)
        pred = np.argmax(predictions, axis=-1)
        for a, p in zip(actual, pred):
            self.confusion.add(int(a), int(p))
        self.total += len(actual)
        if self.top_n > 1:
            top = np.argsort(-predictions, axis=-1)[:, : self.top_n]
            self.top_n_correct += int(np.sum(top == actual[:, None]))
        else:
            self.top_n_correct += int(np.sum(actual == pred))

    # ------------------------------------------------------------- metrics

    def _tp(self, c) -> int:
        return self.confusion.get_count(c, c)

    def _fp(self, c) -> int:
        return int(self.confusion.matrix[:, c].sum() - self._tp(c))

    def _fn(self, c) -> int:
        return int(self.confusion.matrix[c, :].sum() - self._tp(c))

    def accuracy(self) -> float:
        if self.total == 0:
            return 0.0
        return float(np.trace(self.confusion.matrix)) / self.total

    def top_n_accuracy(self) -> float:
        return self.top_n_correct / self.total if self.total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self._tp(cls) + self._fp(cls)
            return self._tp(cls) / denom if denom else 0.0
        vals = [self.precision(c) for c in range(self.num_classes)]
        return float(np.mean(vals))

    def recall(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            denom = self._tp(cls) + self._fn(cls)
            return self._tp(cls) / denom if denom else 0.0
        vals = [self.recall(c) for c in range(self.num_classes)]
        return float(np.mean(vals))

    def f1(self, cls: Optional[int] = None) -> float:
        p, r = self.precision(cls), self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def false_positive_rate(self, cls: int) -> float:
        tn = self.total - self._tp(cls) - self._fp(cls) - self._fn(cls)
        denom = self._fp(cls) + tn
        return self._fp(cls) / denom if denom else 0.0

    def add_counts(self, conf_matrix, top_n_correct: float, total: float):
        """Accumulate pre-computed batch counts (the device-side sharded
        evaluation path, `parallel/evaluation.py`): conf_matrix [C, C]
        rows=actual, cols=predicted."""
        conf_matrix = np.asarray(conf_matrix)
        self._ensure(conf_matrix.shape[0])
        self.confusion.matrix += conf_matrix.astype(np.int64)
        self.top_n_correct += int(round(top_n_correct))
        self.total += int(round(total))
        return self

    def merge(self, other: "Evaluation"):
        """Merge another evaluation (distributed eval, reference `IEvaluation.merge`)."""
        if other.confusion is None:
            return self
        if self.confusion is None:
            self.num_classes = other.num_classes
            self.confusion = ConfusionMatrix(other.num_classes)
        self.confusion.merge(other.confusion)
        self.total += other.total
        self.top_n_correct += other.top_n_correct
        return self

    def stats(self) -> str:
        name = lambda c: (self.label_names[c] if self.label_names else str(c))
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:  {self.num_classes}",
            f" Examples:      {self.total}",
            f" Accuracy:      {self.accuracy():.4f}",
            f" Precision:     {self.precision():.4f}",
            f" Recall:        {self.recall():.4f}",
            f" F1 Score:      {self.f1():.4f}",
        ]
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} acc:   {self.top_n_accuracy():.4f}")
        lines.append("==================================================================")
        return "\n".join(lines)
