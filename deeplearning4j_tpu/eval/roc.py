"""ROC evaluation.

Equivalent of the reference's `eval/ROC.java:34-46` (thresholded binary ROC:
`thresholdSteps` fixed thresholds, accumulated TP/FP/TN/FN counts, AUC by
trapezoidal integration) and `ROCMultiClass.java` (one-vs-all per class).
Thresholded accumulation keeps memory O(steps), merge-able for distributed
eval, exactly like the reference.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


class ROC:
    """Binary ROC/AUC (positive class = column 1 of 2-col labels, or a single
    probability column)."""

    def __init__(self, threshold_steps: int = 30):
        self.threshold_steps = int(threshold_steps)
        self.thresholds = np.linspace(0.0, 1.0, threshold_steps + 1)
        self.tp = np.zeros(threshold_steps + 1, np.int64)
        self.fp = np.zeros(threshold_steps + 1, np.int64)
        self.total_pos = 0
        self.total_neg = 0

    def eval(self, labels, predictions):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 2 and labels.shape[1] == 2:
            pos = labels[:, 1] > 0.5
            prob = predictions[:, 1]
        else:
            pos = labels.reshape(-1) > 0.5
            prob = predictions.reshape(-1)
        self.total_pos += int(pos.sum())
        self.total_neg += int((~pos).sum())
        # predicted positive at threshold t: prob > t (reference semantics)
        above = prob[None, :] > self.thresholds[:, None]
        self.tp += (above & pos[None, :]).sum(1)
        self.fp += (above & ~pos[None, :]).sum(1)

    def get_roc_curve(self) -> List[Tuple[float, float, float]]:
        """[(threshold, fpr, tpr)] sorted by threshold."""
        out = []
        for i, t in enumerate(self.thresholds):
            tpr = self.tp[i] / self.total_pos if self.total_pos else 0.0
            fpr = self.fp[i] / self.total_neg if self.total_neg else 0.0
            out.append((float(t), float(fpr), float(tpr)))
        return out

    def calculate_auc(self) -> float:
        curve = self.get_roc_curve()
        pts = sorted([(fpr, tpr) for _, fpr, tpr in curve]) + [(1.0, 1.0)]
        pts = [(0.0, 0.0)] + pts
        auc = 0.0
        for (x0, y0), (x1, y1) in zip(pts[:-1], pts[1:]):
            auc += (x1 - x0) * (y0 + y1) / 2.0
        return float(auc)

    def merge(self, other: "ROC"):
        if other.threshold_steps != self.threshold_steps:
            raise ValueError("Cannot merge ROC with different threshold steps")
        self.tp += other.tp
        self.fp += other.fp
        self.total_pos += other.total_pos
        self.total_neg += other.total_neg
        return self


class ROCMultiClass:
    """One-vs-all ROC per class (reference: `eval/ROCMultiClass.java`)."""

    def __init__(self, threshold_steps: int = 30):
        self.threshold_steps = threshold_steps
        self._rocs: Dict[int, ROC] = {}

    def eval(self, labels, predictions):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        for c in range(labels.shape[1]):
            roc = self._rocs.setdefault(c, ROC(self.threshold_steps))
            roc.eval(labels[:, c], predictions[:, c])

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs.values()]))

    def merge(self, other: "ROCMultiClass"):
        for c, roc in other._rocs.items():
            if c in self._rocs:
                self._rocs[c].merge(roc)
            else:
                self._rocs[c] = roc
        return self


class EvaluationBinary:
    """Per-output binary metrics for multi-label outputs (reference:
    `eval/EvaluationBinary.java`): counts at threshold 0.5 per column."""

    def __init__(self, n_columns: Optional[int] = None):
        self.n = n_columns
        self._initialized = False

    def _ensure(self, n):
        if self._initialized:
            return
        self.n = self.n or n
        self.tp = np.zeros(self.n, np.int64)
        self.fp = np.zeros(self.n, np.int64)
        self.tn = np.zeros(self.n, np.int64)
        self.fn = np.zeros(self.n, np.int64)
        self._initialized = True

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels) > 0.5
        preds = np.asarray(predictions) > 0.5
        self._ensure(labels.shape[-1])
        if mask is not None:
            m = np.asarray(mask) > 0
            if m.ndim < labels.ndim:
                m = m[..., None]
            valid = np.broadcast_to(m, labels.shape)
        else:
            valid = np.ones_like(labels, bool)
        labels = labels.reshape(-1, self.n)
        preds = preds.reshape(-1, self.n)
        valid = valid.reshape(-1, self.n)
        self.tp += (valid & labels & preds).sum(0)
        self.fp += (valid & ~labels & preds).sum(0)
        self.tn += (valid & ~labels & ~preds).sum(0)
        self.fn += (valid & labels & ~preds).sum(0)

    def accuracy(self, col: int) -> float:
        tot = self.tp[col] + self.fp[col] + self.tn[col] + self.fn[col]
        return float((self.tp[col] + self.tn[col]) / tot) if tot else 0.0

    def precision(self, col: int) -> float:
        d = self.tp[col] + self.fp[col]
        return float(self.tp[col] / d) if d else 0.0

    def recall(self, col: int) -> float:
        d = self.tp[col] + self.fn[col]
        return float(self.tp[col] / d) if d else 0.0

    def f1(self, col: int) -> float:
        p, r = self.precision(col), self.recall(col)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def merge(self, other: "EvaluationBinary"):
        if not getattr(other, "_initialized", False):
            return self
        if not self._initialized:
            self._ensure(other.n)
        self.tp += other.tp
        self.fp += other.fp
        self.tn += other.tn
        self.fn += other.fn
        return self
