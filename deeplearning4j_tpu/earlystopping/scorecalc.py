"""Score calculators (reference: `earlystopping/scorecalc/DataSetLossCalculator`)."""

from __future__ import annotations

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetLossCalculator:
    """Average loss over an iterator/DataSet, optionally averaged per batch."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, net) -> float:
        it = self.iterator
        if hasattr(it, "reset"):
            it.reset()
        if isinstance(it, DataSet):
            return net.score(it)
        total, batches, examples = 0.0, 0, 0
        for ds in it:
            n = ds.num_examples()
            total += net.score(ds) * n
            batches += 1
            examples += n
        if examples == 0:
            return float("nan")
        return total / examples if self.average else total
