"""Early stopping configuration + result (reference:
`earlystopping/EarlyStoppingConfiguration.java`, `EarlyStoppingResult.java`)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class EarlyStoppingConfiguration:
    score_calculator: Any = None
    model_saver: Any = None
    epoch_termination_conditions: List[Any] = field(default_factory=list)
    iteration_termination_conditions: List[Any] = field(default_factory=list)
    save_last_model: bool = False
    evaluate_every_n_epochs: int = 1

    class Builder:
        def __init__(self):
            self._c = EarlyStoppingConfiguration()

        def score_calculator(self, sc):
            self._c.score_calculator = sc
            return self

        def model_saver(self, saver):
            self._c.model_saver = saver
            return self

        def epoch_termination_conditions(self, *conds):
            self._c.epoch_termination_conditions = list(conds)
            return self

        def iteration_termination_conditions(self, *conds):
            self._c.iteration_termination_conditions = list(conds)
            return self

        def save_last_model(self, v=True):
            self._c.save_last_model = bool(v)
            return self

        def evaluate_every_n_epochs(self, n):
            self._c.evaluate_every_n_epochs = int(n)
            return self

        def build(self):
            return self._c

    @staticmethod
    def builder() -> "EarlyStoppingConfiguration.Builder":
        return EarlyStoppingConfiguration.Builder()


@dataclass
class EarlyStoppingResult:
    termination_reason: str = ""
    termination_details: str = ""
    score_vs_epoch: Dict[int, float] = field(default_factory=dict)
    best_model_epoch: int = -1
    best_model_score: float = float("inf")
    total_epochs: int = 0
    best_model: Any = None
