"""Early stopping.

Equivalent of the reference's `earlystopping/` package: configuration,
epoch/iteration termination conditions (`earlystopping/termination/`), score
calculators, model savers (`earlystopping/saver/`), and the trainer loop
(`trainer/BaseEarlyStoppingTrainer.java:76-100`).
"""

from deeplearning4j_tpu.earlystopping.config import (  # noqa: F401
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
)
from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer  # noqa: F401
from deeplearning4j_tpu.earlystopping.termination import (  # noqa: F401
    BestScoreEpochTerminationCondition,
    InvalidScoreIterationTerminationCondition,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.earlystopping.saver import (  # noqa: F401
    InMemoryModelSaver,
    LocalFileModelSaver,
)
from deeplearning4j_tpu.earlystopping.scorecalc import DataSetLossCalculator  # noqa: F401
