"""Model savers for early stopping (reference: `earlystopping/saver/` —
InMemoryModelSaver, LocalFileModelSaver / LocalFileGraphSaver)."""

from __future__ import annotations

import os


class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, net, score: float) -> None:
        self._best = (net.clone() if hasattr(net, "clone") else net, score)

    def save_latest_model(self, net, score: float) -> None:
        self._latest = (net.clone() if hasattr(net, "clone") else net, score)

    def get_best_model(self):
        return self._best[0] if self._best else None

    def get_latest_model(self):
        return self._latest[0] if self._latest else None


class LocalFileModelSaver:
    """Persist best/latest checkpoints via ModelSerializer zips
    (`format="zip"`) or sharded checkpoint directories (`format="sharded"`,
    per-shard chunk I/O + atomic COMMIT — `deeplearning4j_tpu/checkpoint/`).

    Both backends commit atomically: the ZIP path writes to `*.tmp` and
    `os.replace`s into place (a crash mid-save can't corrupt the previous
    `bestModel.zip`); the sharded store renames a fully-fsynced directory.
    """

    def __init__(self, directory: str, format: str = "zip"):
        if format not in ("zip", "sharded"):
            raise ValueError(f"format must be 'zip' or 'sharded', got {format!r}")
        self.directory = directory
        self.format = format
        os.makedirs(directory, exist_ok=True)

    def _path(self, name: str) -> str:
        ext = ".zip" if self.format == "zip" else ""
        return os.path.join(self.directory, name + ext)

    def _save(self, net, name: str) -> None:
        path = self._path(name)
        if self.format == "sharded":
            from deeplearning4j_tpu.checkpoint import save_checkpoint

            save_checkpoint(net, path)
            return
        from deeplearning4j_tpu.util.model_serializer import save_model

        tmp = path + ".tmp"
        save_model(net, tmp)
        os.replace(tmp, path)

    def _load(self, name: str):
        path = self._path(name)
        if self.format == "sharded":
            from deeplearning4j_tpu.checkpoint import (
                is_sharded_checkpoint,
                restore_checkpoint,
            )

            return restore_checkpoint(path) if is_sharded_checkpoint(path) else None
        from deeplearning4j_tpu.util.model_serializer import load_model

        return load_model(path) if os.path.exists(path) else None

    def save_best_model(self, net, score: float) -> None:
        self._save(net, "bestModel")

    def save_latest_model(self, net, score: float) -> None:
        self._save(net, "latestModel")

    def get_best_model(self):
        return self._load("bestModel")

    def get_latest_model(self):
        return self._load("latestModel")
