"""Model savers for early stopping (reference: `earlystopping/saver/` —
InMemoryModelSaver, LocalFileModelSaver / LocalFileGraphSaver)."""

from __future__ import annotations

import os
from typing import Optional


class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, net, score: float) -> None:
        self._best = (net.clone() if hasattr(net, "clone") else net, score)

    def save_latest_model(self, net, score: float) -> None:
        self._latest = (net.clone() if hasattr(net, "clone") else net, score)

    def get_best_model(self):
        return self._best[0] if self._best else None

    def get_latest_model(self):
        return self._latest[0] if self._latest else None


class LocalFileModelSaver:
    """Persist best/latest checkpoints via ModelSerializer zips."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def save_best_model(self, net, score: float) -> None:
        from deeplearning4j_tpu.util.model_serializer import save_model

        save_model(net, self._path("bestModel.zip"))

    def save_latest_model(self, net, score: float) -> None:
        from deeplearning4j_tpu.util.model_serializer import save_model

        save_model(net, self._path("latestModel.zip"))

    def get_best_model(self):
        from deeplearning4j_tpu.util.model_serializer import load_model

        path = self._path("bestModel.zip")
        return load_model(path) if os.path.exists(path) else None

    def get_latest_model(self):
        from deeplearning4j_tpu.util.model_serializer import load_model

        path = self._path("latestModel.zip")
        return load_model(path) if os.path.exists(path) else None
