"""Early stopping trainer.

Equivalent of the reference's `earlystopping/trainer/BaseEarlyStoppingTrainer.java:76-100`:
loop epochs over the training iterator, score with the calculator every N
epochs, save best model, stop on any termination condition.
"""

from __future__ import annotations

import math
from typing import Optional

from deeplearning4j_tpu.earlystopping.config import (
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
)


class EarlyStoppingTrainer:
    def __init__(self, config: EarlyStoppingConfiguration, net, train_iterator):
        self.config = config
        self.net = net
        self.train_iterator = train_iterator

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        result = EarlyStoppingResult()
        for cond in cfg.epoch_termination_conditions + cfg.iteration_termination_conditions:
            cond.initialize()

        epoch = 0
        while True:
            self.net.fit(self.train_iterator)
            result.total_epochs = epoch + 1

            # Iteration-level conditions checked on the train score after the
            # epoch (NaN/exploding-score guards, wall-clock budget).
            train_score = self.net.score_value
            iter_stop = None
            for cond in cfg.iteration_termination_conditions:
                if cond.terminate(train_score):
                    iter_stop = cond
                    break
            if iter_stop is not None:
                result.termination_reason = "IterationTerminationCondition"
                result.termination_details = type(iter_stop).__name__
                break

            if epoch % max(1, cfg.evaluate_every_n_epochs) == 0:
                score = (cfg.score_calculator.calculate_score(self.net)
                         if cfg.score_calculator else train_score)
                result.score_vs_epoch[epoch] = score
                if score < result.best_model_score:
                    result.best_model_score = score
                    result.best_model_epoch = epoch
                    if cfg.model_saver:
                        cfg.model_saver.save_best_model(self.net, score)
                last_score = score
            else:
                last_score = result.score_vs_epoch.get(
                    max(result.score_vs_epoch, default=0), train_score)
            if cfg.save_last_model and cfg.model_saver:
                cfg.model_saver.save_latest_model(self.net, last_score)

            # Epoch conditions run EVERY epoch (reference semantics), using
            # the most recent score for score-based conditions.
            epoch_stop = None
            for cond in cfg.epoch_termination_conditions:
                if cond.terminate(epoch, last_score):
                    epoch_stop = cond
                    break
            if epoch_stop is not None:
                result.termination_reason = "EpochTerminationCondition"
                result.termination_details = type(epoch_stop).__name__
                break
            epoch += 1

        if cfg.model_saver:
            result.best_model = cfg.model_saver.get_best_model()
        if result.best_model is None:
            result.best_model = self.net
        return result
