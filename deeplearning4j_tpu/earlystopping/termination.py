"""Termination conditions (reference: `earlystopping/termination/` — MaxEpochs,
BestScoreEpoch, ScoreImprovementEpoch, MaxTime, MaxScore, InvalidScore)."""

from __future__ import annotations

import math
import time


class EpochTerminationCondition:
    def initialize(self) -> None:
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self) -> None:
        pass

    def terminate(self, score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = int(max_epochs)

    def terminate(self, epoch: int, score: float) -> bool:
        return epoch + 1 >= self.max_epochs


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop as soon as the score is at or below a target value."""

    def __init__(self, best_expected_score: float):
        self.best_expected_score = float(best_expected_score)

    def terminate(self, epoch: int, score: float) -> bool:
        return score <= self.best_expected_score


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs with no (sufficient) improvement."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0):
        self.patience = int(max_epochs_without_improvement)
        self.min_improvement = float(min_improvement)
        self.best = math.inf
        self.since = 0

    def initialize(self) -> None:
        self.best = math.inf
        self.since = 0

    def terminate(self, epoch: int, score: float) -> bool:
        if score < self.best - self.min_improvement:
            self.best = score
            self.since = 0
            return False
        self.since += 1
        return self.since > self.patience


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = float(max_seconds)
        self._start = None

    def initialize(self) -> None:
        self._start = time.monotonic()

    def terminate(self, score: float) -> bool:
        if self._start is None:
            self._start = time.monotonic()
        return (time.monotonic() - self._start) > self.max_seconds


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Terminate if the score explodes above a bound."""

    def __init__(self, max_score: float):
        self.max_score = float(max_score)

    def terminate(self, score: float) -> bool:
        return score > self.max_score


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    def terminate(self, score: float) -> bool:
        return math.isnan(score) or math.isinf(score)
