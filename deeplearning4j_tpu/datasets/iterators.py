"""Dataset iterators.

Equivalent of the reference's `datasets/iterator/` infrastructure
(`AsyncDataSetIterator` background prefetch, `MultipleEpochsIterator`,
`SamplingDataSetIterator`, `IteratorDataSetIterator`, `ListDataSetIterator`,
`ExistingDataSetIterator`; SURVEY.md §2).

TPU-specific: `AsyncDataSetIterator` prefetches batches all the way to the
DEVICE (jax.device_put in a background thread), not just to host memory —
over a high-latency device transport this hides the transfer behind compute,
which is the role the reference's prefetch thread plays for disk I/O.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets import staging as _staging
from deeplearning4j_tpu.datasets.staging import (  # noqa: F401  (re-exports:
    # the transfer layer moved to datasets/staging.py in PR 11; engines,
    # wrapper, and tests historically import these from here)
    _TUPLE_PUT_MAX_BYTES,
    _drop_staged,
    _maybe_stage,
    _np_transfer_dtype,
    _stage_arrays,
    DeviceStager,
    stage_item,
    stage_to_device,
    transfer_cast,
)
from deeplearning4j_tpu import observability as _obs

_log = logging.getLogger(__name__)

_M_CACHE_BYTES = _obs.metrics.gauge(
    "dl4j_device_cache_bytes",
    "Bytes of training batches resident in HBM across "
    "DeviceCacheDataSetIterator caches")
_M_INPUT_WAIT = _obs.metrics.histogram(
    "dl4j_input_wait_seconds",
    "Host seconds blocked in iterator-next waiting for the next batch "
    "(input starvation; the device is idle while this accrues)",
    label_names=("source",)).labels(source="superstep")


def maybe_reset(iterator) -> bool:
    """Reset `iterator` if it supports it; returns whether reset() ran.

    Swallows only the "not resettable" case (no reset attribute /
    NotImplementedError — e.g. a one-shot generator wrapped in an adapter);
    an unexpected failure is LOGGED, not silently hidden, because a reset
    that half-ran can make the following epoch train on a partial stream.
    """
    reset = getattr(iterator, "reset", None)
    if reset is None:
        return False
    try:
        reset()
        return True
    except NotImplementedError:
        return False
    except Exception:
        _log.warning("%s.reset() failed unexpectedly; continuing without "
                     "reset", type(iterator).__name__, exc_info=True)
        return False


def fast_forward(iterator, n: int):
    """Reset `iterator` (when resettable) and skip its first `n` batches,
    returning an iterator positioned at batch `n` — the elastic-recovery
    data path: after restoring a checkpoint at step N, the restarted
    worker must see the SAME batch stream a never-interrupted run would
    see at step N, so recovery reproduces the uninterrupted run's
    numerics instead of re-training on replayed data.

    Skipped batches are drawn and discarded (deterministic iterators
    re-derive them; there is no general seek), so fast-forwarding a
    many-epoch stream costs host iteration time but no device work. A
    stream shorter than `n` yields an exhausted iterator — the caller's
    step loop then simply finds nothing left to train on.
    """
    maybe_reset(iterator)
    it = iter(iterator)
    for _ in range(max(0, int(n))):
        try:
            next(it)
        except StopIteration:
            break
    return it


class DataSetIterator:
    """Iterator protocol (reference: ND4J `DataSetIterator`)."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def batch_size(self) -> Optional[int]:
        return None

    def total_examples(self) -> Optional[int]:
        return None


class ListDataSetIterator(DataSetIterator):
    """Iterate a DataSet (or list of them) in minibatches (reference:
    `ListDataSetIterator.java`)."""

    def __init__(self, data, batch_size: int = 32, shuffle: bool = False,
                 seed: Optional[int] = None):
        if isinstance(data, DataSet):
            self._batches = data.batch_by(batch_size)
            self._source = data
        else:
            self._batches = list(data)
            self._source = None
        self._batch_size = batch_size
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)

    def __iter__(self):
        if self._shuffle and self._source is not None:
            idx = self._rng.permutation(self._source.num_examples())
            shuffled = DataSet(
                self._source.features[idx],
                None if self._source.labels is None else self._source.labels[idx],
                None if self._source.features_mask is None else self._source.features_mask[idx],
                None if self._source.labels_mask is None else self._source.labels_mask[idx],
            )
            return iter(shuffled.batch_by(self._batch_size))
        if self._shuffle:
            # List-of-DataSets source: shuffle the batch ORDER each epoch
            # (cross-batch example shuffling needs a single-DataSet source).
            order = self._rng.permutation(len(self._batches))
            return iter([self._batches[i] for i in order])
        return iter(self._batches)

    def batch_size(self):
        return self._batch_size

    def total_examples(self):
        return sum(b.num_examples() for b in self._batches)


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch to device (reference:
    `AsyncDataSetIterator.java` — the host-side I/O boundary of the fit()
    call stack, SURVEY.md §3.1).

    `device_prefetch=True` (default) runs the overlapped `DeviceStager`
    path: batches cross the host->device link on the worker thread while
    the consumer computes, with HBM backpressure from the staging byte
    budget. `device_prefetch=False` prefetches host-side only (the cast
    still applies; the consumer pays the transfer). Consumer-side queue
    waits are observed as `dl4j_input_wait_seconds{source="async"}`, so
    a prefetch queue running dry is visible, and worker stalls on the
    base iterator land in `dl4j_staging_wait_seconds`."""

    def __init__(self, base: Iterable, queue_size: int = 4, device_prefetch: bool = True,
                 transfer_dtype=None):
        self.base = base
        self.queue_size = max(1, int(queue_size))
        self.device_prefetch = device_prefetch
        self.transfer_dtype = transfer_dtype
        self._active: Optional[DeviceStager] = None

    @property
    def stages_to_device(self) -> bool:
        return bool(self.device_prefetch)

    def __iter__(self):
        prior = self._active
        if prior is not None:
            prior.close()  # one live worker per iterator; re-iter restarts
        stager = DeviceStager(
            self.base,
            depth=self.queue_size,
            transfer_dtype=self.transfer_dtype,
            device_stage=self.device_prefetch,
            engine="async" if self.device_prefetch else None,
            source="async",
        )
        self._active = stager
        return stager

    def reset(self):
        # Stop any live worker FIRST (it may still be draining the base;
        # resetting underneath it would interleave two epochs) and drop
        # its staged device buffers, then reset the base.
        stager = self._active
        if stager is not None:
            self._active = None
            stager.close()
        if hasattr(self.base, "reset"):
            self.base.reset()


class DeviceCacheDataSetIterator(DataSetIterator):
    """Stage every batch to DEVICE memory once, replay from HBM thereafter.

    TPU-native counterpart of the reference's `CachingDataSetIterator`
    (`deeplearning4j-core/.../datasets/iterator/CachingDataSetIterator.java`),
    which caches prepared DataSets host-side. On TPU the expensive boundary is
    the host->device link — on a serialized transport, transfers cannot
    overlap compute at all (measured: concurrent 38.5MB puts degrade 23ms ->
    800ms while slowing the train step 2.7x) — so the cache lives in HBM.
    Use for datasets that fit in device memory (MNIST/CIFAR scale); for
    streaming-scale data use AsyncDataSetIterator and accept the link cost.
    """

    stages_to_device = True  # replays device-resident batches

    def __init__(self, base: Iterable, max_bytes: Optional[int] = None,
                 transfer_dtype=None):
        self.base = base
        self.max_bytes = max_bytes
        self.transfer_dtype = transfer_dtype
        self._cache: Optional[List[DataSet]] = None
        self._cache_bytes = 0

    def _ds_bytes(self, ds: DataSet) -> int:
        return sum(
            np.asarray(a).nbytes
            for a in (ds.features, ds.labels, ds.features_mask, ds.labels_mask)
            if a is not None
        )

    def __iter__(self):
        if self._cache is None:
            staged, total = [], 0
            try:
                for ds in self.base:
                    ds = transfer_cast(ds, self.transfer_dtype)
                    total += self._ds_bytes(ds)
                    if self.max_bytes is not None and total > self.max_bytes:
                        raise MemoryError(
                            f"DeviceCacheDataSetIterator: dataset exceeds "
                            f"max_bytes={self.max_bytes}; use "
                            f"AsyncDataSetIterator for streaming-scale data"
                        )
                    staged.append(stage_to_device(ds))
            except BaseException:
                # Mid-staging failure (MemoryError budget, device OOM,
                # consumer interrupt): `_cache` stays None, so without
                # cleanup the partially staged batches would sit in HBM
                # until GC while the next attempt restages from scratch.
                _drop_staged(staged)
                raise
            self._cache = staged
            self._cache_bytes = total
            _M_CACHE_BYTES.inc(total)
        return iter(self._cache)

    def reset(self):
        pass  # cache replays; the base iterator is consumed exactly once

    def invalidate(self):
        """Drop the device cache (e.g. after the underlying data changed)."""
        self._cache = None
        _M_CACHE_BYTES.inc(-self._cache_bytes)
        self._cache_bytes = 0

    def total_examples(self):
        if self._cache is not None:
            return sum(d.num_examples() for d in self._cache)
        return None


class MultipleEpochsIterator(DataSetIterator):
    """Replay a base iterator N times (reference: `MultipleEpochsIterator.java`)."""

    def __init__(self, num_epochs: int, base: Iterable):
        self.num_epochs = int(num_epochs)
        self.base = base

    def __iter__(self):
        for _ in range(self.num_epochs):
            if hasattr(self.base, "reset"):
                self.base.reset()
            yield from self.base

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()


class SamplingDataSetIterator(DataSetIterator):
    """Sample batches with replacement (reference: `SamplingDataSetIterator.java`)."""

    def __init__(self, data: DataSet, batch_size: int, total_batches: int,
                 seed: Optional[int] = None):
        self.data = data
        self._batch_size = batch_size
        self.total_batches = total_batches
        self._rng = np.random.RandomState(seed)

    def __iter__(self):
        n = self.data.num_examples()
        for _ in range(self.total_batches):
            idx = self._rng.randint(0, n, self._batch_size)
            yield DataSet(
                self.data.features[idx],
                None if self.data.labels is None else self.data.labels[idx],
                None if self.data.features_mask is None else self.data.features_mask[idx],
                None if self.data.labels_mask is None else self.data.labels_mask[idx],
            )

    def batch_size(self):
        return self._batch_size


class ExistingDataSetIterator(DataSetIterator):
    """Wrap any python iterable of DataSets (reference: `ExistingDataSetIterator.java`)."""

    def __init__(self, iterable: Iterable):
        self._items = list(iterable)

    def __iter__(self):
        return iter(self._items)

    def total_examples(self):
        return sum(d.num_examples() for d in self._items)


class IteratorDataSetIterator(DataSetIterator):
    """Re-batch a stream of DataSets to a fixed batch size (reference:
    `IteratorDataSetIterator.java`)."""

    def __init__(self, base: Iterable, batch_size: int):
        self.base = base
        self._batch_size = batch_size

    def __iter__(self):
        buf: List[DataSet] = []
        count = 0
        for ds in self.base:
            buf.append(ds)
            count += ds.num_examples()
            while count >= self._batch_size:
                merged = DataSet.merge(buf)
                out, rest = merged.split_test_and_train(self._batch_size)
                yield out
                buf = [rest] if rest.num_examples() else []
                count = rest.num_examples()
        if buf:
            merged = DataSet.merge(buf)
            if merged.num_examples():
                yield merged

    def batch_size(self):
        return self._batch_size


# --------------------------------------------------------------- superstep
# Superstep training (PERF.md §13): K staged batches stacked into [K, B, ...]
# device arrays so ONE jitted dispatch runs K train iterations as a
# `lax.scan` over the leading axis. The containers below are what the
# engines' `_fit_dispatch` recognizes as "already K batches".


class Superbatch:
    """K same-shape DataSets stacked along a new leading axis.

    Field names match DataSet (features/labels/features_mask/labels_mask) so
    introspection-based consumers (`observability.host_nbytes`,
    `StepProfiler._host_nbytes`) keep working unchanged; each array is
    `[K, B, ...]` (masks `[K, B]` / `[K, B, T]`).
    """

    def __init__(self, features, labels=None, features_mask=None,
                 labels_mask=None, k: int = 1):
        self.features = features
        self.labels = labels
        self.features_mask = features_mask
        self.labels_mask = labels_mask
        self.k = int(k)

    def num_examples(self) -> int:
        return int(np.shape(self.features)[0] * np.shape(self.features)[1])


class MultiSuperbatch:
    """K same-shape MultiDataSets stacked along a new leading axis (the
    ComputationGraph twin of `Superbatch`; per-part lists of [K, B, ...])."""

    def __init__(self, features, labels, features_masks=None,
                 labels_masks=None, k: int = 1):
        self.features = list(features)
        self.labels = list(labels)
        self.features_masks = None if features_masks is None else list(features_masks)
        self.labels_masks = None if labels_masks is None else list(labels_masks)
        self.k = int(k)

    def num_examples(self) -> int:
        return int(np.shape(self.features[0])[0] * np.shape(self.features[0])[1])


def _part_sig(a) -> Optional[tuple]:
    if a is None:
        return None
    dtype = getattr(a, "dtype", None)  # device arrays: no host pull
    if dtype is None:
        dtype = np.asarray(a).dtype
    return (tuple(np.shape(a)), str(dtype))


def batch_signature(item) -> tuple:
    """Shape/dtype/mask-presence signature of one batch. Only CONSECUTIVE
    batches with identical signatures stack into a superbatch; a signature
    change flushes the current block (automatic per-batch fallback for
    heterogeneous streams — every distinct signature is its own program)."""
    if isinstance(item, MultiDataSet):
        return (
            "mds",
            tuple(_part_sig(a) for a in item.features),
            tuple(_part_sig(a) for a in item.labels),
            None if item.features_masks is None
            else tuple(_part_sig(a) for a in item.features_masks),
            None if item.labels_masks is None
            else tuple(_part_sig(a) for a in item.labels_masks),
        )
    return ("ds", _part_sig(item.features), _part_sig(item.labels),
            _part_sig(item.features_mask), _part_sig(item.labels_mask))


def batch_nbytes(item) -> int:
    """Total bytes of one batch's arrays (host or device)."""
    if isinstance(item, MultiDataSet):
        parts = list(item.features) + list(item.labels)
        for masks in (item.features_masks, item.labels_masks):
            if masks is not None:
                parts.extend(masks)
    else:
        parts = [item.features, item.labels, item.features_mask,
                 item.labels_mask]
    return sum(int(a.nbytes) if hasattr(a, "nbytes")
               else np.asarray(a).nbytes for a in parts if a is not None)


def _stack_parts(parts: Sequence) -> Optional[Any]:
    """Stack K same-shape parts along a new leading axis. Host parts stack
    host-side (staged afterwards in ONE transfer); device-resident parts
    (a DeviceCacheDataSetIterator replay) stack on device."""
    if parts[0] is None:
        return None
    if all(isinstance(p, np.ndarray) for p in parts):
        return np.stack(parts)
    import jax.numpy as jnp

    return jnp.stack([jnp.asarray(p) for p in parts])


def _maybe_stage(parts: List) -> List:
    """Stage the np members of a flat part list to device (one tuple-put
    when small, per-array puts when large — see `_stage_arrays`)."""
    np_idx = [i for i, p in enumerate(parts) if isinstance(p, np.ndarray)]
    if not np_idx:
        return parts
    staged = _stage_arrays([parts[i] for i in np_idx])
    out = list(parts)
    for i, s in zip(np_idx, staged):
        out[i] = s
    return out


def stack_superbatch(batches: Sequence, stage: bool = True):
    """Stack K same-signature batches into a Superbatch/MultiSuperbatch,
    optionally staging the stacked arrays to device in one transfer."""
    first = batches[0]
    k = len(batches)
    if isinstance(first, MultiDataSet):
        feats = [_stack_parts([b.features[i] for b in batches])
                 for i in range(len(first.features))]
        labs = [_stack_parts([b.labels[i] for b in batches])
                for i in range(len(first.labels))]
        fmasks = None if first.features_masks is None else [
            _stack_parts([b.features_masks[i] for b in batches])
            for i in range(len(first.features_masks))]
        lmasks = None if first.labels_masks is None else [
            _stack_parts([b.labels_masks[i] for b in batches])
            for i in range(len(first.labels_masks))]
        if stage:
            flat = feats + labs + (fmasks or []) + (lmasks or [])
            flat = _maybe_stage(flat)
            pos = 0
            for dst in (feats, labs, fmasks, lmasks):
                if dst is None:
                    continue
                dst[:] = flat[pos:pos + len(dst)]
                pos += len(dst)
        return MultiSuperbatch(feats, labs, fmasks, lmasks, k=k)
    parts = [
        _stack_parts([b.features for b in batches]),
        _stack_parts([b.labels for b in batches]),
        _stack_parts([b.features_mask for b in batches]),
        _stack_parts([b.labels_mask for b in batches]),
    ]
    if stage:
        parts = _maybe_stage(parts)
    return Superbatch(parts[0], parts[1], parts[2], parts[3], k=k)


class SuperbatchIterator(DataSetIterator):
    """Chunk any base iterator into K-blocks for superstep training.

    Consecutive same-signature batches are stacked into `[K, B, ...]`
    superbatches (see `stack_superbatch`); a signature change or the end of
    the stream flushes early, so the last `< K` batches form a TRUE-LENGTH
    tail block (no padding — the engines compile one extra program per
    distinct block length and the numerics match the per-batch loop
    exactly). Singleton blocks yield the ORIGINAL item, reusing the
    engine's per-batch program.

    Byte-budget aware: `max_bytes` (default from `DL4J_TPU_SUPERSTEP_BYTES`)
    caps a block's stacked size, lowering the effective K for large batches
    so the stacked superbatch never multiplies peak HBM unexpectedly.

    When the base is a `DeviceCacheDataSetIterator` the stacked device
    blocks are cached here too (keyed on the identity of the base's cache,
    so `invalidate()` propagates): cached epochs restack ONCE, not per
    epoch.
    """

    def __init__(self, base: Iterable, k: int,
                 max_bytes: Optional[int] = None, stage: bool = True,
                 cache: Optional[bool] = None,
                 transform: Optional[Callable] = None,
                 transfer_dtype=None, net=None):
        self.base = base
        self.k = max(1, int(k))
        if max_bytes is None:
            env = os.environ.get("DL4J_TPU_SUPERSTEP_BYTES")
            max_bytes = int(env) if env else None
        self.max_bytes = max_bytes
        self.stage = stage
        self.cache = (isinstance(base, DeviceCacheDataSetIterator)
                      if cache is None else bool(cache))
        self.transform = transform
        self.transfer_dtype = transfer_dtype
        self.net = net  # staging byte-budget context (measured_model_bytes)
        self._blocks: Optional[List] = None
        self._built_from: Any = None

    @property
    def stages_to_device(self) -> bool:
        return bool(self.stage)

    def _iter_blocks(self, stage: Optional[bool] = None) -> Iterator:
        if stage is None:
            stage = self.stage
        buf: List = []
        sig = None
        limit = self.k

        def flush():
            if len(buf) == 1:
                return buf[0]
            return stack_superbatch(buf, stage=stage)

        base_it = iter(self.base)
        while True:
            # Time the base iterator's next separately: when K batches
            # stack into one dispatch, the per-batch waits here are the
            # starvation the engine loop can no longer see.
            t_wait = time.perf_counter()
            try:
                item = next(base_it)
            except StopIteration:
                break
            _M_INPUT_WAIT.observe(time.perf_counter() - t_wait)
            if self.transform is not None:
                item = self.transform(item)
            if self.transfer_dtype is not None:
                # Cast BEFORE signature/stacking: the stacked superbatch is
                # staged in the reduced dtype, so one tuple-put moves half
                # the bytes (satellite of PERF.md §17; singleton fall-through
                # blocks get the same treatment since the cast happens here).
                item = transfer_cast(item, self.transfer_dtype)
            s = batch_signature(item)
            if buf and s != sig:
                yield flush()
                buf = []
            if not buf:
                sig = s
                limit = self.k
                if self.max_bytes is not None:
                    per = batch_nbytes(item)
                    if per > 0:
                        limit = max(1, min(self.k, self.max_bytes // per))
            buf.append(item)
            if len(buf) >= limit:
                yield flush()
                buf = []
        if buf:
            yield flush()

    def __iter__(self):
        if not self.cache:
            if self.stage and _staging.staging_enabled():
                # Stack blocks host-side on the stager thread and device-put
                # them there: the NEXT [K, B, ...] block crosses the link
                # while the current K-step scan runs. The cast already
                # happened in _iter_blocks, so the stager only puts.
                return DeviceStager(self._iter_blocks(stage=False),
                                    net=self.net, engine="superstep")
            return self._iter_blocks()
        base_cache = getattr(self.base, "_cache", None)
        if self._blocks is None or self._built_from is not base_cache:
            self._blocks = list(self._iter_blocks())
            # Captured AFTER iterating (a cold DeviceCache builds its cache
            # during the iteration above); identity mismatch on the next
            # epoch means the base was invalidated and restaged.
            self._built_from = getattr(self.base, "_cache", None)
        return iter(self._blocks)

    def reset(self):
        maybe_reset(self.base)

    def batch_size(self):
        bs = getattr(self.base, "batch_size", None)
        return bs() if callable(bs) else None

    def total_examples(self):
        te = getattr(self.base, "total_examples", None)
        return te() if callable(te) else None
