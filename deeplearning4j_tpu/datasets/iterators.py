"""Dataset iterators.

Equivalent of the reference's `datasets/iterator/` infrastructure
(`AsyncDataSetIterator` background prefetch, `MultipleEpochsIterator`,
`SamplingDataSetIterator`, `IteratorDataSetIterator`, `ListDataSetIterator`,
`ExistingDataSetIterator`; SURVEY.md §2).

TPU-specific: `AsyncDataSetIterator` prefetches batches all the way to the
DEVICE (jax.device_put in a background thread), not just to host memory —
over a high-latency device transport this hides the transfer behind compute,
which is the role the reference's prefetch thread plays for disk I/O.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Iterator protocol (reference: ND4J `DataSetIterator`)."""

    def __iter__(self) -> Iterator[DataSet]:
        raise NotImplementedError

    def reset(self) -> None:
        pass

    def batch_size(self) -> Optional[int]:
        return None

    def total_examples(self) -> Optional[int]:
        return None


class ListDataSetIterator(DataSetIterator):
    """Iterate a DataSet (or list of them) in minibatches (reference:
    `ListDataSetIterator.java`)."""

    def __init__(self, data, batch_size: int = 32, shuffle: bool = False,
                 seed: Optional[int] = None):
        if isinstance(data, DataSet):
            self._batches = data.batch_by(batch_size)
            self._source = data
        else:
            self._batches = list(data)
            self._source = None
        self._batch_size = batch_size
        self._shuffle = shuffle
        self._rng = np.random.RandomState(seed)

    def __iter__(self):
        if self._shuffle and self._source is not None:
            idx = self._rng.permutation(self._source.num_examples())
            shuffled = DataSet(
                self._source.features[idx],
                None if self._source.labels is None else self._source.labels[idx],
                None if self._source.features_mask is None else self._source.features_mask[idx],
                None if self._source.labels_mask is None else self._source.labels_mask[idx],
            )
            return iter(shuffled.batch_by(self._batch_size))
        if self._shuffle:
            # List-of-DataSets source: shuffle the batch ORDER each epoch
            # (cross-batch example shuffling needs a single-DataSet source).
            order = self._rng.permutation(len(self._batches))
            return iter([self._batches[i] for i in order])
        return iter(self._batches)

    def batch_size(self):
        return self._batch_size

    def total_examples(self):
        return sum(b.num_examples() for b in self._batches)


# Below this many bytes, one device_put of the whole batch tuple wins
# (saves per-message round trips: 1.0ms vs 5.2ms for a LeNet batch on a
# tunneled TPU). Above it, the batched-transfer RPC degrades badly
# (178ms vs 23ms for a ResNet batch) and per-array puts win.
_TUPLE_PUT_MAX_BYTES = 4 << 20


def stage_to_device(ds: DataSet) -> DataSet:
    """Transfer one DataSet's arrays host->device, choosing the transfer
    shape empirically fastest for the batch size (see _TUPLE_PUT_MAX_BYTES)."""
    import jax

    parts = [np.asarray(ds.features)]
    idx = {"features": 0}
    for name in ("labels", "features_mask", "labels_mask"):
        a = getattr(ds, name)
        if a is not None:
            idx[name] = len(parts)
            parts.append(np.asarray(a))
    if sum(p.nbytes for p in parts) <= _TUPLE_PUT_MAX_BYTES:
        staged = jax.device_put(tuple(parts))
    else:
        staged = [jax.device_put(p) for p in parts]
    return DataSet(
        staged[0],
        staged[idx["labels"]] if "labels" in idx else None,
        staged[idx["features_mask"]] if "features_mask" in idx else None,
        staged[idx["labels_mask"]] if "labels_mask" in idx else None,
    )


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch to device (reference:
    `AsyncDataSetIterator.java` — the host-side I/O boundary of the fit()
    call stack, SURVEY.md §3.1)."""

    def __init__(self, base: Iterable, queue_size: int = 4, device_prefetch: bool = True):
        self.base = base
        self.queue_size = max(1, int(queue_size))
        self.device_prefetch = device_prefetch

    def _put(self, ds: DataSet) -> DataSet:
        if not self.device_prefetch:
            return ds
        return stage_to_device(ds)

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        _END = object()
        stop = threading.Event()
        errors: List[BaseException] = []

        def offer(item) -> bool:
            # Bounded put that gives up when the consumer abandoned iteration,
            # so the worker never blocks forever holding device buffers.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for ds in self.base:
                    if not offer(self._put(ds)):
                        return
            except BaseException as e:  # surfaced on the consumer side
                errors.append(e)
            finally:
                offer(_END)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                yield item
        finally:
            # Consumer done or bailed early (break/exception/GeneratorExit):
            # release the worker and drop any prefetched device buffers.
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5)
        if errors:
            raise errors[0]

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()


class DeviceCacheDataSetIterator(DataSetIterator):
    """Stage every batch to DEVICE memory once, replay from HBM thereafter.

    TPU-native counterpart of the reference's `CachingDataSetIterator`
    (`deeplearning4j-core/.../datasets/iterator/CachingDataSetIterator.java`),
    which caches prepared DataSets host-side. On TPU the expensive boundary is
    the host->device link — on a serialized transport, transfers cannot
    overlap compute at all (measured: concurrent 38.5MB puts degrade 23ms ->
    800ms while slowing the train step 2.7x) — so the cache lives in HBM.
    Use for datasets that fit in device memory (MNIST/CIFAR scale); for
    streaming-scale data use AsyncDataSetIterator and accept the link cost.
    """

    def __init__(self, base: Iterable, max_bytes: Optional[int] = None):
        self.base = base
        self.max_bytes = max_bytes
        self._cache: Optional[List[DataSet]] = None

    def _ds_bytes(self, ds: DataSet) -> int:
        return sum(
            np.asarray(a).nbytes
            for a in (ds.features, ds.labels, ds.features_mask, ds.labels_mask)
            if a is not None
        )

    def __iter__(self):
        if self._cache is None:
            staged, total = [], 0
            for ds in self.base:
                total += self._ds_bytes(ds)
                if self.max_bytes is not None and total > self.max_bytes:
                    raise MemoryError(
                        f"DeviceCacheDataSetIterator: dataset exceeds "
                        f"max_bytes={self.max_bytes}; use AsyncDataSetIterator "
                        f"for streaming-scale data"
                    )
                staged.append(stage_to_device(ds))
            self._cache = staged
        return iter(self._cache)

    def reset(self):
        pass  # cache replays; the base iterator is consumed exactly once

    def invalidate(self):
        """Drop the device cache (e.g. after the underlying data changed)."""
        self._cache = None

    def total_examples(self):
        if self._cache is not None:
            return sum(d.num_examples() for d in self._cache)
        return None


class MultipleEpochsIterator(DataSetIterator):
    """Replay a base iterator N times (reference: `MultipleEpochsIterator.java`)."""

    def __init__(self, num_epochs: int, base: Iterable):
        self.num_epochs = int(num_epochs)
        self.base = base

    def __iter__(self):
        for _ in range(self.num_epochs):
            if hasattr(self.base, "reset"):
                self.base.reset()
            yield from self.base

    def reset(self):
        if hasattr(self.base, "reset"):
            self.base.reset()


class SamplingDataSetIterator(DataSetIterator):
    """Sample batches with replacement (reference: `SamplingDataSetIterator.java`)."""

    def __init__(self, data: DataSet, batch_size: int, total_batches: int,
                 seed: Optional[int] = None):
        self.data = data
        self._batch_size = batch_size
        self.total_batches = total_batches
        self._rng = np.random.RandomState(seed)

    def __iter__(self):
        n = self.data.num_examples()
        for _ in range(self.total_batches):
            idx = self._rng.randint(0, n, self._batch_size)
            yield DataSet(
                self.data.features[idx],
                None if self.data.labels is None else self.data.labels[idx],
                None if self.data.features_mask is None else self.data.features_mask[idx],
                None if self.data.labels_mask is None else self.data.labels_mask[idx],
            )

    def batch_size(self):
        return self._batch_size


class ExistingDataSetIterator(DataSetIterator):
    """Wrap any python iterable of DataSets (reference: `ExistingDataSetIterator.java`)."""

    def __init__(self, iterable: Iterable):
        self._items = list(iterable)

    def __iter__(self):
        return iter(self._items)

    def total_examples(self):
        return sum(d.num_examples() for d in self._items)


class IteratorDataSetIterator(DataSetIterator):
    """Re-batch a stream of DataSets to a fixed batch size (reference:
    `IteratorDataSetIterator.java`)."""

    def __init__(self, base: Iterable, batch_size: int):
        self.base = base
        self._batch_size = batch_size

    def __iter__(self):
        buf: List[DataSet] = []
        count = 0
        for ds in self.base:
            buf.append(ds)
            count += ds.num_examples()
            while count >= self._batch_size:
                merged = DataSet.merge(buf)
                out, rest = merged.split_test_and_train(self._batch_size)
                yield out
                buf = [rest] if rest.num_examples() else []
                count = rest.num_examples()
        if buf:
            merged = DataSet.merge(buf)
            if merged.num_examples():
                yield merged

    def batch_size(self):
        return self._batch_size
