"""DataSet / MultiDataSet containers.

Equivalent of ND4J's `DataSet`/`MultiDataSet` (features, labels, optional
feature/label masks) consumed by every `fit()` path in the reference. Arrays
are host numpy until they cross into a jitted step — the framework controls
the host->device boundary, not the container.

Layouts: features [b, f] | [b, t, f] | [b, h, w, c]; masks [b, t].
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np


@dataclass
class DataSet:
    features: np.ndarray
    labels: Optional[np.ndarray] = None
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_test_and_train(self, n_train: int):
        return (
            DataSet(
                self.features[:n_train],
                None if self.labels is None else self.labels[:n_train],
                None if self.features_mask is None else self.features_mask[:n_train],
                None if self.labels_mask is None else self.labels_mask[:n_train],
            ),
            DataSet(
                self.features[n_train:],
                None if self.labels is None else self.labels[n_train:],
                None if self.features_mask is None else self.features_mask[n_train:],
                None if self.labels_mask is None else self.labels_mask[n_train:],
            ),
        )

    def shuffle(self, seed: Optional[int] = None):
        rng = np.random.RandomState(seed)
        idx = rng.permutation(self.num_examples())
        self.features = self.features[idx]
        if self.labels is not None:
            self.labels = self.labels[idx]
        if self.features_mask is not None:
            self.features_mask = self.features_mask[idx]
        if self.labels_mask is not None:
            self.labels_mask = self.labels_mask[idx]

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        n = self.num_examples()
        return [
            DataSet(
                self.features[i : i + batch_size],
                None if self.labels is None else self.labels[i : i + batch_size],
                None if self.features_mask is None else self.features_mask[i : i + batch_size],
                None if self.labels_mask is None else self.labels_mask[i : i + batch_size],
            )
            for i in range(0, n, batch_size)
        ]

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        def cat(parts):
            if any(p is None for p in parts):
                return None
            return np.concatenate(parts, axis=0)

        return DataSet(
            cat([d.features for d in datasets]),
            cat([d.labels for d in datasets]),
            cat([d.features_mask for d in datasets]),
            cat([d.labels_mask for d in datasets]),
        )


@dataclass
class MultiDataSet:
    """Multiple features/labels arrays (reference: ND4J MultiDataSet, consumed
    by ComputationGraph.fit)."""

    features: List[np.ndarray] = field(default_factory=list)
    labels: List[np.ndarray] = field(default_factory=list)
    features_masks: Optional[List[Optional[np.ndarray]]] = None
    labels_masks: Optional[List[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])

    @staticmethod
    def from_dataset(ds: DataSet) -> "MultiDataSet":
        return MultiDataSet(
            features=[ds.features],
            labels=[ds.labels] if ds.labels is not None else [],
            features_masks=[ds.features_mask] if ds.features_mask is not None else None,
            labels_masks=[ds.labels_mask] if ds.labels_mask is not None else None,
        )
