"""Overlapped host->device staging (ROADMAP open item 3; PERF.md §20).

The transfer layer of the input pipeline: everything that moves a batch
across the host->device link lives here, so the engines' fit loops never
call `device_put` themselves (tpulint JX011 enforces that split).

Two tiers:

* The synchronous primitives (`transfer_cast`, `stage_to_device`,
  `stage_item`) — moved from `datasets/iterators.py`, unchanged in
  behavior. `transfer_cast` applies the DtypePolicy `transfer_dtype`
  cast HOST-side (f32 -> bf16 halves wire bytes) while leaving integer /
  uint8 parts untouched — compact image bytes ship as-is and are scaled
  on device by the engine's uint8 policy, so the wire always carries the
  reduced representation.

* `DeviceStager` — a background thread that pulls from a base iterator,
  applies the cast, and issues non-blocking `device_put`s into a bounded
  in-flight window so the NEXT batch crosses the link while the current
  train step runs. With JAX's async dispatch the consumer thread only
  enqueues device work, so on streaming workloads the link transfer is
  hidden behind compute and `dl4j_input_wait_seconds` collapses to ~0.

Backpressure: the in-flight window is budgeted in BYTES (not batch
count) against `DL4J_TPU_STAGE_BYTES`, defaulting to half the device
headroom left after `observability.memory.measured_model_bytes` (model +
optimizer + largest recorded transient). When the budget is tight the
window SHRINKS — the worker blocks until the consumer retires bytes —
and a single oversized batch is still admitted once the window is empty,
so staging degrades toward the synchronous path instead of erroring.

Donation note (the PR 9 aliasing lesson): train steps donate ONLY params
and opt_state (`donate_argnums` never includes batch arguments), so a
staged batch buffer is read-only to the step and needs no
`mesh.own_on_device` defensive copy. Anything staged here that later
feeds DONATED state (e.g. a checkpoint restore path reusing these
helpers) must copy via `mesh.own_on_device` first.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu import observability as _obs

# Hot-loop series resolved once at import (observability/metrics.py rule 2).
_M_INFLIGHT = _obs.metrics.gauge(
    "dl4j_staging_inflight_bytes",
    "Bytes admitted to DeviceStager in-flight windows and not yet handed "
    "to a consumer (bounded by the staging byte budget)")
_M_DEPTH = _obs.metrics.gauge(
    "dl4j_staging_depth",
    "Batches currently staged ahead across DeviceStager queues")
_M_STAGE_WAIT = _obs.metrics.histogram(
    "dl4j_staging_wait_seconds",
    "Stager-thread seconds blocked waiting on the base iterator's next "
    "(producer-side stall, the dual of dl4j_input_wait_seconds)")
_M_STAGED_BYTES = _obs.metrics.counter(
    "dl4j_staging_bytes_total",
    "Host bytes shipped to device by background DeviceStager threads "
    "(the overlapped share of host->device traffic)")
_M_PUT_SECONDS = _obs.metrics.counter(
    "dl4j_staging_put_seconds_total",
    "Host seconds spent issuing device_put, split by whether the put ran "
    "on a DeviceStager thread (overlapped with compute) or on the caller "
    "thread (synchronous)",
    label_names=("mode",))
_M_PUT_OVERLAPPED = _M_PUT_SECONDS.labels(mode="overlapped")
_M_PUT_SYNC = _M_PUT_SECONDS.labels(mode="synchronous")

# Families shared with the engines/iterators: re-registration returns the
# existing family (kind+labels must match), children are cached per label.
_H2D_FAMILY = _obs.metrics.counter(
    "dl4j_host_to_device_bytes_total",
    "Host-resident bytes staged to device with training batches",
    label_names=("engine",))
_WAIT_FAMILY = _obs.metrics.histogram(
    "dl4j_input_wait_seconds",
    "Host seconds blocked in iterator-next waiting for the next batch "
    "(input starvation; the device is idle while this accrues)",
    label_names=("source",))
_H2D_CHILDREN: dict = {}
_WAIT_CHILDREN: dict = {}


def _h2d_child(engine: str):
    child = _H2D_CHILDREN.get(engine)
    if child is None:
        child = _H2D_FAMILY.labels(engine=engine)
        _H2D_CHILDREN[engine] = child
    return child


def _wait_child(source: str):
    child = _WAIT_CHILDREN.get(source)
    if child is None:
        child = _WAIT_FAMILY.labels(source=source)
        _WAIT_CHILDREN[source] = child
    return child


# Puts issued from a DeviceStager worker are overlapped with compute;
# everything else is synchronous caller-thread transfer time.
_TLS = threading.local()


def _put_seconds_child():
    return (_M_PUT_OVERLAPPED if getattr(_TLS, "overlapped", False)
            else _M_PUT_SYNC)


# Below this many bytes, one device_put of the whole batch tuple wins
# (saves per-message round trips: 1.0ms vs 5.2ms for a LeNet batch on a
# tunneled TPU). Above it, the batched-transfer RPC degrades badly
# (178ms vs 23ms for a ResNet batch) and per-array puts win.
_TUPLE_PUT_MAX_BYTES = 4 << 20


def _stage_arrays(parts: Sequence[np.ndarray]) -> List:
    """device_put a set of host arrays, choosing the transfer shape
    empirically fastest for the total size (see _TUPLE_PUT_MAX_BYTES)."""
    import jax

    t0 = time.perf_counter()
    if sum(p.nbytes for p in parts) <= _TUPLE_PUT_MAX_BYTES:
        out = list(jax.device_put(tuple(parts)))
    else:
        out = [jax.device_put(p) for p in parts]
    _put_seconds_child().inc(time.perf_counter() - t0)
    return out


def _np_transfer_dtype(transfer_dtype):
    """Resolve a DtypePolicy `transfer_dtype` string to a numpy dtype
    (bf16 via ml_dtypes). None passes through (no cast)."""
    if transfer_dtype is None:
        return None
    s = str(transfer_dtype)
    if s in ("bfloat16", "bf16"):
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    if s in ("float16", "f16", "fp16"):
        return np.dtype(np.float16)
    return np.dtype(s)


def transfer_cast(item, transfer_dtype):
    """Cast a batch's floating features/labels HOST-SIDE to the policy's
    `transfer_dtype` before staging — the generalized BENCH_r05 streaming
    cast: bytes over the host->device link halve (f32 -> bf16) and the
    `dl4j_host_to_device_bytes_total` counters record the reduced size.
    Masks and integer parts (embedding ids, image bytes) are untouched;
    already-staged device arrays pass through (their transfer is sunk)."""
    dt = _np_transfer_dtype(transfer_dtype)
    if dt is None:
        return item

    def cast(a):
        if (isinstance(a, np.ndarray)
                and np.issubdtype(a.dtype, np.floating) and a.dtype != dt):
            return a.astype(dt)
        return a

    def host(a):
        return a if hasattr(a, "dtype") else np.asarray(a)

    if isinstance(item, MultiDataSet):
        return MultiDataSet(
            features=[cast(host(f)) for f in item.features],
            labels=[cast(host(l)) for l in item.labels],
            features_masks=item.features_masks,
            labels_masks=item.labels_masks,
        )
    if isinstance(item, DataSet):
        return DataSet(
            cast(host(item.features)),
            None if item.labels is None else cast(host(item.labels)),
            item.features_mask,
            item.labels_mask,
        )
    return item


def stage_to_device(ds: DataSet, transfer_dtype=None) -> DataSet:
    """Transfer one DataSet's arrays host->device (see _stage_arrays),
    optionally casting floating features/labels to `transfer_dtype` first
    so the link carries the reduced representation."""
    if transfer_dtype is not None:
        ds = transfer_cast(ds, transfer_dtype)
    parts = [np.asarray(ds.features)]
    idx = {"features": 0}
    for name in ("labels", "features_mask", "labels_mask"):
        a = getattr(ds, name)
        if a is not None:
            idx[name] = len(parts)
            parts.append(np.asarray(a))
    staged = _stage_arrays(parts)
    return DataSet(
        staged[0],
        staged[idx["labels"]] if "labels" in idx else None,
        staged[idx["features_mask"]] if "features_mask" in idx else None,
        staged[idx["labels_mask"]] if "labels_mask" in idx else None,
    )


def _maybe_stage(parts: List) -> List:
    """Stage the np members of a flat part list to device (one tuple-put
    when small, per-array puts when large — see `_stage_arrays`)."""
    np_idx = [i for i, p in enumerate(parts) if isinstance(p, np.ndarray)]
    if not np_idx:
        return parts
    staged = _stage_arrays([parts[i] for i in np_idx])
    out = list(parts)
    for i, s in zip(np_idx, staged):
        out[i] = s
    return out


def _host(a):
    if a is None or hasattr(a, "dtype"):
        return a
    return np.asarray(a)


def stage_item(item):
    """Device-put every host leaf of a batch container, preserving the
    container type: DataSet, MultiDataSet, and the superstep
    Superbatch/MultiSuperbatch stacks (duck-typed on `k` so this module
    never imports iterators). Device-resident leaves pass through."""
    if isinstance(item, DataSet):
        return stage_to_device(item)
    if isinstance(item, MultiDataSet) or (
            hasattr(item, "features_masks") and hasattr(item, "features")):
        feats = [_host(a) for a in item.features]
        labs = [_host(a) for a in item.labels]
        fmasks = (None if item.features_masks is None
                  else [_host(a) for a in item.features_masks])
        lmasks = (None if item.labels_masks is None
                  else [_host(a) for a in item.labels_masks])
        flat = _maybe_stage(feats + labs + (fmasks or []) + (lmasks or []))
        pos = 0
        out = []
        for src in (feats, labs, fmasks, lmasks):
            if src is None:
                out.append(None)
                continue
            out.append(flat[pos:pos + len(src)])
            pos += len(src)
        if isinstance(item, MultiDataSet):
            return MultiDataSet(features=out[0], labels=out[1],
                                features_masks=out[2], labels_masks=out[3])
        return type(item)(out[0], out[1], out[2], out[3], k=item.k)
    if hasattr(item, "features"):  # Superbatch
        parts = _maybe_stage([
            _host(item.features), _host(item.labels),
            _host(item.features_mask), _host(item.labels_mask)])
        return type(item)(parts[0], parts[1], parts[2], parts[3],
                          k=getattr(item, "k", 1))
    return item


def _iter_leaves(item):
    """Yield every non-None array leaf of a batch container (or of a
    list/tuple of containers)."""
    if item is None:
        return
    if isinstance(item, (list, tuple)):
        for sub in item:
            yield from _iter_leaves(sub)
        return
    if hasattr(item, "features"):
        if hasattr(item, "features_masks"):
            slots = (item.features, item.labels, item.features_masks,
                     item.labels_masks)
        else:
            slots = (item.features, item.labels, item.features_mask,
                     item.labels_mask)
        for s in slots:
            if s is None:
                continue
            if isinstance(s, (list, tuple)):
                for a in s:
                    if a is not None:
                        yield a
            else:
                yield s
        return
    yield item


def host_item_nbytes(item) -> int:
    """Bytes a batch container will move over the link when staged: the
    sum of its HOST (numpy) leaves. Device-resident leaves cost nothing
    (their transfer is sunk), so a DeviceCache replay budgets at 0."""
    return sum(a.nbytes for a in _iter_leaves(item)
               if isinstance(a, np.ndarray))


def drop_item(item) -> None:
    """Eagerly free a staged batch's device buffers (best-effort)."""
    for a in _iter_leaves(item):
        delete = getattr(a, "delete", None)
        if delete is None:
            continue
        try:
            delete()
        except Exception:
            pass  # already deleted / not a device array


def _drop_staged(staged: Sequence) -> None:
    """Eagerly free the device buffers of partially staged batches."""
    for ds in staged:
        drop_item(ds)


# ------------------------------------------------------------------ knobs

_DEFAULT_BUDGET = 256 << 20  # no device memory stats (CPU backend)
_MIN_BUDGET = 16 << 20


def staging_enabled() -> bool:
    """Overlapped staging on/off (`DL4J_TPU_STAGING=0|false|off` disables;
    every consumer then degrades to its synchronous path)."""
    return (os.environ.get("DL4J_TPU_STAGING", "").strip().lower()
            not in ("0", "false", "off"))


def staging_depth() -> int:
    """Default stager queue depth (`DL4J_TPU_STAGE_DEPTH`, default 2:
    double-buffering — one batch in flight while one is consumed)."""
    try:
        return max(1, int(os.environ.get("DL4J_TPU_STAGE_DEPTH", "2")))
    except ValueError:
        return 2


def staging_budget_bytes(net=None) -> int:
    """Byte budget for a stager's in-flight window: `DL4J_TPU_STAGE_BYTES`
    when set, else half the device headroom after the net's measured
    footprint (`measured_model_bytes`: params + optimizer + largest
    recorded transient), else a 256 MiB default when the backend reports
    no memory stats."""
    env = os.environ.get("DL4J_TPU_STAGE_BYTES")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    limit = 0
    try:
        import jax

        stats = getattr(jax.devices()[0], "memory_stats", lambda: None)()
        limit = int((stats or {}).get("bytes_limit", 0))
    except Exception:
        limit = 0
    if limit:
        reserved = 0
        if net is not None:
            try:
                from deeplearning4j_tpu.observability import memory as _mem

                reserved = int(_mem.measured_model_bytes(net) or 0)
            except Exception:
                reserved = 0
        headroom = max(0, limit - reserved)
        if headroom:
            return max(_MIN_BUDGET, headroom // 2)
    return _DEFAULT_BUDGET


_END = object()


class DeviceStager:
    """Background-thread staging of a batch stream to device.

    Pulls items from `base` on a worker thread, applies `transform` then
    the `transfer_dtype` cast, stages via `stage_fn` (default
    `stage_item`; `device_stage=False` skips the put for host-only
    prefetch), and hands consumers already-resident batches through a
    bounded queue. Iteration order and contents match the base stream
    exactly; a producer exception is re-raised on the consumer side.

    In-flight bytes are admitted against `max_bytes` BEFORE each put (see
    module docstring for the backpressure contract); `max_inflight_bytes`
    records the high-water mark. `close()` is idempotent: it stops the
    worker, joins it, and drops any staged-but-unconsumed device buffers
    so the in-flight gauges return to their pre-stager level.
    """

    stages_to_device = True

    def __init__(self, base: Iterable, *, stage_fn: Optional[Callable] = None,
                 transform: Optional[Callable] = None, transfer_dtype=None,
                 device_stage: bool = True, depth: Optional[int] = None,
                 max_bytes: Optional[int] = None, net=None,
                 engine: Optional[str] = None, source: Optional[str] = None):
        self.base = base
        self._transform = transform
        self._transfer_dtype = transfer_dtype
        self._device_stage = bool(device_stage)
        self._stage_fn = stage_item if stage_fn is None else stage_fn
        self.depth = staging_depth() if depth is None else max(1, int(depth))
        if max_bytes is None and self._device_stage:
            max_bytes = staging_budget_bytes(net)
        self.max_bytes = max_bytes
        self._h2d = (_h2d_child(engine)
                     if engine and self._device_stage else None)
        self._wait_obs = _wait_child(source) if source else None
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._lock = threading.Lock()
        self._can_admit = threading.Condition(self._lock)
        self._inflight = 0
        self.max_inflight_bytes = 0
        self.last_wait = 0.0
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._closed = False
        self._done = False
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name="dl4j-device-stager")
        self._thread.start()

    # ------------------------------------------------------------ producer

    def _admit(self, nb: int) -> bool:
        """Block until `nb` bytes fit the in-flight window (an oversized
        item is admitted alone once the window is empty, so tight budgets
        shrink the window instead of erroring). False when closed."""
        with self._can_admit:
            while (self.max_bytes is not None and self._inflight > 0
                   and self._inflight + nb > self.max_bytes):
                if self._stop.is_set():
                    return False
                self._can_admit.wait(timeout=0.1)
            if self._stop.is_set():
                return False
            self._inflight += nb
            if self._inflight > self.max_inflight_bytes:
                self.max_inflight_bytes = self._inflight
        _M_INFLIGHT.inc(nb)
        return True

    def _retire(self, nb: int, item=None, drop: bool = False) -> None:
        with self._can_admit:
            self._inflight -= nb
            self._can_admit.notify_all()
        _M_INFLIGHT.inc(-nb)
        if drop and item is not None:
            drop_item(item)

    def _offer(self, payload) -> bool:
        # Bounded put that gives up when the consumer abandoned iteration,
        # so the worker never blocks forever holding device buffers.
        while not self._stop.is_set():
            try:
                self._q.put(payload, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self) -> None:
        _TLS.overlapped = True
        try:
            base_it = iter(self.base)
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    item = next(base_it)
                except StopIteration:
                    break
                _M_STAGE_WAIT.observe(time.perf_counter() - t0)
                if self._transform is not None:
                    item = self._transform(item)
                if self._transfer_dtype is not None:
                    item = transfer_cast(item, self._transfer_dtype)
                nb = host_item_nbytes(item) if self._device_stage else 0
                if self._device_stage:
                    if not self._admit(nb):
                        return
                    try:
                        staged = self._stage_fn(item)
                    except BaseException:
                        self._retire(nb)
                        raise
                    _M_STAGED_BYTES.inc(nb)
                    if self._h2d is not None:
                        self._h2d.inc(nb)
                else:
                    staged = item
                if not self._offer((staged, nb)):
                    self._retire(nb, staged, drop=self._device_stage)
                    return
                _M_DEPTH.inc(1)
        except BaseException as e:  # surfaced on the consumer side
            self._error = e
        finally:
            self._offer(_END)
            _TLS.overlapped = False

    # ------------------------------------------------------------ consumer

    def __iter__(self):
        return self

    def __next__(self):
        if self._done or self._closed:
            self._finish()
        t0 = time.perf_counter()
        payload = self._q.get()
        wait = time.perf_counter() - t0
        self.last_wait = wait
        if self._wait_obs is not None:
            self._wait_obs.observe(wait)
        if payload is _END:
            self._done = True
            self._thread.join(timeout=5)
            self._finish()
        item, nb = payload
        _M_DEPTH.inc(-1)
        self._retire(nb)
        return item

    def _finish(self):
        if self._error is not None:
            raise self._error
        raise StopIteration

    def close(self) -> None:
        """Stop the worker, join it, and drop staged-but-unconsumed
        device buffers. Idempotent; the stager then iterates as
        exhausted (a stored producer error still re-raises)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        with self._can_admit:
            self._can_admit.notify_all()
        self._drain()
        self._thread.join(timeout=5)
        self._drain()  # a put may have landed between drain and join

    def _drain(self) -> None:
        while True:
            try:
                payload = self._q.get_nowait()
            except queue.Empty:
                return
            if payload is _END:
                continue
            item, nb = payload
            _M_DEPTH.inc(-1)
            self._retire(nb, item, drop=self._device_stage)

    def __del__(self):  # pragma: no cover - GC safety net
        try:
            self.close()
        except Exception:
            pass


def maybe_stage(src, *, net=None, engine: Optional[str] = None,
                transfer_dtype=None, source: Optional[str] = None,
                depth: Optional[int] = None):
    """Wrap an epoch's batch source in a `DeviceStager` unless staging is
    disabled, the source already stages to device (`stages_to_device` —
    Async/DeviceCache/SuperbatchIterator), or it is a single-batch
    list/tuple (the `fit(ds)` and elastic per-step paths, where a thread
    per call buys nothing); those pass through to the synchronous path."""
    if not staging_enabled():
        return src
    if getattr(src, "stages_to_device", False):
        return src
    if isinstance(src, (list, tuple)) and len(src) <= 1:
        return src
    return DeviceStager(src, net=net, engine=engine,
                        transfer_dtype=transfer_dtype, source=source,
                        depth=depth)


def close_stager(src) -> None:
    """Close `src` if it is a DeviceStager (no-op otherwise) — the
    engines' fit loops call this in a finally so an abandoned epoch
    never leaves staged buffers in HBM."""
    if isinstance(src, DeviceStager):
        src.close()
