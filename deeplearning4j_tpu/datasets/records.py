"""Record readers + record-reader dataset iterators (the DataVec bridge).

Equivalent of the reference's main real-data path: DataVec record readers
(CSVRecordReader, CSVSequenceRecordReader, ImageRecordReader — consumed as
the external DataVec dependency, SURVEY.md §2.2) feeding
`datasets/datavec/RecordReaderDataSetIterator.java:52`,
`SequenceRecordReaderDataSetIterator.java:33` and
`RecordReaderMultiDataSetIterator.java:57` in `deeplearning4j-core`.

TPU-shape discipline: batches are padded to the iterator's fixed batch size
on request (`pad_batches=True`) so every step compiles once; sequence
iterators emit [B, T, F] with [B, T] masks (the framework's RNN layout —
NHWC for images, matching the conv stack in `nn/layers/convolution.py`).
These iterators compose with the staging wrappers in
`datasets/iterators.py` (Async prefetch / DeviceCache).
"""

from __future__ import annotations

import csv
import os
import struct
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator

IMAGE_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".gif")


# --------------------------------------------------------------- readers

class RecordReader:
    """Record-reader SPI (reference: DataVec `RecordReader` — initialize
    with a source, then iterate records)."""

    def records(self) -> Iterator[List]:
        raise NotImplementedError

    def reset(self) -> None:
        """Re-read from the start (default: records() restarts)."""

    def __iter__(self):
        return self.records()


def _read_csv_rows(path: str, delimiter: str, skip: int) -> Iterator[List[str]]:
    """The one definition of CSV row semantics (every line counts toward
    `skip`, blank rows dropped) — shared by the readers and the
    numeric_matrix fallback so the paths cannot drift."""
    with open(path, newline="") as f:
        reader = csv.reader(f, delimiter=delimiter)
        for i, row in enumerate(reader):
            if i < skip or not row:
                continue
            yield row


class CSVRecordReader(RecordReader):
    """CSV lines -> lists of string values (reference: DataVec
    `CSVRecordReader(skipNumLines, delimiter)`)."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ","):
        self.skip_num_lines = skip_num_lines
        self.delimiter = delimiter
        self._paths: List[str] = []

    def initialize(self, path) -> "CSVRecordReader":
        self._paths = [path] if isinstance(path, str) else list(path)
        return self

    def records(self) -> Iterator[List[str]]:
        for path in self._paths:
            yield from _read_csv_rows(path, self.delimiter,
                                      self.skip_num_lines)

    def numeric_matrix(self) -> "np.ndarray":
        """All rows as one float32 [n, cols] matrix. Uses the native C++
        parser (`deeplearning4j_tpu/native`, ~4x the csv-module path) when
        available and the file is uniformly numeric; transparently falls
        back to the Python reader otherwise."""
        from deeplearning4j_tpu import native as native_mod

        mats = []
        for path in self._paths:
            m = native_mod.parse_numeric_csv(path, self.delimiter,
                                             self.skip_num_lines)
            if m is None:  # no toolchain / non-numeric file
                rows = [[float(v) for v in row] for row in _read_csv_rows(
                    path, self.delimiter, self.skip_num_lines)]
                m = (np.asarray(rows, np.float32) if rows
                     else np.zeros((0, 0), np.float32))
            if m.shape[0]:
                mats.append(m)
        if not mats:
            return np.zeros((0, 0), np.float32)
        return mats[0] if len(mats) == 1 else np.concatenate(mats, axis=0)


class CSVSequenceRecordReader(RecordReader):
    """One CSV file per sequence (reference: DataVec
    `CSVSequenceRecordReader` as used by
    `SequenceRecordReaderDataSetIterator`). `sequence_records()` yields
    [T, cols] string arrays."""

    def __init__(self, skip_num_lines: int = 0, delimiter: str = ","):
        self.skip_num_lines = skip_num_lines
        self.delimiter = delimiter
        self._paths: List[str] = []

    def initialize(self, paths) -> "CSVSequenceRecordReader":
        if isinstance(paths, str):
            if os.path.isdir(paths):
                self._paths = sorted(
                    os.path.join(paths, f) for f in os.listdir(paths)
                    if f.endswith(".csv") or f.endswith(".txt"))
            else:
                self._paths = [paths]
        else:
            self._paths = list(paths)
        return self

    def sequence_records(self) -> Iterator[np.ndarray]:
        for path in self._paths:
            rows = list(_read_csv_rows(path, self.delimiter,
                                       self.skip_num_lines))
            yield np.asarray(rows, dtype=object)

    def records(self) -> Iterator[List]:
        return self.sequence_records()


class ImageRecordReader(RecordReader):
    """Image files -> (NHWC float array, label index) records (reference:
    DataVec `ImageRecordReader(height, width, channels)` with
    `ParentPathLabelGenerator` — the label is the image's parent directory
    name). Decoding/resizing via PIL; grayscale when channels == 1."""

    def __init__(self, height: int, width: int, channels: int = 3,
                 normalize: bool = True):
        self.height = height
        self.width = width
        self.channels = channels
        self.normalize = normalize
        self.labels: List[str] = []
        self._files: List[Tuple[str, int]] = []

    def initialize(self, parent_dir: str) -> "ImageRecordReader":
        """Scan `parent_dir/<label>/<image files>` (the reference's
        parent-path label layout)."""
        self.labels = sorted(
            d for d in os.listdir(parent_dir)
            if os.path.isdir(os.path.join(parent_dir, d)))
        if not self.labels:
            raise ValueError(f"no class subdirectories under {parent_dir}")
        self._files = []
        for li, label in enumerate(self.labels):
            d = os.path.join(parent_dir, label)
            for fname in sorted(os.listdir(d)):
                if fname.lower().endswith(IMAGE_EXTENSIONS):
                    self._files.append((os.path.join(d, fname), li))
        return self

    def num_labels(self) -> int:
        return len(self.labels)

    def _load(self, path: str) -> np.ndarray:
        from PIL import Image
        with Image.open(path) as im:
            im = im.convert("L" if self.channels == 1 else "RGB")
            if im.size != (self.width, self.height):
                im = im.resize((self.width, self.height))
            arr = np.asarray(im, np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.normalize:
            arr = arr / 255.0
        return arr  # [H, W, C]

    def records(self) -> Iterator[Tuple[np.ndarray, int]]:
        for path, label in self._files:
            yield self._load(path), label


# ------------------------------------------------------------- iterators

def _to_float(rows: List[List[str]]) -> np.ndarray:
    return np.asarray(rows, np.float64).astype(np.float32)


class RecordReaderDataSetIterator(DataSetIterator):
    """Record reader -> DataSet batches (reference:
    `RecordReaderDataSetIterator.java:52`).

    Classification: `(reader, batch_size, label_index, num_classes)` —
    the label column is one-hot encoded, remaining columns are features.
    Regression: `(reader, batch_size, label_index, label_index_to=...,
    regression=True)` — label columns [label_index, label_index_to] raw.
    Image readers need only `(reader, batch_size)`; num_classes defaults
    to the reader's label count.
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = None,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index_to: Optional[int] = None,
                 pad_batches: bool = False):
        self.reader = reader
        self._batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self.label_index_to = label_index_to
        self.pad_batches = pad_batches
        if isinstance(reader, ImageRecordReader) and num_classes is None:
            self.num_classes = reader.num_labels()
        if not regression and self.num_classes is None and not isinstance(
                reader, ImageRecordReader):
            raise ValueError(
                "classification mode needs num_classes (or pass "
                "regression=True)")

    def _emit(self, feats: List[np.ndarray], labels: List[np.ndarray]):
        f = np.stack(feats)
        l = np.stack(labels)
        if self.pad_batches and len(f) < self._batch_size:
            # Static-shape batches: pad with zero rows + a per-example [B]
            # labels_mask (the shape the losses/eval stack consumes for 2-D
            # labels) so every step hits one compiled program (XLA
            # recompiles per shape otherwise — SURVEY §7 hard part (a)).
            n_real = len(f)
            pad = self._batch_size - n_real
            f = np.concatenate([f, np.zeros((pad,) + f.shape[1:], f.dtype)])
            mask = np.zeros((self._batch_size,), np.float32)
            mask[:n_real] = 1.0
            l = np.concatenate([l, np.zeros((pad,) + l.shape[1:], l.dtype)])
            return DataSet(f, l, labels_mask=mask)
        return DataSet(f, l)

    def __iter__(self) -> Iterator[DataSet]:
        feats: List[np.ndarray] = []
        labels: List[np.ndarray] = []
        for rec in self.reader.records():
            if isinstance(self.reader, ImageRecordReader):
                img, li = rec
                feats.append(img)
                labels.append(np.eye(self.num_classes, dtype=np.float32)[li])
            else:
                row = np.asarray(rec)
                if self.label_index is None:
                    raise ValueError("label_index required for CSV records")
                if self.regression:
                    hi = (self.label_index_to
                          if self.label_index_to is not None else self.label_index)
                    lab = row[self.label_index:hi + 1].astype(np.float32)
                    feat = np.concatenate(
                        [row[: self.label_index], row[hi + 1:]]).astype(np.float32)
                else:
                    cls = int(float(row[self.label_index]))
                    lab = np.eye(self.num_classes, dtype=np.float32)[cls]
                    feat = np.concatenate(
                        [row[: self.label_index],
                         row[self.label_index + 1:]]).astype(np.float32)
                feats.append(feat)
                labels.append(lab)
            if len(feats) == self._batch_size:
                yield self._emit(feats, labels)
                feats, labels = [], []
        if feats:
            yield self._emit(feats, labels)

    def batch_size(self):
        return self._batch_size


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Sequence readers -> padded [B, T, F] DataSets with [B, T] masks
    (reference: `SequenceRecordReaderDataSetIterator.java:33` — the
    ALIGN_END/variable-length handling collapses to mask arrays here,
    which is what the engines' masking system consumes).

    Two-reader form: `features_reader` + `labels_reader` give aligned
    sequences. Single-reader form: the label column is sliced out of the
    same sequence (`label_index`).
    """

    def __init__(self, features_reader: CSVSequenceRecordReader,
                 labels_reader: Optional[CSVSequenceRecordReader] = None,
                 batch_size: int = 32,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 label_index: Optional[int] = None,
                 max_length: Optional[int] = None):
        self.features_reader = features_reader
        self.labels_reader = labels_reader
        self._batch_size = batch_size
        self.num_classes = num_classes
        self.regression = regression
        self.label_index = label_index
        self.max_length = max_length
        if not regression and num_classes is None:
            raise ValueError(
                "classification mode needs num_classes (or pass "
                "regression=True)")

    def _pairs(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        if self.labels_reader is not None:
            for fseq, lseq in zip(self.features_reader.sequence_records(),
                                  self.labels_reader.sequence_records()):
                yield (_to_float(fseq.tolist()), _to_float(lseq.tolist()))
        else:
            if self.label_index is None:
                raise ValueError(
                    "single-reader mode needs label_index to split the "
                    "label column out of each sequence")
            for seq in self.features_reader.sequence_records():
                arr = _to_float(seq.tolist())
                lab = arr[:, self.label_index:self.label_index + 1]
                feat = np.concatenate(
                    [arr[:, : self.label_index],
                     arr[:, self.label_index + 1:]], axis=1)
                yield feat, lab

    def _emit(self, batch: List[Tuple[np.ndarray, np.ndarray]]) -> DataSet:
        # Without max_length, T is the per-batch maximum — each distinct
        # (B, T) shape costs one XLA compile; set max_length for a single
        # static shape across the whole run (sequences are truncated to it).
        T = max(f.shape[0] for f, _ in batch)
        if self.max_length is not None:
            T = self.max_length
            batch = [(f[:T], l[:T]) for f, l in batch]
        B = len(batch)
        F = batch[0][0].shape[1]
        if self.regression:
            L = batch[0][1].shape[1]
        else:
            L = self.num_classes
        feats = np.zeros((B, T, F), np.float32)
        labels = np.zeros((B, T, L), np.float32)
        mask = np.zeros((B, T), np.float32)
        for i, (f, l) in enumerate(batch):
            t = f.shape[0]
            feats[i, :t] = f
            mask[i, :t] = 1.0
            if self.regression:
                labels[i, :t] = l
            else:
                cls = l[:, 0].astype(np.int64)
                labels[i, :t] = np.eye(L, dtype=np.float32)[cls]
        return DataSet(feats, labels, features_mask=mask, labels_mask=mask)

    def __iter__(self) -> Iterator[DataSet]:
        batch: List[Tuple[np.ndarray, np.ndarray]] = []
        for pair in self._pairs():
            batch.append(pair)
            if len(batch) == self._batch_size:
                yield self._emit(batch)
                batch = []
        if batch:
            yield self._emit(batch)

    def batch_size(self):
        return self._batch_size


class _SubsetDetails:
    """One input/output slot: a reader name, a column subset, and optional
    one-hot encoding (reference: RecordReaderMultiDataSetIterator.SubsetDetails)."""

    def __init__(self, reader_name: str, col_first: Optional[int] = None,
                 col_last: Optional[int] = None, one_hot: bool = False,
                 num_classes: Optional[int] = None):
        self.reader_name = reader_name
        self.col_first = col_first
        self.col_last = col_last
        self.one_hot = one_hot
        self.num_classes = num_classes

    def extract(self, row: np.ndarray) -> np.ndarray:
        """row: [cols] (record) or [T, cols] (sequence) string/float array
        -> float32 subset, one-hot encoded if configured."""
        vals = np.asarray(row, dtype=np.float64)
        if vals.ndim == 1:
            vals = vals[None, :]  # uniform [T, cols]; squeezed by caller
        if self.col_first is not None:
            hi = self.col_last if self.col_last is not None else self.col_first
            vals = vals[:, self.col_first:hi + 1]
        if self.one_hot:
            cls = vals[:, 0].astype(np.int64)
            if np.any(cls < 0) or np.any(cls >= self.num_classes):
                raise ValueError(
                    f"one-hot column for reader {self.reader_name!r} has "
                    f"class ids outside [0, {self.num_classes})")
            return np.eye(self.num_classes, dtype=np.float32)[cls]
        return vals.astype(np.float32)


class RecordReaderMultiDataSetIterator:
    """Multiple inputs/outputs from one or more record readers ->
    `MultiDataSet` batches for `ComputationGraph.fit` (reference:
    `datasets/datavec/RecordReaderMultiDataSetIterator.java:57` with its
    Builder: addReader/addSequenceReader + addInput/addInputOneHot/
    addOutput/addOutputOneHot, column subsets per slot).

    Sequence readers emit [B, T, F] arrays with [B, T] masks; mixed-length
    sequences are padded to the batch max (align="start", the reference's
    ALIGN_START) or right-aligned (align="end", sequence-classification
    ALIGN_END). Use the Builder:

        it = (RecordReaderMultiDataSetIterator.builder(batch_size=16)
              .add_reader("in", CSVRecordReader().initialize(path_a))
              .add_reader("out", CSVRecordReader().initialize(path_b))
              .add_input("in", 0, 3)
              .add_output_one_hot("out", 0, num_classes=5)
              .build())
    """

    class Builder:
        def __init__(self, batch_size: int):
            self.batch_size = batch_size
            self.readers = {}
            self.seq_readers = {}
            self.inputs: List[_SubsetDetails] = []
            self.outputs: List[_SubsetDetails] = []
            self.align = "start"

        def add_reader(self, name: str, reader: RecordReader):
            self.readers[name] = reader
            return self

        def add_sequence_reader(self, name: str,
                                reader: "CSVSequenceRecordReader"):
            self.seq_readers[name] = reader
            return self

        def sequence_alignment_mode(self, align: str):
            if align not in ("start", "end", "equal_length"):
                raise ValueError(f"align must be start|end|equal_length, "
                                 f"got {align!r}")
            self.align = align
            return self

        def add_input(self, name: str, col_first: Optional[int] = None,
                      col_last: Optional[int] = None):
            self.inputs.append(_SubsetDetails(name, col_first, col_last))
            return self

        def add_input_one_hot(self, name: str, column: int, num_classes: int):
            self.inputs.append(_SubsetDetails(
                name, column, column, one_hot=True, num_classes=num_classes))
            return self

        def add_output(self, name: str, col_first: Optional[int] = None,
                       col_last: Optional[int] = None):
            self.outputs.append(_SubsetDetails(name, col_first, col_last))
            return self

        def add_output_one_hot(self, name: str, column: int,
                               num_classes: int):
            self.outputs.append(_SubsetDetails(
                name, column, column, one_hot=True, num_classes=num_classes))
            return self

        def build(self) -> "RecordReaderMultiDataSetIterator":
            return RecordReaderMultiDataSetIterator(self)

    @staticmethod
    def builder(batch_size: int) -> "RecordReaderMultiDataSetIterator.Builder":
        return RecordReaderMultiDataSetIterator.Builder(batch_size)

    def __init__(self, b: "RecordReaderMultiDataSetIterator.Builder"):
        if not b.inputs or not b.outputs:
            raise ValueError("need at least one add_input and one add_output")
        for sd in b.inputs + b.outputs:
            if sd.reader_name not in b.readers and \
                    sd.reader_name not in b.seq_readers:
                raise ValueError(f"subset references unknown reader "
                                 f"{sd.reader_name!r}")
        self._b = b

    def _record_streams(self):
        return (
            {n: iter(r.records()) for n, r in self._b.readers.items()},
            {n: iter(r.sequence_records())
             for n, r in self._b.seq_readers.items()},
        )

    def _emit(self, rows_by_reader, seqs_by_reader):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet

        def assemble(subsets):
            arrays, masks, any_mask = [], [], False
            for sd in subsets:
                if sd.reader_name in rows_by_reader:
                    rows = rows_by_reader[sd.reader_name]
                    arrays.append(np.stack(
                        [sd.extract(r)[0] for r in rows]))
                    masks.append(None)
                    continue
                seqs = [sd.extract(s) for s in seqs_by_reader[sd.reader_name]]
                T = max(s.shape[0] for s in seqs)
                if self._b.align == "equal_length" and \
                        any(s.shape[0] != T for s in seqs):
                    raise ValueError(
                        "equal_length alignment but sequence lengths differ")
                B, F = len(seqs), seqs[0].shape[1]
                out = np.zeros((B, T, F), np.float32)
                m = np.zeros((B, T), np.float32)
                for i, s in enumerate(seqs):
                    t = s.shape[0]
                    if self._b.align == "end":
                        out[i, T - t:], m[i, T - t:] = s, 1.0
                    else:
                        out[i, :t], m[i, :t] = s, 1.0
                arrays.append(out)
                masks.append(m)
                any_mask = True
            return arrays, (masks if any_mask else None)

        feats, fmasks = assemble(self._b.inputs)
        labels, lmasks = assemble(self._b.outputs)
        return MultiDataSet(features=feats, labels=labels,
                            features_masks=fmasks, labels_masks=lmasks)

    def __iter__(self):
        streams, seq_streams = self._record_streams()
        while True:
            rows_by_reader = {}
            seqs_by_reader = {}
            n = None
            for name, it in streams.items():
                rows = []
                for _ in range(self._b.batch_size):
                    try:
                        rows.append(next(it))
                    except StopIteration:
                        break
                rows_by_reader[name] = rows
                n = len(rows) if n is None else n
                if len(rows) != n:
                    raise ValueError(
                        f"reader {name!r} ran out of records before the "
                        f"others (got {len(rows)}, expected {n})")
            for name, it in seq_streams.items():
                seqs = []
                for _ in range(self._b.batch_size):
                    try:
                        seqs.append(next(it))
                    except StopIteration:
                        break
                seqs_by_reader[name] = seqs
                n = len(seqs) if n is None else n
                if len(seqs) != n:
                    raise ValueError(
                        f"sequence reader {name!r} ran out of records before "
                        f"the others (got {len(seqs)}, expected {n})")
            if not n:
                return
            yield self._emit(rows_by_reader, seqs_by_reader)

    def batch_size(self):
        return self._b.batch_size

    def reset(self):
        """Streams restart on each __iter__; kept for iterator-API parity."""


# ----------------------------------------------------------------- CIFAR

def _cifar_search_dirs() -> List[str]:
    # CIFAR_DIR is read at CALL time so setting it after import works.
    return [
        os.environ.get("CIFAR_DIR", ""),
        os.path.expanduser("~/.deeplearning4j_tpu/cifar"),
        "/root/data/cifar",
    ]
_CIFAR_LABELS = ["airplane", "automobile", "bird", "cat", "deer", "dog",
                 "frog", "horse", "ship", "truck"]


def load_cifar10(train: bool = True, num_examples: Optional[int] = None,
                 seed: int = 123) -> DataSet:
    """CIFAR-10 binary-format parser (reference: `CifarDataSetIterator` /
    CifarLoader reading `data_batch_*.bin`: each record is 1 label byte +
    3072 channel-major pixel bytes). No network egress here, so files are
    searched locally (CIFAR_DIR et al.); absent that, a deterministic
    synthetic 10-class set with class-dependent color/texture statistics
    stands in, mirroring the MNIST fallback in `builtin.py`."""
    names = ([f"data_batch_{i}.bin" for i in range(1, 6)]
             if train else ["test_batch.bin"])
    for d in _cifar_search_dirs():
        if d and all(os.path.exists(os.path.join(d, n)) for n in names):
            imgs, labels = [], []
            loaded = 0
            for n in names:
                raw = np.fromfile(os.path.join(d, n), np.uint8)
                rec = raw.reshape(-1, 3073)
                labels.append(rec[:, 0])
                imgs.append(rec[:, 1:].reshape(-1, 3, 32, 32))
                loaded += len(rec)
                if num_examples is not None and loaded >= num_examples:
                    break  # enough records; skip the remaining 30MB files
            x = np.concatenate(imgs)
            y = np.concatenate(labels)
            if num_examples is not None:
                x, y = x[:num_examples], y[:num_examples]
            x = np.transpose(x.astype(np.float32) / 255.0,
                             (0, 2, 3, 1))  # NCHW file layout -> NHWC
            break
    else:
        rng = np.random.RandomState(seed)
        n = num_examples or (2000 if train else 400)
        y = rng.randint(0, 10, n)
        # Class-dependent mean color + oriented grating, separable enough
        # for smoke training.
        x = rng.rand(n, 32, 32, 3).astype(np.float32) * 0.25
        grid = np.arange(32)
        for cls in range(10):
            idx = np.flatnonzero(y == cls)
            phase = np.sin(grid * (cls + 1) * np.pi / 16.0) * 0.25 + 0.5
            x[idx, :, :, cls % 3] += phase[None, None, :]
        x = np.clip(x, 0.0, 1.0)
    if num_examples is not None:
        x, y = x[:num_examples], y[:num_examples]
    onehot = np.eye(10, dtype=np.float32)[y]
    return DataSet(x, onehot)


def _lfw_search_dirs() -> List[str]:
    return [
        os.environ.get("LFW_DIR", ""),
        os.path.expanduser("~/.deeplearning4j_tpu/lfw"),
        os.path.expanduser("~/lfw"),
    ]


class LFWDataSetIterator(RecordReaderDataSetIterator):
    """Labeled Faces in the Wild (reference:
    `datasets/iterator/impl/LFWDataSetIterator.java` over `LFWLoader` —
    parent-path person labels, configurable image dims / numExamples /
    train-test split).

    Zero-egress policy: the loader searches `LFW_DIR` /
    `~/.deeplearning4j_tpu/lfw` for the standard `lfw/<person>/<img>.jpg`
    layout (the reference downloads lfw.tgz to the same layout); absent
    that, a deterministic synthetic face-like set (class-dependent
    blob/stripe statistics, like the CIFAR fallback) stands in so the
    pipeline stays drivable.
    """

    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 image_shape: Tuple[int, int, int] = (250, 250, 3),
                 num_labels: Optional[int] = None, train: bool = True,
                 split_train_test: float = 1.0, seed: int = 123):
        h, w, c = image_shape
        for d in _lfw_search_dirs():
            if d and os.path.isdir(d) and any(
                    os.path.isdir(os.path.join(d, s)) for s in os.listdir(d)):
                reader = ImageRecordReader(h, w, c).initialize(d)
                if num_labels is not None:
                    # Truncate the label SPACE too, so the one-hot width is
                    # num_labels (old indices stay valid: kept labels are a
                    # prefix of the sorted label list).
                    reader.labels = reader.labels[:num_labels]
                    reader._files = [(p, li) for p, li in reader._files
                                     if li < num_labels]
                self._synthetic = False
                break
        else:
            reader = _SyntheticFaceReader(h, w, c, num_labels or 5,
                                          num_examples or 200, seed)
            self._synthetic = True
        files = reader._files
        rng = np.random.RandomState(seed)
        order = rng.permutation(len(files))
        if num_examples is not None:
            order = order[:num_examples]
        split = int(len(order) * split_train_test)
        order = order[:split] if train else order[split:]
        reader._files = [files[i] for i in order]
        super().__init__(reader, batch_size)

    def total_examples(self) -> int:
        return len(self.reader._files)


class _SyntheticFaceReader(ImageRecordReader):
    """Deterministic stand-in for the LFW archive (see LFWDataSetIterator)."""

    def __init__(self, h, w, c, n_labels, n_examples, seed):
        self.height, self.width, self.channels = h, w, c
        self.normalize = True
        self.labels = [f"person_{i}" for i in range(n_labels)]
        self._files = [(f"synthetic_{i}", i % n_labels)
                       for i in range(n_examples)]
        self._seed = seed

    def _load(self, path: str) -> np.ndarray:
        i = int(path.rsplit("_", 1)[1])
        li = i % len(self.labels)
        rng = np.random.RandomState(self._seed + i)
        img = rng.rand(self.height, self.width, self.channels) * 0.2
        # "Face": a class-positioned bright ellipse + identity stripes.
        yy, xx = np.mgrid[0:self.height, 0:self.width]
        cy = self.height * (0.3 + 0.05 * li)
        cx = self.width * 0.5
        r = ((yy - cy) / (0.3 * self.height)) ** 2 + \
            ((xx - cx) / (0.22 * self.width)) ** 2
        img[r < 1.0] += 0.5
        img[:, :: max(2, li + 2), :] += 0.15
        return np.clip(img, 0.0, 1.0).astype(np.float32)


def load_curves(num_examples: Optional[int] = None,
                seed: int = 123) -> DataSet:
    """The "curves" benchmark set (reference:
    `datasets/fetchers/CurvesDataFetcher.java` — downloads `curves.ser`,
    the classic 28x28 synthetic-curve images used for deep-autoencoder
    pretraining; features double as reconstruction targets).

    Zero-egress: searches `CURVES_DIR` / `~/.deeplearning4j_tpu/curves`
    for `curves.npz` (key "x", [N, 784] float; the Java-serialized
    `curves.ser` is not parseable outside the JVM — convert once with any
    dl4j install). Absent that, generates the same KIND of data the
    benchmark uses: random cubic Bezier curves rasterized onto 28x28."""
    for d in (os.environ.get("CURVES_DIR", ""),
              os.path.expanduser("~/.deeplearning4j_tpu/curves")):
        p = os.path.join(d, "curves.npz") if d else ""
        if p and os.path.exists(p):
            x = np.load(p)["x"].astype(np.float32)
            break
    else:
        rng = np.random.RandomState(seed)
        n = num_examples or 2000
        ts = np.linspace(0.0, 1.0, 64)[:, None]
        b0 = (1 - ts) ** 3
        b1 = 3 * ts * (1 - ts) ** 2
        b2 = 3 * ts ** 2 * (1 - ts)
        b3 = ts ** 3
        ctrl = rng.rand(n, 4, 2) * 24 + 2  # 4 control points in [2, 26)
        pts = (b0[None] * ctrl[:, None, 0] + b1[None] * ctrl[:, None, 1]
               + b2[None] * ctrl[:, None, 2] + b3[None] * ctrl[:, None, 3])
        x = np.zeros((n, 28, 28), np.float32)
        idx = np.clip(pts.round().astype(int), 0, 27)
        rows = np.repeat(np.arange(n), 64)
        x[rows, idx[:, :, 1].ravel(), idx[:, :, 0].ravel()] = 1.0
        x = x.reshape(n, 784)
    if num_examples is not None:
        x = x[:num_examples]
    return DataSet(x, x.copy())  # reconstruction targets = inputs


class CurvesDataSetIterator(DataSetIterator):
    """Reference: `CurvesDataFetcher` consumed through the fetcher-backed
    iterator pattern (BaseDatasetIterator)."""

    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 seed: int = 123, shuffle: bool = False):
        from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
        ds = load_curves(num_examples=num_examples, seed=seed)
        self._impl = ListDataSetIterator(ds, batch_size=batch_size,
                                         shuffle=shuffle, seed=seed)

    def __iter__(self):
        return iter(self._impl)

    def reset(self):
        self._impl.reset()

    def batch_size(self):
        return self._impl.batch_size()

    def total_examples(self):
        return self._impl.total_examples()


class Cifar10DataSetIterator(DataSetIterator):
    """Reference: `CifarDataSetIterator` (deeplearning4j-core)."""

    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 train: bool = True, seed: int = 123, shuffle: bool = False):
        from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
        ds = load_cifar10(train=train, num_examples=num_examples, seed=seed)
        self._impl = ListDataSetIterator(ds, batch_size=batch_size,
                                         shuffle=shuffle, seed=seed)
        self.labels = list(_CIFAR_LABELS)

    def __iter__(self):
        return iter(self._impl)

    def reset(self):
        self._impl.reset()

    def batch_size(self):
        return self._impl.batch_size()

    def total_examples(self):
        return self._impl.total_examples()
