"""Built-in dataset fetchers: MNIST and Iris.

Equivalent of the reference's `datasets/mnist/` raw-IDX parser and Iris fetcher
(`deeplearning4j-core/.../datasets/`). This environment has no network egress,
so:

- `MnistDataSetIterator` parses real IDX files when present (searched in
  `MNIST_DIR`, `~/.deeplearning4j_tpu/mnist`, `/root/data/mnist`); otherwise it
  falls back to a DETERMINISTIC synthetic digit set (class-dependent stroke
  templates + noise) that is linearly separable enough for examples/tests.
  The IDX parser is format-compatible with the real files
  (`train-images-idx3-ubyte` etc.), matching the reference's MnistFetcher.
- `IrisDataSetIterator` generates the classic 3-cluster structure
  deterministically (4 features, 150 examples).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

_MNIST_SEARCH = [
    os.environ.get("MNIST_DIR", ""),
    os.path.expanduser("~/.deeplearning4j_tpu/mnist"),
    "/root/data/mnist",
]


def _read_idx(path: str) -> np.ndarray:
    """Parse an IDX file (reference: `datasets/mnist/MnistImageFile.java`)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = [struct.unpack(">I", f.read(4))[0] for _ in range(ndim)]
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find_mnist(train: bool) -> Optional[Tuple[str, str]]:
    img = "train-images-idx3-ubyte" if train else "t10k-images-idx3-ubyte"
    lab = "train-labels-idx1-ubyte" if train else "t10k-labels-idx1-ubyte"
    for d in _MNIST_SEARCH:
        if not d:
            continue
        for suffix in ("", ".gz"):
            ip, lp = os.path.join(d, img + suffix), os.path.join(d, lab + suffix)
            if os.path.exists(ip) and os.path.exists(lp):
                return ip, lp
    return None


def _synthetic_mnist(n: int, seed: int, split: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic digit-like data: per-class smoothed template + noise.

    Class templates depend only on `seed` so the train (split=0) and test
    (split=1) sets share the same class structure; only the noise and label
    draws differ per split."""
    templates = np.random.RandomState(seed).rand(10, 7, 7)
    rng = np.random.RandomState(seed * 1000 + split + 1)
    labels = rng.randint(0, 10, n)
    coarse = templates[labels] + 0.35 * rng.rand(n, 7, 7)
    imgs = np.kron(coarse, np.ones((1, 4, 4)))  # upsample 7x7 -> 28x28
    imgs = np.clip(imgs, 0, 1).astype("float32")
    return imgs.reshape(n, 28, 28, 1), labels


def load_mnist(train: bool = True, num_examples: Optional[int] = None,
               seed: int = 123, flat: bool = False) -> DataSet:
    found = _find_mnist(train)
    if found:
        imgs = _read_idx(found[0]).astype("float32") / 255.0
        labels = _read_idx(found[1]).astype("int64")
        imgs = imgs[..., None]  # NHWC, c=1
    else:
        n = num_examples or (60000 if train else 10000)
        imgs, labels = _synthetic_mnist(n, seed, split=0 if train else 1)
    if num_examples:
        imgs, labels = imgs[:num_examples], labels[:num_examples]
    if flat:
        imgs = imgs.reshape(imgs.shape[0], -1)
    onehot = np.eye(10, dtype="float32")[labels]
    return DataSet(imgs, onehot)


class MnistDataSetIterator(ListDataSetIterator):
    """Reference: `MnistDataSetIterator` (deeplearning4j-core)."""

    def __init__(self, batch_size: int, num_examples: Optional[int] = None,
                 train: bool = True, flat: bool = False, seed: int = 123,
                 shuffle: bool = False):
        ds = load_mnist(train=train, num_examples=num_examples, seed=seed, flat=flat)
        super().__init__(ds, batch_size=batch_size, shuffle=shuffle, seed=seed)


def load_iris(seed: int = 6) -> DataSet:
    """Deterministic iris-structured data: 150 examples, 4 features, 3 classes."""
    rng = np.random.RandomState(seed)
    means = np.array([
        [5.0, 3.4, 1.5, 0.2],
        [5.9, 2.8, 4.3, 1.3],
        [6.6, 3.0, 5.6, 2.0],
    ])
    stds = np.array([
        [0.35, 0.38, 0.17, 0.10],
        [0.51, 0.31, 0.47, 0.20],
        [0.64, 0.32, 0.55, 0.27],
    ])
    feats, labels = [], []
    for c in range(3):
        feats.append(means[c] + stds[c] * rng.randn(50, 4))
        labels.extend([c] * 50)
    X = np.concatenate(feats).astype("float32")
    Y = np.eye(3, dtype="float32")[np.asarray(labels)]
    idx = rng.permutation(150)
    return DataSet(X[idx], Y[idx])


class IrisDataSetIterator(ListDataSetIterator):
    """Reference: `IrisDataSetIterator` (deeplearning4j-core)."""

    def __init__(self, batch_size: int = 150, num_examples: int = 150, seed: int = 6):
        ds = load_iris(seed)
        ds = DataSet(ds.features[:num_examples], ds.labels[:num_examples])
        super().__init__(ds, batch_size=batch_size)
