// Native numeric-CSV parser (the DataVec-bridge hot path).
//
// The reference's data-ingestion layer is native-backed (DataVec's readers
// sit on JavaCV/opencv and ND4J native buffers); this is the TPU build's
// analog for tabular data: a single-allocation two-pass parser that turns a
// numeric CSV straight into a float32 matrix at C speed. Python fallback
// lives in datasets/records.py; deeplearning4j_tpu/native/__init__.py
// builds this file with g++ on first use and loads it via ctypes (no
// pybind11 in the image).
//
// Exported contract (all returns: 0 ok, -1 file error, -2 non-numeric
// field, -3 ragged rows):
//   csv_dims(path, delim, skip, &rows, &cols)   -- count data rows/cols
//   csv_parse(path, delim, skip, out, rows, cols) -- fill out[rows*cols]

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

// Read the whole file + a trailing NUL (so strtof can never overrun);
// empty vector on failure. The NUL is part of the vector: use
// `content_end()` for the logical end of the file data.
std::vector<char> slurp(const char* path) {
    std::vector<char> buf;
    FILE* f = std::fopen(path, "rb");
    if (!f) return buf;
    std::fseek(f, 0, SEEK_END);
    long n = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    if (n > 0) {
        buf.resize(static_cast<size_t>(n) + 1);
        if (std::fread(buf.data(), 1, static_cast<size_t>(n), f) !=
            static_cast<size_t>(n)) {
            buf.clear();
        } else {
            buf.back() = '\0';
        }
    }
    std::fclose(f);
    return buf;
}

struct LineWalker {
    const char* p;
    const char* end;
    explicit LineWalker(const std::vector<char>& b)  // excludes the NUL
        : p(b.data()), end(b.data() + b.size() - 1) {}
    // Next line [begin, stop) INCLUDING blank ones (callers count every
    // line toward `skip`, exactly like the Python csv.reader fallback,
    // then drop blanks); false at EOF.
    bool next(const char** begin, const char** stop) {
        if (p >= end) return false;
        const char* line = p;
        const char* nl = static_cast<const char*>(
            std::memchr(p, '\n', static_cast<size_t>(end - p)));
        const char* e = nl ? nl : end;
        p = nl ? nl + 1 : end;
        if (e > line && e[-1] == '\r') --e;
        *begin = line;
        *stop = e;
        return true;
    }
};

long count_fields(const char* b, const char* e, char delim) {
    long n = 1;
    for (const char* q = b; q < e; ++q)
        if (*q == delim) ++n;
    return n;
}

}  // namespace

extern "C" {

long csv_dims(const char* path, char delim, long skip, long* rows,
              long* cols) {
    std::vector<char> buf = slurp(path);
    if (buf.empty()) return -1;
    LineWalker w(buf);
    const char *b, *e;
    long line_no = 0, nrows = 0, ncols = 0;
    while (w.next(&b, &e)) {
        if (line_no++ < skip) {
            // A quoted field in the skipped region can span lines: the
            // Python csv.reader fallback counts LOGICAL rows toward skip,
            // this walker counts physical lines. Punt to the fallback the
            // moment a quote shows up so the two paths can never start
            // data at different rows.
            if (std::memchr(b, '"', static_cast<size_t>(e - b))) return -2;
            continue;
        }
        if (b == e) continue;  // blank line (counted toward skip above)
        long c = count_fields(b, e, delim);
        if (ncols == 0) ncols = c;
        else if (c != ncols) return -3;
        ++nrows;
    }
    *rows = nrows;
    *cols = ncols;
    return 0;
}

long csv_parse(const char* path, char delim, long skip, float* out,
               long rows, long cols) {
    std::vector<char> buf = slurp(path);
    if (buf.empty()) return -1;
    LineWalker w(buf);
    const char *b, *e;
    long line_no = 0, r = 0;
    while (w.next(&b, &e)) {
        if (line_no++ < skip) {
            // Match csv_dims: quoted skip regions go to the Python fallback.
            if (std::memchr(b, '"', static_cast<size_t>(e - b))) return -2;
            continue;
        }
        if (b == e) continue;  // blank line
        if (r >= rows) return -3;
        long c = 0;
        const char* q = b;
        while (q <= e) {
            const char* field_end = q;
            while (field_end < e && *field_end != delim) ++field_end;
            if (c >= cols || field_end == q) return -2;
            // strtof directly on the buffer: the delimiter/newline byte
            // after the field stops the parse (slurp() NUL-terminates the
            // whole buffer so the final field is safe too).
            // Python float() rejects C hex-float literals; stay in sync
            // so native vs fallback never disagree on the same file.
            for (const char* hx = q; hx < field_end; ++hx)
                if (*hx == 'x' || *hx == 'X') return -2;
            char* endp = nullptr;
            float v = std::strtof(q, &endp);
            while (endp < field_end && *endp == ' ') ++endp;
            if (endp != field_end) return -2;
            out[r * cols + c] = v;
            ++c;
            q = field_end + 1;
            if (field_end == e) break;
        }
        if (c != cols) return -3;
        ++r;
    }
    return r == rows ? 0 : -3;
}

}  // extern "C"
