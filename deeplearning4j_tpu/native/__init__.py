"""Native (C++) runtime components.

The reference's runtime around the compute path is native (ND4J C++ ops,
DataVec's JavaCV-backed readers); the TPU build keeps XLA as the compute
path and implements its IO hot paths in C++ too. Modules here are built
with `g++` on first use (no pybind11 in the image — plain `extern "C"` +
ctypes) and every caller has a pure-Python fallback, so the package works
on machines without a toolchain.

Current components:
- `fastcsv` — numeric CSV -> float32 matrix parser
  (`parse_numeric_csv`), used by `datasets/records.py`'s
  `CSVRecordReader.numeric_matrix`. ~4x the csv-module path on a
  100k x 10 file (PERF.md §7).
- `fastvocab` — tokenizer + vocab counter + corpus encoder
  (`build_vocab_corpus`), used by `nlp/word2vec.py`'s fit path; replaces
  the Python dict-count + per-token index lookups (PERF.md §5's 1-2 s
  of host string handling at 2M words). Exactness guards: falls back to
  the Python path whenever byte-level processing could diverge from
  Python string semantics (non-ASCII with the preprocessor, tokens
  containing separators, non-default tokenizers).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIBS: dict = {}  # name -> CDLL | None (None = build failed, don't retry)


def _build_and_load(name: str, configure) -> Optional[ctypes.CDLL]:
    src = os.path.join(_HERE, f"{name}.cpp")
    so = os.path.join(_HERE, f"_{name}.so")
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 "-o", so + ".tmp", src],
                check=True, capture_output=True, timeout=120)
            os.replace(so + ".tmp", so)
        lib = ctypes.CDLL(so)
        configure(lib)
        return lib
    except Exception:
        return None


def _configure_fastcsv(lib):
    lib.csv_dims.restype = ctypes.c_long
    lib.csv_dims.argtypes = [
        ctypes.c_char_p, ctypes.c_char, ctypes.c_long,
        ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
    lib.csv_parse.restype = ctypes.c_long
    lib.csv_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_char, ctypes.c_long,
        ctypes.POINTER(ctypes.c_float), ctypes.c_long, ctypes.c_long]


def _configure_fastvocab(lib):
    L = ctypes.c_long
    lib.vocab_build.restype = L
    lib.vocab_build.argtypes = [ctypes.c_char_p, L, ctypes.c_int,
                                ctypes.c_int, ctypes.c_double]
    lib.vocab_stats.restype = L
    lib.vocab_stats.argtypes = [L] + [ctypes.POINTER(L)] * 5
    lib.vocab_dump.restype = L
    lib.vocab_dump.argtypes = [L, ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_double)]
    lib.vocab_encode.restype = L
    lib.vocab_encode.argtypes = [L, ctypes.POINTER(ctypes.c_int),
                                 ctypes.POINTER(ctypes.c_longlong)]
    lib.vocab_free.restype = None
    lib.vocab_free.argtypes = [L]


_CONFIGURE = {"fastcsv": _configure_fastcsv, "fastvocab": _configure_fastvocab}


def _lib(name: str = "fastcsv") -> Optional[ctypes.CDLL]:
    if name not in _LIBS:
        with _LOCK:
            if name not in _LIBS:
                _LIBS[name] = _build_and_load(name, _CONFIGURE[name])
    return _LIBS[name]


def native_available() -> bool:
    return _lib("fastcsv") is not None


def parse_numeric_csv(path: str, delimiter: str = ",",
                      skip: int = 0) -> Optional[np.ndarray]:
    """Parse an all-numeric CSV into a float32 [rows, cols] matrix with the
    native parser. Returns None when the native library is unavailable OR
    the file isn't uniformly numeric (callers fall back to the Python
    reader — same result, slower)."""
    lib = _lib()
    if lib is None or len(delimiter.encode()) != 1:
        return None
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    p = os.fsencode(path)
    d = delimiter.encode()
    if lib.csv_dims(p, d, skip, ctypes.byref(rows), ctypes.byref(cols)) != 0:
        return None
    if rows.value == 0 or cols.value == 0:
        return np.zeros((rows.value, cols.value), np.float32)
    out = np.empty((rows.value, cols.value), np.float32)
    rc = lib.csv_parse(p, d, skip,
                       out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                       rows.value, cols.value)
    return out if rc == 0 else None


def build_vocab_corpus(sentences, min_word_frequency: float = 1.0,
                       tokenizer_factory=None):
    """Native tokenize + vocab count + encode for the embedding trainers.

    Returns (words, counts, seqs) — vocab words in finalize_vocab order,
    float counts, and each sentence as an int32 index array with OOV
    dropped — or None when the fast path can't GUARANTEE Python-identical
    results (caller falls back to `VocabConstructor` — same output,
    slower). `sentences` must be a sequence (materialized), either all raw
    strings or all pre-split token lists.
    """
    from deeplearning4j_tpu.nlp.tokenization import (
        CommonPreprocessor, TokenizerFactory,
    )

    lib = _lib("fastvocab")
    if lib is None or not isinstance(sentences, (list, tuple)):
        return None
    # Tokenizer guard: only the default whitespace tokenizer, bare or with
    # CommonPreprocessor, has a native equivalent.
    mode = 0
    if tokenizer_factory is not None:
        if type(tokenizer_factory) is not TokenizerFactory:
            return None
        pre = tokenizer_factory.preprocessor
        if pre is None:
            pass
        elif type(pre) is CommonPreprocessor:
            mode = 1
        else:
            return None

    if all(isinstance(s, str) for s in sentences):
        raw = True
        try:
            buf = "\n".join(sentences).encode("utf-8")
        except Exception:
            return None
        # Python str.split also splits on UNICODE whitespace; restrict the
        # raw path to ASCII so byte-level splitting can't diverge.
        strict_ascii = 1
        n_expected_seqs = None  # embedded '\n' changes it; checked below
    elif all(isinstance(s, (list, tuple)) for s in sentences):
        raw = False
        try:
            buf = "\n".join(" ".join(s) for s in sentences).encode("utf-8")
        except Exception:
            return None
        # Pre-split lists are used as-is by tokenize_corpus (no
        # preprocessor), so mode drops to 0; UTF-8 byte order == code-point
        # order keeps the sort tie-break identical, so non-ASCII is fine.
        mode = 0
        strict_ascii = 0
        n_expected_seqs = len(sentences)
    else:
        return None  # mixed corpus: per-line mode switching not supported

    h = lib.vocab_build(buf, len(buf), mode, strict_ascii,
                        float(min_word_frequency))
    if h < 0:
        return None
    try:
        n_words = ctypes.c_long()
        words_bytes = ctypes.c_long()
        n_seqs = ctypes.c_long()
        n_idx = ctypes.c_long()
        n_raw = ctypes.c_long()
        if lib.vocab_stats(h, ctypes.byref(n_words), ctypes.byref(words_bytes),
                           ctypes.byref(n_seqs), ctypes.byref(n_idx),
                           ctypes.byref(n_raw)) != 0:
            return None
        if raw:
            # A sentence containing '\n' splits differently: reject.
            if n_seqs.value != len(sentences):
                return None
        else:
            # A token containing whitespace splits into more tokens than
            # Python saw: reject (exactness guard).
            if n_seqs.value != n_expected_seqs:
                return None
            if n_raw.value != sum(len(s) for s in sentences):
                return None
        wb = ctypes.create_string_buffer(max(1, words_bytes.value))
        counts = np.zeros((n_words.value,), np.float64)
        if lib.vocab_dump(
                h, wb, counts.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_double))) != 0:
            return None
        words = (wb.raw[:words_bytes.value].decode("utf-8").split("\n")[:-1]
                 if words_bytes.value else [])
        ids = np.zeros((max(1, n_idx.value),), np.int32)
        offs = np.zeros((n_seqs.value + 1,), np.int64)
        if lib.vocab_encode(
                h, ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
                offs.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))) != 0:
            return None
        ids = ids[: n_idx.value]
        seqs = [ids[offs[i]:offs[i + 1]] for i in range(n_seqs.value)]
        return words, counts, seqs
    finally:
        lib.vocab_free(h)
