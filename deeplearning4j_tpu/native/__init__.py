"""Native (C++) runtime components.

The reference's runtime around the compute path is native (ND4J C++ ops,
DataVec's JavaCV-backed readers); the TPU build keeps XLA as the compute
path and implements its IO hot paths in C++ too. Modules here are built
with `g++` on first use (no pybind11 in the image — plain `extern "C"` +
ctypes) and every caller has a pure-Python fallback, so the package works
on machines without a toolchain.

Current components:
- `fastcsv` — numeric CSV -> float32 matrix parser
  (`parse_numeric_csv`), used by `datasets/records.py`'s
  `CSVRecordReader.numeric_matrix`. ~4x the csv-module path on a
  100k x 10 file (PERF.md §7).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    src = os.path.join(_HERE, "fastcsv.cpp")
    so = os.path.join(_HERE, "_fastcsv.so")
    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 "-o", so + ".tmp", src],
                check=True, capture_output=True, timeout=120)
            os.replace(so + ".tmp", so)
        lib = ctypes.CDLL(so)
        lib.csv_dims.restype = ctypes.c_long
        lib.csv_dims.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long)]
        lib.csv_parse.restype = ctypes.c_long
        lib.csv_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_long,
            ctypes.POINTER(ctypes.c_float), ctypes.c_long, ctypes.c_long]
        return lib
    except Exception:
        return None


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_FAILED
    if _LIB is None and not _LIB_FAILED:
        with _LOCK:
            if _LIB is None and not _LIB_FAILED:
                _LIB = _build_and_load()
                _LIB_FAILED = _LIB is None
    return _LIB


def native_available() -> bool:
    return _lib() is not None


def parse_numeric_csv(path: str, delimiter: str = ",",
                      skip: int = 0) -> Optional[np.ndarray]:
    """Parse an all-numeric CSV into a float32 [rows, cols] matrix with the
    native parser. Returns None when the native library is unavailable OR
    the file isn't uniformly numeric (callers fall back to the Python
    reader — same result, slower)."""
    lib = _lib()
    if lib is None or len(delimiter.encode()) != 1:
        return None
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    p = os.fsencode(path)
    d = delimiter.encode()
    if lib.csv_dims(p, d, skip, ctypes.byref(rows), ctypes.byref(cols)) != 0:
        return None
    if rows.value == 0 or cols.value == 0:
        return np.zeros((rows.value, cols.value), np.float32)
    out = np.empty((rows.value, cols.value), np.float32)
    rc = lib.csv_parse(p, d, skip,
                       out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                       rows.value, cols.value)
    return out if rc == 0 else None
