// Native tokenizer + vocab counter + corpus encoder for the NLP pipeline.
//
// The reference's corpus pipeline is JVM-native (DataVec/ND4J string
// processing, `VocabConstructor.buildJointVocabulary`); the TPU build's
// equivalent hot path was pure-Python dict counting + per-token index
// lookups — PERF.md §5 puts 1-2 s of the 4.3 s Word2Vec end-to-end there
// at 2M words. This module does the whole host-side pass in one shot:
// tokenize (whitespace, optional CommonPreprocessor), count, filter by
// min frequency, sort by (-freq, word) EXACTLY like
// `nlp/vocab.py::VocabCache.finalize_vocab` (byte-wise UTF-8 comparison
// equals Python's code-point string order), and encode every sentence as
// int32 vocab indices with OOV tokens skipped.
//
// Exactness contract with the Python fallback (enforced by the wrapper's
// guards + tests): identical vocab order, counts, and encoded id streams,
// or the wrapper rejects the fast path entirely (returns -2):
// - mode 1 (CommonPreprocessor) requires ASCII input — Python lower() is
//   unicode-aware, bytewise tolower is not;
// - strict_ascii additionally rejects non-ASCII in mode 0 for RAW text
//   (Python str.split also splits on unicode whitespace);
// - the wrapper cross-checks sentence/token counts to catch tokens that
//   contain separator bytes.
//
// Protocol (ctypes, handle-based like nothing else here needs to be —
// the dump/encode buffers are sized from vocab_stats):
//   h = vocab_build(buf, len, mode, strict_ascii, min_freq)
//   vocab_stats(h, &n_words, &words_bytes, &n_seqs, &n_idx, &n_raw)
//   vocab_dump(h, words_buf, counts)       // '\n'-joined words, doubles
//   vocab_encode(h, idx_out, seq_offsets)  // int32 ids + int64[n_seqs+1]
//   vocab_free(h)

#include <algorithm>
#include <cctype>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct VocabState {
    std::vector<std::string> words;     // vocab words in final index order
    std::vector<double> counts;         // parallel to words
    std::vector<int> ids;               // encoded corpus (OOV dropped)
    std::vector<long long> seq_off;     // n_seqs + 1 offsets into ids
    long n_raw_tokens = 0;              // tokens seen before OOV filtering
    long words_bytes = 0;               // sum(len(w) + 1) for the dump
};

std::mutex g_mu;
std::unordered_map<long, VocabState*> g_states;
long g_next = 1;

inline bool is_space(unsigned char c) {
    // Python str.split()'s ASCII whitespace set minus '\n' (the sentence
    // separator): \t \v \f \r space and the file/group/record/unit
    // separators \x1c-\x1f ('a\x1cb'.split() == ['a', 'b']).
    return c == ' ' || c == '\t' || c == '\r' || c == '\v' || c == '\f' ||
           (c >= 0x1c && c <= 0x1f);
}

inline bool strip_char(unsigned char c) {
    // CommonPreprocessor's strip set: [\d\.:,"'\(\)\[\]|/?!;]
    return (c >= '0' && c <= '9') || c == '.' || c == ':' || c == ',' ||
           c == '"' || c == '\'' || c == '(' || c == ')' || c == '[' ||
           c == ']' || c == '|' || c == '/' || c == '?' || c == '!' ||
           c == ';';
}

}  // namespace

extern "C" {

long vocab_build(const char* buf, long len, int mode, int strict_ascii,
                 double min_freq) {
    if (!buf || len < 0) return -1;
    if (mode == 1 || strict_ascii) {
        for (long i = 0; i < len; ++i)
            if (static_cast<unsigned char>(buf[i]) >= 0x80) return -2;
    }

    // Pass 1: tokenize + count; record each sentence as first-seen ids.
    std::unordered_map<std::string, long> seen;  // word -> first-seen id
    std::vector<double> freq;                    // by first-seen id
    std::vector<std::vector<int>> sent_tokens;   // first-seen ids per line
    sent_tokens.emplace_back();
    std::string tok;
    long n_raw = 0;

    auto flush_token = [&]() {
        if (tok.empty()) return;
        std::string t;
        if (mode == 1) {
            t.reserve(tok.size());
            for (unsigned char c : tok)
                if (!strip_char(c))
                    t.push_back(static_cast<char>(std::tolower(c)));
        } else {
            t = tok;
        }
        tok.clear();
        ++n_raw;
        if (t.empty()) return;  // preprocessor stripped it entirely
        auto it = seen.find(t);
        long id;
        if (it == seen.end()) {
            id = static_cast<long>(freq.size());
            seen.emplace(std::move(t), id);
            freq.push_back(0.0);
        } else {
            id = it->second;
        }
        freq[id] += 1.0;
        sent_tokens.back().push_back(static_cast<int>(id));
    };

    for (long i = 0; i < len; ++i) {
        unsigned char c = static_cast<unsigned char>(buf[i]);
        if (c == '\n') {
            flush_token();
            sent_tokens.emplace_back();
        } else if (is_space(c)) {
            flush_token();
        } else {
            tok.push_back(static_cast<char>(c));
        }
    }
    flush_token();
    // Note: mode 1 counts tokens that preprocess to "" toward n_raw only;
    // they join no sentence, matching the Python `if t` filter.

    // Sort kept words by (-freq, word) — finalize_vocab order.
    std::vector<long> kept;
    kept.reserve(freq.size());
    std::vector<const std::string*> word_of(freq.size(), nullptr);
    for (const auto& kv : seen) word_of[kv.second] = &kv.first;
    for (long id = 0; id < static_cast<long>(freq.size()); ++id)
        if (freq[id] >= min_freq) kept.push_back(id);
    std::sort(kept.begin(), kept.end(), [&](long a, long b) {
        if (freq[a] != freq[b]) return freq[a] > freq[b];
        return *word_of[a] < *word_of[b];
    });

    auto* st = new VocabState();
    std::vector<int> final_of(freq.size(), -1);
    st->words.reserve(kept.size());
    st->counts.reserve(kept.size());
    for (long rank = 0; rank < static_cast<long>(kept.size()); ++rank) {
        long id = kept[rank];
        final_of[id] = static_cast<int>(rank);
        st->words.push_back(*word_of[id]);
        st->counts.push_back(freq[id]);
        st->words_bytes += static_cast<long>(word_of[id]->size()) + 1;
    }

    // Pass 2 (in-memory): encode sentences, dropping OOV.
    st->seq_off.push_back(0);
    for (const auto& sent : sent_tokens) {
        for (int id : sent) {
            int f = final_of[id];
            if (f >= 0) st->ids.push_back(f);
        }
        st->seq_off.push_back(static_cast<long long>(st->ids.size()));
    }
    st->n_raw_tokens = n_raw;

    std::lock_guard<std::mutex> lock(g_mu);
    long h = g_next++;
    g_states[h] = st;
    return h;
}

long vocab_stats(long h, long* n_words, long* words_bytes, long* n_seqs,
                 long* n_idx, long* n_raw_tokens) {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_states.find(h);
    if (it == g_states.end()) return -1;
    VocabState* st = it->second;
    *n_words = static_cast<long>(st->words.size());
    *words_bytes = st->words_bytes;
    *n_seqs = static_cast<long>(st->seq_off.size()) - 1;
    *n_idx = static_cast<long>(st->ids.size());
    *n_raw_tokens = st->n_raw_tokens;
    return 0;
}

long vocab_dump(long h, char* words_buf, double* counts) {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_states.find(h);
    if (it == g_states.end()) return -1;
    VocabState* st = it->second;
    char* p = words_buf;
    for (size_t i = 0; i < st->words.size(); ++i) {
        std::memcpy(p, st->words[i].data(), st->words[i].size());
        p += st->words[i].size();
        *p++ = '\n';
        counts[i] = st->counts[i];
    }
    return 0;
}

long vocab_encode(long h, int* idx_out, long long* seq_off) {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_states.find(h);
    if (it == g_states.end()) return -1;
    VocabState* st = it->second;
    if (!st->ids.empty())
        std::memcpy(idx_out, st->ids.data(), st->ids.size() * sizeof(int));
    std::memcpy(seq_off, st->seq_off.data(),
                st->seq_off.size() * sizeof(long long));
    return 0;
}

void vocab_free(long h) {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_states.find(h);
    if (it == g_states.end()) return;
    delete it->second;
    g_states.erase(it);
}

}  // extern "C"
