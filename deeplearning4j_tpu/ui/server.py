"""Training UI server.

TPU-lite equivalent of the reference's Play-framework UI
(`deeplearning4j-play/.../PlayUIServer.java:53,183` + the train module
`ui/module/train/TrainModule.java:92-99`): a stdlib `http.server` app that
attaches to a `StatsStorage` and serves
- `/`                    — overview page (score curve, throughput, per-layer
                           mean magnitudes, memory) rendered with inline JS
- `/histogram`           — per-parameter distribution bars from the latest
                           sampled update (reference histogram module,
                           `HistogramModule`)
- `/model`               — model overview table: layers, types, hyperparams
                           from the static-info config JSON (reference
                           `TrainModule.java:92-99` model route)
- `/system`              — device memory / host RSS / throughput charts
                           (reference `TrainModule` system tab)
- `/api/sessions`        — session ids
- `/api/static?sid=`     — model static info
- `/api/updates?sid=`    — the full update stream as JSON
- `/flow`                — network-graph page: the model topology (layer
                           chain or ComputationGraph DAG) rendered as
                           layered boxes + edges (reference flow module,
                           `ui/module/flow/FlowListenerModule`)
- `/tsne`                — t-SNE scatter of coords posted to `/api/tsne`
                           or uploaded via `UIServer.upload_tsne(Y, labels)`
                           (reference `ui/module/tsne/TsneModule`; compute
                           coords with `plot/tsne.py`)
- `/activations`         — convolutional activation grids from the latest
                           `ConvolutionalListener` sample (reference
                           `ui/module/convolutional/ConvolutionalListenerModule`)
- `/metrics`             — Prometheus text scrape of the process-global
                           observability registry (no reference equivalent;
                           PERF.md §11)
- `/api/trace`           — the span tracer's ring buffer as Chrome
                           trace-event JSON: save the body to a file and
                           open it in ui.perfetto.dev
- `/api/slo`             — burn-rate evaluation of the declarative SLOs
                           (`observability/slo.py`) over this process's
                           registry, or over the federated fleet view
                           when a coordinator is attached
- `/api`                 — route index (machine-readable version of this
                           docstring)
- `POST /remote`         — remote-receiver endpoint for
                           `RemoteStatsStorageRouter` (reference
                           `RemoteReceiverModule`); enable with
                           `UIServer(enable_remote=True)`

Usage (mirrors `UIServer.getInstance().attach(statsStorage)`):

    server = UIServer(port=9000).attach(storage).start()
    ...
    server.stop()
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.api.storage import StatsStorage

_STYLE = """<style>
 body { font-family: sans-serif; margin: 2em; background: #fafafa; }
 h1 { font-size: 1.3em; } h2 { font-size: 1.05em; margin-top: 1.5em; }
 .chart { border: 1px solid #ccc; background: #fff; }
 #meta { color: #555; font-size: 0.9em; white-space: pre-line; }
</style>"""

_NAV = ("<div id=nav><a href=/>overview</a> | <a href=/histogram>histograms</a> | <a href=/model>model</a> | <a href=/system>system</a> | <a href=/flow>flow</a> | <a href=/tsne>t-SNE</a> | <a href=/activations>activations</a></div>")

# Shared canvas line-chart renderer, interpolated into every page.
_CHART_JS = """function drawSeries(canvas, series, labels) {
  const ctx = canvas.getContext('2d');
  ctx.clearRect(0, 0, canvas.width, canvas.height);
  const all = series.flatMap(s => s.pts.map(p => p[1]))
      .filter(v => isFinite(v));
  if (!all.length) return;
  const xs = series.flatMap(s => s.pts.map(p => p[0]));
  const xmin = Math.min(...xs), xmax = Math.max(...xs) || 1;
  const ymin = Math.min(...all), ymax = Math.max(...all);
  const px = x => 40 + (canvas.width - 50) * (x - xmin) / Math.max(1, xmax - xmin);
  const py = y => canvas.height - 20 - (canvas.height - 40) *
      (y - ymin) / Math.max(1e-12, ymax - ymin);
  const colors = ['#1565c0','#c62828','#2e7d32','#6a1b9a','#ef6c00','#00838f'];
  series.forEach((s, i) => {
    ctx.strokeStyle = colors[i % colors.length];
    ctx.beginPath();
    s.pts.forEach((p, j) => j ? ctx.lineTo(px(p[0]), py(p[1]))
                              : ctx.moveTo(px(p[0]), py(p[1])));
    ctx.stroke();
    ctx.fillStyle = ctx.strokeStyle;
    ctx.fillText(s.name, 45 + 150 * i, 12);
  });
  ctx.fillStyle = '#333';
  ctx.fillText(ymax.toPrecision(4), 2, 14);
  ctx.fillText(ymin.toPrecision(4), 2, canvas.height - 8);
}
"""


_PAGE = """<!doctype html>
<html><head><title>deeplearning4j-tpu training UI</title>
{style}</head>
<body>
<h1>deeplearning4j-tpu training UI</h1>
{nav}
<div id="meta">loading…</div>
<h2>Score</h2><canvas id="score" class="chart" width="860" height="240"></canvas>
<h2>Per-layer mean magnitudes (updates)</h2>
<canvas id="mm" class="chart" width="860" height="240"></canvas>
<script>
{chart_js}async function refresh() {
  const sessions = await (await fetch('api/sessions')).json();
  if (!sessions.length) return;
  const sid = sessions[sessions.length - 1];
  const updates = await (await fetch('api/updates?sid=' + sid)).json();
  const info = await (await fetch('api/static?sid=' + sid)).json();
  const last = updates[updates.length - 1] || {};
  document.getElementById('meta').textContent =
    'session ' + sid + ' — ' + (info.model_class || '?') + ', ' +
    (info.num_params || '?') + ' params — ' + updates.length + ' samples' +
    (last.iterations_per_sec ?
     ' — ' + last.iterations_per_sec.toFixed(2) + ' it/s' : '') +
    (last.device_memory ? ' — mem ' +
     (last.device_memory.bytes_in_use / 1048576).toFixed(0) + ' MiB' : '');
  drawSeries(document.getElementById('score'),
    [{name: 'score', pts: updates.map(u => [u.iteration, u.score])}]);
  const layers = {};
  updates.forEach(u => {
    Object.entries(u.layer_stats || {}).forEach(([lk, ps]) => {
      Object.entries(ps).forEach(([pn, d]) => {
        const key = lk + '/' + pn;
        (layers[key] = layers[key] || []).push([u.iteration, d.update_mm]);
      });
    });
  });
  drawSeries(document.getElementById('mm'),
    Object.entries(layers).slice(0, 6)
      .map(([name, pts]) => ({name, pts})));
}
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""


_SYSTEM_PAGE = """<!doctype html>
<html><head><title>system — deeplearning4j-tpu UI</title>
{style}</head>
<body>
<h1>System (reference: TrainModule system tab)</h1>
{nav}
<div id="meta">loading…</div>
<h2>Device memory in use (MiB)</h2>
<canvas id="dev" class="chart" width="860" height="220"></canvas>
<h2>Host process RSS (MiB)</h2>
<canvas id="host" class="chart" width="860" height="220"></canvas>
<h2>Throughput (iterations/sec)</h2>
<canvas id="tput" class="chart" width="860" height="220"></canvas>
<script>
{chart_js}
async function refresh() {
  const sessions = await (await fetch('api/sessions')).json();
  if (!sessions.length) return;
  const sid = sessions[sessions.length - 1];
  const updates = await (await fetch('api/updates?sid=' + sid)).json();
  const info = await (await fetch('api/static?sid=' + sid)).json();
  document.getElementById('meta').textContent =
    'session ' + sid + ' — ' + (info.model_class || '?') + ' — ' +
    updates.length + ' samples';
  drawSeries(document.getElementById('dev'),
    [{name: 'bytes_in_use', pts: updates.filter(u => u.device_memory)
      .map(u => [u.iteration, u.device_memory.bytes_in_use / 1048576])}]);
  drawSeries(document.getElementById('host'),
    [{name: 'host_rss_mb', pts: updates.filter(u => u.host_rss_mb)
      .map(u => [u.iteration, u.host_rss_mb])}]);
  drawSeries(document.getElementById('tput'),
    [{name: 'it/s', pts: updates.filter(u => u.iterations_per_sec)
      .map(u => [u.iteration, u.iterations_per_sec])}]);
}
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""


_HISTOGRAM_PAGE = """<!doctype html>
<html><head><title>parameter histograms</title>
<style>
 body { font-family: sans-serif; margin: 2em; background: #fafafa; }
 h1 { font-size: 1.3em; } h2 { font-size: 0.95em; margin: 1.2em 0 0.2em; }
 .chart { border: 1px solid #ccc; background: #fff; }
 a { color: #1565c0; }
</style></head>
<body>
<h1>Parameter histograms <a href="/">overview</a> <a href="/model">model</a> <a href="/system">system</a></h1>
<div id="charts">loading…</div>
<script>
function drawHist(canvas, hist) {
  const ctx = canvas.getContext('2d');
  const n = hist.counts.length, peak = Math.max(...hist.counts, 1);
  const w = (canvas.width - 60) / n;
  ctx.fillStyle = '#1565c0';
  hist.counts.forEach((c, i) => {
    const h = (canvas.height - 24) * c / peak;
    ctx.fillRect(30 + i * w, canvas.height - 12 - h, w - 1, h);
  });
  ctx.fillStyle = '#333';
  ctx.fillText(hist.min.toPrecision(3), 2, canvas.height - 2);
  ctx.fillText(hist.max.toPrecision(3), canvas.width - 55, canvas.height - 2);
}
async function refresh() {
  const sessions = await (await fetch('api/sessions')).json();
  if (!sessions.length) return;
  const updates = await (await fetch('api/updates?sid=' +
      sessions[sessions.length - 1])).json();
  const last = [...updates].reverse().find(u => u.param_histograms);
  if (!last) return;
  const div = document.getElementById('charts');
  div.textContent = '';
  Object.entries(last.param_histograms).forEach(([name, hist]) => {
    const h2 = document.createElement('h2');
    h2.textContent = name + ' (iteration ' + last.iteration + ')';
    const c = document.createElement('canvas');
    c.className = 'chart'; c.width = 420; c.height = 110;
    div.appendChild(h2); div.appendChild(c);
    drawHist(c, hist);
  });
}
refresh(); setInterval(refresh, 5000);
</script></body></html>
"""

_MODEL_PAGE = """<!doctype html>
<html><head><title>model overview</title>
<style>
 body { font-family: sans-serif; margin: 2em; background: #fafafa; }
 h1 { font-size: 1.3em; } a { color: #1565c0; }
 table { border-collapse: collapse; background: #fff; }
 td, th { border: 1px solid #ccc; padding: 4px 10px; font-size: 0.9em; }
 th { background: #eee; }
 pre { background: #fff; border: 1px solid #ccc; padding: 8px;
       max-width: 900px; overflow: auto; font-size: 0.8em; }
</style></head>
<body>
<h1>Model <a href="/">overview</a> <a href="/histogram">histograms</a> <a href="/system">system</a></h1>
<div id="meta"></div>
<table id="layers"><tr><th>#</th><th>layer</th><th>type</th>
<th>n_in</th><th>n_out</th><th>activation</th></tr></table>
<h2>Config JSON</h2><pre id="json"></pre>
<script>
async function refresh() {
  const sessions = await (await fetch('api/sessions')).json();
  if (!sessions.length) return;
  const info = await (await fetch('api/static?sid=' +
      sessions[sessions.length - 1])).json();
  document.getElementById('meta').textContent =
    (info.model_class || '?') + ' — ' + (info.num_params || '?') + ' params';
  if (!info.model_config_json) return;
  const conf = JSON.parse(info.model_config_json);
  document.getElementById('json').textContent =
    JSON.stringify(conf, null, 1);
  const layers = conf.layers ||
    Object.entries(conf.vertices || {}).map(([k, v]) => v.layer ?
      Object.assign({name: k}, v.layer) : {name: k, '@class': v['@class']});
  const table = document.getElementById('layers');
  while (table.rows.length > 1) table.deleteRow(1);
  (layers || []).forEach((l, i) => {
    const r = table.insertRow();
    [i, l.name || '', l['@class'] || '?', l.n_in || '', l.n_out || '',
     l.activation || ''].forEach(v => r.insertCell().textContent = v);
  });
}
refresh();
</script></body></html>
"""


_FLOW_PAGE = """<!doctype html>
<html><head><title>flow — deeplearning4j-tpu UI</title>
{style}</head>
<body>
<h1>Network graph (reference: flow module)</h1>
{nav}
<div id="meta">loading…</div>
<canvas id="graph" class="chart" width="980" height="640"></canvas>
<script>
function layout(conf) {
  // MLN: a chain. CG: rank = 1 + max(rank of inputs) (topological layers).
  if (conf.layers) {
    return {nodes: conf.layers.map((l, i) => ({
        id: 'layer_' + i, label: (l.name || ('layer_' + i)),
        type: l['@class'] || '?', n_out: l.n_out, rank: i, col: 0})),
      edges: conf.layers.slice(1).map((_, i) =>
        ['layer_' + i, 'layer_' + (i + 1)])};
  }
  const nodes = [], edges = [], rank = {};
  (conf.network_inputs || []).forEach((n, i) => {
    rank[n] = 0;
    nodes.push({id: n, label: n, type: 'input', rank: 0});
  });
  const vertices = conf.vertices || {};
  const inputs = conf.vertex_inputs || {};
  let changed = true, guard = 0;
  while (changed && guard++ < 100) {
    changed = false;
    Object.keys(vertices).forEach(name => {
      const ins = inputs[name] || [];
      if (name in rank || !ins.every(i => i in rank)) return;
      rank[name] = 1 + Math.max(...ins.map(i => rank[i]), 0);
      const v = vertices[name];
      nodes.push({id: name, label: name,
        type: (v.layer ? v.layer['@class'] : v['@class']) || '?',
        n_out: v.layer ? v.layer.n_out : undefined, rank: rank[name]});
      ins.forEach(i => edges.push([i, name]));
      changed = true;
    });
  }
  return {nodes, edges};
}
async function refresh() {
  const sessions = await (await fetch('api/sessions')).json();
  if (!sessions.length) return;
  const sid = sessions[sessions.length - 1];
  const info = await (await fetch('api/static?sid=' + sid)).json();
  const updates = await (await fetch('api/updates?sid=' + sid)).json();
  if (!info.model_config_json) return;
  const conf = JSON.parse(info.model_config_json);
  const g = layout(conf);
  document.getElementById('meta').textContent =
    (info.model_class || '?') + ' — ' + g.nodes.length + ' nodes, ' +
    g.edges.length + ' edges — ' + updates.length + ' update samples';
  // update-magnitude coloring from the latest layer_stats sample
  const last = [...updates].reverse().find(u => u.layer_stats) || {};
  const mags = {};
  Object.entries(last.layer_stats || {}).forEach(([lk, ps]) => {
    mags[lk] = Math.max(...Object.values(ps).map(d => d.update_mm || 0));
  });
  const canvas = document.getElementById('graph');
  const ctx = canvas.getContext('2d');
  ctx.clearRect(0, 0, canvas.width, canvas.height);
  const ranks = {};
  g.nodes.forEach(n => (ranks[n.rank] = ranks[n.rank] || []).push(n));
  const nRanks = Object.keys(ranks).length;
  const pos = {};
  Object.entries(ranks).forEach(([r, ns]) => {
    ns.forEach((n, i) => {
      pos[n.id] = [60 + (canvas.width - 200) * i / Math.max(1, ns.length - 1 || 1),
                   40 + (canvas.height - 90) * r / Math.max(1, nRanks - 1)];
      if (ns.length === 1) pos[n.id][0] = canvas.width / 2 - 70;
    });
  });
  ctx.strokeStyle = '#999';
  g.edges.forEach(([a, b]) => {
    const [xa, ya] = pos[a], [xb, yb] = pos[b];
    ctx.beginPath(); ctx.moveTo(xa + 70, ya + 14);
    ctx.lineTo(xb + 70, yb); ctx.stroke();
  });
  const peak = Math.max(...Object.values(mags), 1e-12);
  g.nodes.forEach(n => {
    const [x, y] = pos[n.id];
    const m = mags[n.id];
    ctx.fillStyle = m === undefined ? '#e3f2fd'
      : 'rgba(21,101,192,' + (0.15 + 0.6 * m / peak).toFixed(2) + ')';
    ctx.fillRect(x, y, 140, 28);
    ctx.strokeStyle = '#1565c0'; ctx.strokeRect(x, y, 140, 28);
    ctx.fillStyle = '#111';
    ctx.fillText(n.label + ' · ' + n.type.replace('Layer', '') +
      (n.n_out ? ' · ' + n.n_out : ''), x + 4, y + 17);
  });
}
refresh(); setInterval(refresh, 4000);
</script></body></html>
"""

_TSNE_PAGE = """<!doctype html>
<html><head><title>t-SNE — deeplearning4j-tpu UI</title>
{style}</head>
<body>
<h1>t-SNE (reference: tsne module; coords from plot/tsne.py)</h1>
{nav}
<div id="meta">no coordinates uploaded — POST /api/tsne or
UIServer.upload_tsne(Y, labels)</div>
<canvas id="scatter" class="chart" width="860" height="640"></canvas>
<script>
async function refresh() {
  const data = await (await fetch('api/tsne')).json();
  if (!data.coords || !data.coords.length) return;
  document.getElementById('meta').textContent =
    data.coords.length + ' points' + (data.name ? ' — ' + data.name : '');
  const canvas = document.getElementById('scatter');
  const ctx = canvas.getContext('2d');
  ctx.clearRect(0, 0, canvas.width, canvas.height);
  const xs = data.coords.map(p => p[0]), ys = data.coords.map(p => p[1]);
  const xmin = Math.min(...xs), xmax = Math.max(...xs);
  const ymin = Math.min(...ys), ymax = Math.max(...ys);
  const px = x => 20 + (canvas.width - 40) * (x - xmin) / Math.max(1e-12, xmax - xmin);
  const py = y => canvas.height - 20 - (canvas.height - 40) * (y - ymin) / Math.max(1e-12, ymax - ymin);
  const colors = ['#1565c0','#c62828','#2e7d32','#6a1b9a','#ef6c00',
                  '#00838f','#5d4037','#455a64','#9e9d24','#d81b60'];
  const labelIdx = {};
  (data.labels || []).forEach(l => {
    if (!(l in labelIdx)) labelIdx[l] = Object.keys(labelIdx).length;
  });
  data.coords.forEach((p, i) => {
    const l = data.labels ? data.labels[i] : 0;
    ctx.fillStyle = colors[(labelIdx[l] || 0) % colors.length];
    ctx.beginPath();
    ctx.arc(px(p[0]), py(p[1]), 3, 0, 6.3);
    ctx.fill();
    if (data.point_names) ctx.fillText(data.point_names[i], px(p[0]) + 4, py(p[1]));
  });
  Object.entries(labelIdx).forEach(([l, i]) => {
    ctx.fillStyle = colors[i % colors.length];
    ctx.fillText(String(l), 8, 16 + 14 * i);
  });
}
refresh(); setInterval(refresh, 5000);
</script></body></html>
"""

_ACTIVATIONS_PAGE = """<!doctype html>
<html><head><title>activations — deeplearning4j-tpu UI</title>
{style}</head>
<body>
<h1>Convolutional activations (reference: convolutional module)</h1>
{nav}
<div id="meta">waiting for a ConvolutionalListener sample…</div>
<div id="grids"></div>
<script>
function drawGrid(canvas, act) {
  // act: {h, w, channels: [[row-major floats]]} — grayscale tiles.
  const n = act.channels.length;
  const cols = Math.min(n, 8), rows = Math.ceil(n / cols);
  const cw = act.w * 3, ch = act.h * 3;
  canvas.width = cols * (cw + 4); canvas.height = rows * (ch + 4);
  const ctx = canvas.getContext('2d');
  act.channels.forEach((chan, ci) => {
    let lo = Infinity, hi = -Infinity;
    chan.forEach(v => { lo = Math.min(lo, v); hi = Math.max(hi, v); });
    const img = ctx.createImageData(act.w, act.h);
    chan.forEach((v, i) => {
      const g = Math.round(255 * (v - lo) / Math.max(1e-12, hi - lo));
      img.data[4 * i] = img.data[4 * i + 1] = img.data[4 * i + 2] = g;
      img.data[4 * i + 3] = 255;
    });
    const ox = (ci % cols) * (cw + 4), oy = Math.floor(ci / cols) * (ch + 4);
    // scale via a temp canvas
    const tmp = document.createElement('canvas');
    tmp.width = act.w; tmp.height = act.h;
    tmp.getContext('2d').putImageData(img, 0, 0);
    ctx.imageSmoothingEnabled = false;
    ctx.drawImage(tmp, ox, oy, cw, ch);
  });
}
async function refresh() {
  const sessions = await (await fetch('api/sessions')).json();
  if (!sessions.length) return;
  const updates = await (await fetch('api/updates?sid=' +
      sessions[sessions.length - 1])).json();
  const last = [...updates].reverse().find(u => u.conv_activations);
  if (!last) return;
  document.getElementById('meta').textContent =
    'iteration ' + last.iteration;
  const div = document.getElementById('grids');
  div.textContent = '';
  Object.entries(last.conv_activations).forEach(([name, act]) => {
    const h2 = document.createElement('h2');
    h2.textContent = name + '  [' + act.h + 'x' + act.w + ' x ' +
      act.channels.length + 'ch]';
    const c = document.createElement('canvas');
    c.className = 'chart';
    div.appendChild(h2); div.appendChild(c);
    drawGrid(c, act);
  });
}
refresh(); setInterval(refresh, 5000);
</script></body></html>
"""


for _n in ("_PAGE", "_HISTOGRAM_PAGE", "_MODEL_PAGE", "_SYSTEM_PAGE",
           "_FLOW_PAGE", "_TSNE_PAGE", "_ACTIVATIONS_PAGE"):
    globals()[_n] = (globals()[_n]
                     .replace("{style}", _STYLE)
                     .replace("{chart_js}", _CHART_JS)
                     .replace("{nav}", _NAV))


class _Handler(BaseHTTPRequestHandler):
    storage: Optional[StatsStorage] = None
    enable_remote: bool = False
    tsne_data: Optional[dict] = None  # latest uploaded t-SNE coords
    coordinator_address: Optional[str] = None  # fleet federation source
    _fleet_agg = None  # lazily built FleetAggregator
    _slo_engine = None  # lazily built BurnRateEngine (/api/slo)

    @classmethod
    def _fleet_aggregator(cls):
        if cls.coordinator_address is None:
            return None
        if cls._fleet_agg is None:
            from deeplearning4j_tpu.observability import federation as _fed

            cls._fleet_agg = _fed.FleetAggregator(cls.coordinator_address)
        return cls._fleet_agg

    @classmethod
    def _slo(cls):
        """Burn-rate state for `/api/slo`: federated when a coordinator
        is attached, this process's own registry otherwise."""
        if cls._slo_engine is None:
            from deeplearning4j_tpu.observability import slo as _slo_mod

            cls._slo_engine = _slo_mod.BurnRateEngine()
        agg = cls._fleet_aggregator()
        if agg is not None:
            text = agg.federate_metrics()
        else:
            from deeplearning4j_tpu import observability as obs

            text = obs.metrics.to_prometheus()
        return cls._slo_engine.report(text)

    def log_message(self, *args):  # quiet
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _html(self, page: str) -> None:
        body = page.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        storage = type(self).storage
        path = urlparse(self.path).path
        if path == "/api/tsne":
            # t-SNE coord upload (reference: TsneModule's file upload).
            # HTTP writes are gated like /remote — same explicit-enable
            # policy; in-process callers use UIServer.upload_tsne.
            if not type(self).enable_remote:
                return self._json({"error": "remote writes disabled "
                                   "(UIServer(enable_remote=True))"}, 403)
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length))
                coords = payload["coords"]
                if not coords or len(coords[0]) != 2:
                    raise ValueError("coords must be a [N, 2] list")
                type(self).tsne_data = {
                    "coords": coords,
                    "labels": payload.get("labels"),
                    "point_names": payload.get("point_names"),
                    "name": payload.get("name"),
                }
            except Exception as e:
                return self._json({"error": str(e)}, 400)
            return self._json({"ok": True, "n": len(coords)})
        # Remote-receiver endpoint (reference: `RemoteReceiverModule` —
        # must be explicitly enabled, like the reference's enable flag).
        if path != "/remote":
            return self._json({"error": "not found"}, 404)
        if not type(self).enable_remote:
            return self._json({"error": "remote receiver disabled"}, 403)
        if storage is None:
            return self._json({"error": "no storage attached"}, 503)
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length))
            record = payload["record"]
            if payload.get("type") == "static":
                storage.put_static_info(record)
            else:
                storage.put_update(record)
        except Exception as e:
            return self._json({"error": str(e)}, 400)
        self._json({"ok": True})

    def do_GET(self):
        url = urlparse(self.path)
        q = parse_qs(url.query)
        sid = (q.get("sid") or [None])[0]
        storage = type(self).storage
        if url.path in ("/", "/train", "/index.html"):
            self._html(_PAGE)
        elif url.path == "/histogram":
            self._html(_HISTOGRAM_PAGE)
        elif url.path == "/model":
            self._html(_MODEL_PAGE)
        elif url.path == "/system":
            self._html(_SYSTEM_PAGE)
        elif url.path == "/flow":
            self._html(_FLOW_PAGE)
        elif url.path == "/tsne":
            self._html(_TSNE_PAGE)
        elif url.path == "/activations":
            self._html(_ACTIVATIONS_PAGE)
        elif url.path == "/api/tsne":
            self._json(type(self).tsne_data or {})
        elif url.path == "/api/sessions":
            self._json(storage.list_session_ids() if storage else [])
        elif url.path == "/api/static":
            info = storage.get_static_info(sid) if storage and sid else None
            self._json(info or {})
        elif url.path == "/api/updates":
            ups = storage.get_updates(sid) if storage and sid else []
            self._json(ups)
        elif url.path == "/metrics":
            from deeplearning4j_tpu import observability as obs

            body, ctype = obs.prometheus_payload(
                (q.get("format") or ["prometheus"])[0])
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif url.path == "/api/trace":
            from deeplearning4j_tpu import observability as obs

            self._json(obs.tracer.export_chrome())
        elif url.path in ("/api/fleet/metrics", "/api/fleet/trace"):
            agg = type(self)._fleet_aggregator()
            if agg is None:
                return self._json(
                    {"error": "no coordinator attached "
                              "(UIServer(coordinator_address=...))"}, 503)
            try:
                if url.path.endswith("/metrics"):
                    body = agg.federate_metrics().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._json(agg.federate_trace())
            except Exception as e:
                self._json({"error": f"{type(e).__name__}: {e}"}, 502)
        elif url.path == "/api/flight":
            from deeplearning4j_tpu import observability as obs

            self._json(obs.flight.status())
        elif url.path == "/api/memory":
            from deeplearning4j_tpu.observability import memory as obsmem

            self._json(obsmem.report())
        elif url.path == "/api/slo":
            try:
                self._json(type(self)._slo())
            except Exception as e:
                self._json({"error": f"{type(e).__name__}: {e}"}, 502)
        elif url.path == "/api":
            self._json({"routes": _ROUTES})
        else:
            self._json({"error": "not found", "routes": _ROUTES}, 404)


# Route index served by /api and echoed in 404 bodies.
_ROUTES = [
    "/", "/histogram", "/model", "/system", "/flow", "/tsne",
    "/activations", "/metrics", "/api", "/api/sessions", "/api/static",
    "/api/updates", "/api/tsne", "/api/trace", "/api/flight", "/api/memory",
    "/api/slo", "/api/fleet/metrics", "/api/fleet/trace",
    "POST /remote", "POST /api/tsne",
]


class UIServer:
    """Reference: `PlayUIServer` / `UIServer.getInstance()`."""

    def __init__(self, port: int = 9000, host: str = "127.0.0.1",
                 enable_remote: bool = False,
                 coordinator_address: Optional[str] = None):
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._handler = type("BoundHandler", (_Handler,),
                             {"enable_remote": bool(enable_remote),
                              "coordinator_address": coordinator_address,
                              "_fleet_agg": None})

    def attach(self, storage: StatsStorage) -> "UIServer":
        self._handler.storage = storage
        return self

    def upload_tsne(self, coords, labels=None, point_names=None,
                    name: Optional[str] = None) -> "UIServer":
        """Publish a t-SNE embedding to the `/tsne` page (compute coords
        with `plot.tsne.Tsne().fit_transform(X)`). In-process equivalent
        of POSTing to `/api/tsne`."""
        import numpy as np

        coords = np.asarray(coords, float)
        if coords.ndim != 2 or coords.shape[1] != 2:
            raise ValueError(f"coords must be [N, 2], got {coords.shape}")
        self._handler.tsne_data = {
            "coords": coords.tolist(),
            "labels": None if labels is None else list(labels),
            "point_names": None if point_names is None else list(point_names),
            "name": name,
        }
        return self

    def start(self) -> "UIServer":
        self._httpd = ThreadingHTTPServer((self.host, self.port), self._handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
