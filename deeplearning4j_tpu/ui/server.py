"""Training UI server.

TPU-lite equivalent of the reference's Play-framework UI
(`deeplearning4j-play/.../PlayUIServer.java:53,183` + the train module
`ui/module/train/TrainModule.java:92-99`): a stdlib `http.server` app that
attaches to a `StatsStorage` and serves
- `/`                    — overview page (score curve, throughput, per-layer
                           mean magnitudes, memory) rendered with inline JS
- `/histogram`           — per-parameter distribution bars from the latest
                           sampled update (reference histogram module,
                           `HistogramModule`)
- `/model`               — model overview table: layers, types, hyperparams
                           from the static-info config JSON (reference
                           `TrainModule.java:92-99` model route)
- `/system`              — device memory / host RSS / throughput charts
                           (reference `TrainModule` system tab)
- `/api/sessions`        — session ids
- `/api/static?sid=`     — model static info
- `/api/updates?sid=`    — the full update stream as JSON
- `POST /remote`         — remote-receiver endpoint for
                           `RemoteStatsStorageRouter` (reference
                           `RemoteReceiverModule`); enable with
                           `UIServer(enable_remote=True)`

Usage (mirrors `UIServer.getInstance().attach(statsStorage)`):

    server = UIServer(port=9000).attach(storage).start()
    ...
    server.stop()
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.api.storage import StatsStorage

_STYLE = """<style>
 body { font-family: sans-serif; margin: 2em; background: #fafafa; }
 h1 { font-size: 1.3em; } h2 { font-size: 1.05em; margin-top: 1.5em; }
 .chart { border: 1px solid #ccc; background: #fff; }
 #meta { color: #555; font-size: 0.9em; white-space: pre-line; }
</style>"""

_NAV = ("<div id=nav><a href=/>overview</a> | <a href=/histogram>histograms</a> | <a href=/model>model</a> | <a href=/system>system</a></div>")

# Shared canvas line-chart renderer, interpolated into every page.
_CHART_JS = """function drawSeries(canvas, series, labels) {
  const ctx = canvas.getContext('2d');
  ctx.clearRect(0, 0, canvas.width, canvas.height);
  const all = series.flatMap(s => s.pts.map(p => p[1]))
      .filter(v => isFinite(v));
  if (!all.length) return;
  const xs = series.flatMap(s => s.pts.map(p => p[0]));
  const xmin = Math.min(...xs), xmax = Math.max(...xs) || 1;
  const ymin = Math.min(...all), ymax = Math.max(...all);
  const px = x => 40 + (canvas.width - 50) * (x - xmin) / Math.max(1, xmax - xmin);
  const py = y => canvas.height - 20 - (canvas.height - 40) *
      (y - ymin) / Math.max(1e-12, ymax - ymin);
  const colors = ['#1565c0','#c62828','#2e7d32','#6a1b9a','#ef6c00','#00838f'];
  series.forEach((s, i) => {
    ctx.strokeStyle = colors[i % colors.length];
    ctx.beginPath();
    s.pts.forEach((p, j) => j ? ctx.lineTo(px(p[0]), py(p[1]))
                              : ctx.moveTo(px(p[0]), py(p[1])));
    ctx.stroke();
    ctx.fillStyle = ctx.strokeStyle;
    ctx.fillText(s.name, 45 + 150 * i, 12);
  });
  ctx.fillStyle = '#333';
  ctx.fillText(ymax.toPrecision(4), 2, 14);
  ctx.fillText(ymin.toPrecision(4), 2, canvas.height - 8);
}
"""


_PAGE = """<!doctype html>
<html><head><title>deeplearning4j-tpu training UI</title>
{style}</head>
<body>
<h1>deeplearning4j-tpu training UI</h1>
{nav}
<div id="meta">loading…</div>
<h2>Score</h2><canvas id="score" class="chart" width="860" height="240"></canvas>
<h2>Per-layer mean magnitudes (updates)</h2>
<canvas id="mm" class="chart" width="860" height="240"></canvas>
<script>
{chart_js}async function refresh() {
  const sessions = await (await fetch('api/sessions')).json();
  if (!sessions.length) return;
  const sid = sessions[sessions.length - 1];
  const updates = await (await fetch('api/updates?sid=' + sid)).json();
  const info = await (await fetch('api/static?sid=' + sid)).json();
  const last = updates[updates.length - 1] || {};
  document.getElementById('meta').textContent =
    'session ' + sid + ' — ' + (info.model_class || '?') + ', ' +
    (info.num_params || '?') + ' params — ' + updates.length + ' samples' +
    (last.iterations_per_sec ?
     ' — ' + last.iterations_per_sec.toFixed(2) + ' it/s' : '') +
    (last.device_memory ? ' — mem ' +
     (last.device_memory.bytes_in_use / 1048576).toFixed(0) + ' MiB' : '');
  drawSeries(document.getElementById('score'),
    [{name: 'score', pts: updates.map(u => [u.iteration, u.score])}]);
  const layers = {};
  updates.forEach(u => {
    Object.entries(u.layer_stats || {}).forEach(([lk, ps]) => {
      Object.entries(ps).forEach(([pn, d]) => {
        const key = lk + '/' + pn;
        (layers[key] = layers[key] || []).push([u.iteration, d.update_mm]);
      });
    });
  });
  drawSeries(document.getElementById('mm'),
    Object.entries(layers).slice(0, 6)
      .map(([name, pts]) => ({name, pts})));
}
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""


_SYSTEM_PAGE = """<!doctype html>
<html><head><title>system — deeplearning4j-tpu UI</title>
{style}</head>
<body>
<h1>System (reference: TrainModule system tab)</h1>
{nav}
<div id="meta">loading…</div>
<h2>Device memory in use (MiB)</h2>
<canvas id="dev" class="chart" width="860" height="220"></canvas>
<h2>Host process RSS (MiB)</h2>
<canvas id="host" class="chart" width="860" height="220"></canvas>
<h2>Throughput (iterations/sec)</h2>
<canvas id="tput" class="chart" width="860" height="220"></canvas>
<script>
{chart_js}
async function refresh() {
  const sessions = await (await fetch('api/sessions')).json();
  if (!sessions.length) return;
  const sid = sessions[sessions.length - 1];
  const updates = await (await fetch('api/updates?sid=' + sid)).json();
  const info = await (await fetch('api/static?sid=' + sid)).json();
  document.getElementById('meta').textContent =
    'session ' + sid + ' — ' + (info.model_class || '?') + ' — ' +
    updates.length + ' samples';
  drawSeries(document.getElementById('dev'),
    [{name: 'bytes_in_use', pts: updates.filter(u => u.device_memory)
      .map(u => [u.iteration, u.device_memory.bytes_in_use / 1048576])}]);
  drawSeries(document.getElementById('host'),
    [{name: 'host_rss_mb', pts: updates.filter(u => u.host_rss_mb)
      .map(u => [u.iteration, u.host_rss_mb])}]);
  drawSeries(document.getElementById('tput'),
    [{name: 'it/s', pts: updates.filter(u => u.iterations_per_sec)
      .map(u => [u.iteration, u.iterations_per_sec])}]);
}
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""


_HISTOGRAM_PAGE = """<!doctype html>
<html><head><title>parameter histograms</title>
<style>
 body { font-family: sans-serif; margin: 2em; background: #fafafa; }
 h1 { font-size: 1.3em; } h2 { font-size: 0.95em; margin: 1.2em 0 0.2em; }
 .chart { border: 1px solid #ccc; background: #fff; }
 a { color: #1565c0; }
</style></head>
<body>
<h1>Parameter histograms <a href="/">overview</a> <a href="/model">model</a> <a href="/system">system</a></h1>
<div id="charts">loading…</div>
<script>
function drawHist(canvas, hist) {
  const ctx = canvas.getContext('2d');
  const n = hist.counts.length, peak = Math.max(...hist.counts, 1);
  const w = (canvas.width - 60) / n;
  ctx.fillStyle = '#1565c0';
  hist.counts.forEach((c, i) => {
    const h = (canvas.height - 24) * c / peak;
    ctx.fillRect(30 + i * w, canvas.height - 12 - h, w - 1, h);
  });
  ctx.fillStyle = '#333';
  ctx.fillText(hist.min.toPrecision(3), 2, canvas.height - 2);
  ctx.fillText(hist.max.toPrecision(3), canvas.width - 55, canvas.height - 2);
}
async function refresh() {
  const sessions = await (await fetch('api/sessions')).json();
  if (!sessions.length) return;
  const updates = await (await fetch('api/updates?sid=' +
      sessions[sessions.length - 1])).json();
  const last = [...updates].reverse().find(u => u.param_histograms);
  if (!last) return;
  const div = document.getElementById('charts');
  div.textContent = '';
  Object.entries(last.param_histograms).forEach(([name, hist]) => {
    const h2 = document.createElement('h2');
    h2.textContent = name + ' (iteration ' + last.iteration + ')';
    const c = document.createElement('canvas');
    c.className = 'chart'; c.width = 420; c.height = 110;
    div.appendChild(h2); div.appendChild(c);
    drawHist(c, hist);
  });
}
refresh(); setInterval(refresh, 5000);
</script></body></html>
"""

_MODEL_PAGE = """<!doctype html>
<html><head><title>model overview</title>
<style>
 body { font-family: sans-serif; margin: 2em; background: #fafafa; }
 h1 { font-size: 1.3em; } a { color: #1565c0; }
 table { border-collapse: collapse; background: #fff; }
 td, th { border: 1px solid #ccc; padding: 4px 10px; font-size: 0.9em; }
 th { background: #eee; }
 pre { background: #fff; border: 1px solid #ccc; padding: 8px;
       max-width: 900px; overflow: auto; font-size: 0.8em; }
</style></head>
<body>
<h1>Model <a href="/">overview</a> <a href="/histogram">histograms</a> <a href="/system">system</a></h1>
<div id="meta"></div>
<table id="layers"><tr><th>#</th><th>layer</th><th>type</th>
<th>n_in</th><th>n_out</th><th>activation</th></tr></table>
<h2>Config JSON</h2><pre id="json"></pre>
<script>
async function refresh() {
  const sessions = await (await fetch('api/sessions')).json();
  if (!sessions.length) return;
  const info = await (await fetch('api/static?sid=' +
      sessions[sessions.length - 1])).json();
  document.getElementById('meta').textContent =
    (info.model_class || '?') + ' — ' + (info.num_params || '?') + ' params';
  if (!info.model_config_json) return;
  const conf = JSON.parse(info.model_config_json);
  document.getElementById('json').textContent =
    JSON.stringify(conf, null, 1);
  const layers = conf.layers ||
    Object.entries(conf.vertices || {}).map(([k, v]) => v.layer ?
      Object.assign({name: k}, v.layer) : {name: k, '@class': v['@class']});
  const table = document.getElementById('layers');
  while (table.rows.length > 1) table.deleteRow(1);
  (layers || []).forEach((l, i) => {
    const r = table.insertRow();
    [i, l.name || '', l['@class'] || '?', l.n_in || '', l.n_out || '',
     l.activation || ''].forEach(v => r.insertCell().textContent = v);
  });
}
refresh();
</script></body></html>
"""


for _n in ("_PAGE", "_HISTOGRAM_PAGE", "_MODEL_PAGE", "_SYSTEM_PAGE"):
    globals()[_n] = (globals()[_n]
                     .replace("{style}", _STYLE)
                     .replace("{chart_js}", _CHART_JS)
                     .replace("{nav}", _NAV))


class _Handler(BaseHTTPRequestHandler):
    storage: Optional[StatsStorage] = None
    enable_remote: bool = False

    def log_message(self, *args):  # quiet
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _html(self, page: str) -> None:
        body = page.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        # Remote-receiver endpoint (reference: `RemoteReceiverModule` —
        # must be explicitly enabled, like the reference's enable flag).
        storage = type(self).storage
        if urlparse(self.path).path != "/remote":
            return self._json({"error": "not found"}, 404)
        if not type(self).enable_remote:
            return self._json({"error": "remote receiver disabled"}, 403)
        if storage is None:
            return self._json({"error": "no storage attached"}, 503)
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length))
            record = payload["record"]
            if payload.get("type") == "static":
                storage.put_static_info(record)
            else:
                storage.put_update(record)
        except Exception as e:
            return self._json({"error": str(e)}, 400)
        self._json({"ok": True})

    def do_GET(self):
        url = urlparse(self.path)
        q = parse_qs(url.query)
        sid = (q.get("sid") or [None])[0]
        storage = type(self).storage
        if url.path in ("/", "/train", "/index.html"):
            self._html(_PAGE)
        elif url.path == "/histogram":
            self._html(_HISTOGRAM_PAGE)
        elif url.path == "/model":
            self._html(_MODEL_PAGE)
        elif url.path == "/system":
            self._html(_SYSTEM_PAGE)
        elif url.path == "/api/sessions":
            self._json(storage.list_session_ids() if storage else [])
        elif url.path == "/api/static":
            info = storage.get_static_info(sid) if storage and sid else None
            self._json(info or {})
        elif url.path == "/api/updates":
            ups = storage.get_updates(sid) if storage and sid else []
            self._json(ups)
        else:
            self._json({"error": "not found"}, 404)


class UIServer:
    """Reference: `PlayUIServer` / `UIServer.getInstance()`."""

    def __init__(self, port: int = 9000, host: str = "127.0.0.1",
                 enable_remote: bool = False):
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._handler = type("BoundHandler", (_Handler,),
                             {"enable_remote": bool(enable_remote)})

    def attach(self, storage: StatsStorage) -> "UIServer":
        self._handler.storage = storage
        return self

    def start(self) -> "UIServer":
        self._httpd = ThreadingHTTPServer((self.host, self.port), self._handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
