"""StatsListener: rich periodic training telemetry.

TPU-native equivalent of the reference's
`deeplearning4j-ui-model/.../stats/BaseStatsListener.java:43,273`: every N
iterations it samples score, per-layer parameter/gradient/update mean
magnitudes and histograms, per-step wall time, throughput, learning-rate
info and device memory, and routes the record through a
`StatsStorageRouter` (`api/storage.py`). Where the reference pulls
gradients off the host model object, here gradient/update magnitudes are
computed INSIDE the jitted train step (only scalars leave the device —
`MultiLayerNetwork._train_step(collect_stats=True)`); histograms are taken
from the params pytree on the sampled iterations only.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, Optional

import numpy as np

from deeplearning4j_tpu.api.storage import StatsStorageRouter
from deeplearning4j_tpu.optimize.listeners import IterationListener


def _host_rss_mb():
    """CURRENT process resident-set size in MiB (the process-level analog
    of the reference BaseStatsListener's JVM memory reporting). Prefers
    /proc/self/statm (live value, Linux); falls back to getrusage peak RSS
    with the platform's unit (KiB on Linux, bytes on macOS)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        import os

        return pages * os.sysconf("SC_PAGE_SIZE") / 1048576.0
    except Exception:
        pass
    try:
        import resource
        import sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return peak / (1048576.0 if sys.platform == "darwin" else 1024.0)
    except Exception:
        return None


class StatsListener(IterationListener):
    """See module docstring. `frequency` = sample every N iterations."""

    requires_training_stats = True

    def __init__(self, storage: StatsStorageRouter, frequency: int = 10,
                 session_id: Optional[str] = None, worker_id: str = "worker_0",
                 collect_histograms: bool = True, histogram_bins: int = 20):
        self.storage = storage
        self.frequency = max(1, int(frequency))
        self.session_id = session_id or f"session_{uuid.uuid4().hex[:12]}"
        self.worker_id = worker_id
        self.collect_histograms = collect_histograms
        self.histogram_bins = int(histogram_bins)
        self._static_sent = False
        self._last_time: Optional[float] = None
        self._last_iter = 0

    # ------------------------------------------------------------- helpers

    def _send_static(self, model) -> None:
        info: Dict[str, Any] = {
            "session_id": self.session_id,
            "worker_id": self.worker_id,
            "model_class": type(model).__name__,
            "num_params": int(model.num_params()),
        }
        try:
            info["model_config_json"] = model.conf.to_json()
        except Exception:
            pass
        self.storage.put_static_info(info)
        self._static_sent = True

    def _histogram(self, arr: np.ndarray):
        counts, edges = np.histogram(arr, bins=self.histogram_bins)
        return {"min": float(edges[0]), "max": float(edges[-1]),
                "counts": counts.tolist()}

    @staticmethod
    def _device_memory() -> Optional[Dict[str, int]]:
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
            if not stats:
                return None
            return {k: int(v) for k, v in stats.items()
                    if k in ("bytes_in_use", "peak_bytes_in_use",
                             "bytes_limit", "largest_alloc_size")}
        except Exception:
            return None

    # ---------------------------------------------------------------- hook

    def iteration_done(self, model, iteration: int) -> None:
        if not self._static_sent:
            self._send_static(model)
        if iteration % self.frequency != 0:
            return
        now = time.perf_counter()
        record: Dict[str, Any] = {
            "session_id": self.session_id,
            "worker_id": self.worker_id,
            "iteration": int(iteration),
            "score": float(model.score_value),
        }
        if self._last_time is not None and iteration > self._last_iter:
            dt = now - self._last_time
            record["iterations_per_sec"] = (iteration - self._last_iter) / dt
            record["ms_per_iteration"] = 1000.0 * dt / (iteration - self._last_iter)
        self._last_time = now
        self._last_iter = iteration

        # In-jit gradient/update/param mean magnitudes (device scalars).
        tstats = getattr(model, "last_training_stats", None)
        if tstats:
            record["layer_stats"] = {
                lk: {pn: {k: float(v) for k, v in d.items()}
                     for pn, d in lstats.items()}
                for lk, lstats in tstats.items()
            }
        if self.collect_histograms:
            hists: Dict[str, Any] = {}
            for lk, lparams in model.params_tree.items():
                for pn, arr in lparams.items():
                    hists[f"{lk}/{pn}"] = self._histogram(
                        np.asarray(arr, dtype="float32").ravel())
            record["param_histograms"] = hists
        mem = self._device_memory()
        if mem:
            record["device_memory"] = mem
        rss = _host_rss_mb()
        if rss is not None:
            record["host_rss_mb"] = rss
        self.storage.put_update(record)


class ConvolutionalListener(IterationListener):
    """Sample convolutional activation grids for the UI's `/activations`
    page (reference: `ui/module/convolutional/ConvolutionalListenerModule`
    fed by `ConvolutionalIterationListener` — activation maps rendered as
    image grids).

    The reference listener grabs the live minibatch's activations off the
    mutable model; the jitted engines don't keep batches around, so this
    listener carries its own fixed `probe_input` (one example is enough)
    and runs a forward pass on the sampled iterations. 4-D [1, H, W, C]
    activations are strided down to `max_hw` per side and capped at
    `max_channels`, then shipped as row-major float lists in the update
    record under `conv_activations`.

    Pass the StatsListener's `session_id` when using both, so the UI sees
    one merged update stream."""

    def __init__(self, storage: StatsStorageRouter, probe_input,
                 frequency: int = 25, session_id: Optional[str] = None,
                 max_hw: int = 24, max_channels: int = 16):
        self.storage = storage
        self.probe = np.asarray(probe_input)[:1]
        self.frequency = max(1, int(frequency))
        self.session_id = session_id or f"session_{uuid.uuid4().hex[:12]}"
        self.max_hw = int(max_hw)
        self.max_channels = int(max_channels)

    def iteration_done(self, model, iteration: int) -> None:
        if iteration % self.frequency != 0:
            return
        acts = model.feed_forward(self.probe)
        grids: Dict[str, Any] = {}
        names = getattr(model, "layer_keys", None) or [
            f"layer_{i}" for i in range(len(acts))]
        for name, a in zip(names, acts):
            a = np.asarray(a, dtype="float32")
            if a.ndim != 4:  # NHWC conv activations only
                continue
            a = a[0]
            # Ceil division: guarantees <= max_hw per side (floor under-
            # strides, e.g. 47//24 == 1 would ship a 47x47 grid).
            sh = max(1, -(-a.shape[0] // self.max_hw))
            sw = max(1, -(-a.shape[1] // self.max_hw))
            a = a[::sh, ::sw, : self.max_channels]
            grids[name] = {
                "h": int(a.shape[0]), "w": int(a.shape[1]),
                "channels": [a[:, :, c].ravel().tolist()
                             for c in range(a.shape[2])],
            }
        if grids:
            self.storage.put_update({
                "session_id": self.session_id,
                "iteration": int(iteration),
                "conv_activations": grids,
            })


class ProfilerListener(IterationListener):
    """Opt-in `jax.profiler` trace around a window of iterations — the
    XPlane-level analog of the reference's per-phase timing stats
    (SURVEY.md §5 tracing). Produces a TensorBoard-loadable trace dir."""

    def __init__(self, log_dir: str, start_iteration: int = 10,
                 num_iterations: int = 5):
        self.log_dir = log_dir
        self.start_iteration = int(start_iteration)
        self.stop_iteration = int(start_iteration + num_iterations)
        self._active = False

    def iteration_done(self, model, iteration: int) -> None:
        import jax

        if not self._active and iteration == self.start_iteration:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif self._active and iteration >= self.stop_iteration:
            jax.block_until_ready(model.params_tree)
            jax.profiler.stop_trace()
            self._active = False

    def on_epoch_end(self, model) -> None:
        if self._active:  # never leak an open trace
            import jax

            jax.profiler.stop_trace()
            self._active = False
