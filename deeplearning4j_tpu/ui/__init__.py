"""Observability: StatsListener telemetry + training UI server.

TPU-native replacement for the reference's `deeplearning4j-ui-parent`
(`BaseStatsListener.java`, `PlayUIServer.java`) — see `ui/stats.py` and
`ui/server.py`.
"""

from deeplearning4j_tpu.ui.stats import ProfilerListener, StatsListener  # noqa: F401
from deeplearning4j_tpu.ui.server import UIServer  # noqa: F401
