"""CheckpointManager: step-named sharded checkpoints with retention and
async off-thread saves.

Directory layout under the manager root:

    root/
      step_00000005/   <- committed (has COMMIT)
      step_00000010/
      step_00000015.tmp/  <- half-written save (crash): never listed

`latest()`/`all_steps()` only ever see COMMITTED steps whose manifest
validates (`store.verify_checkpoint` — existence + byte sizes), so a
truncated chunk, a missing COMMIT, or a half-written `.tmp` directory all
degrade to "that step doesn't exist" and the manager falls back to the last
good one; `restore()` of an explicitly named bad step raises the clean
`CheckpointCorruptError` instead.

Retention = keep-last-k AND keep-every-m: the newest `keep_last` steps
always survive; with `keep_every=m > 0`, steps divisible by m are kept
forever (the long-horizon audit trail). Saves snapshot on the caller's
thread (donated buffers) and write on a single background worker, bounded
to one in-flight snapshot — same discipline as `util/checkpoint.py`'s
`CheckpointListener`.
"""

from __future__ import annotations

import os
import re
import shutil
import threading
from typing import List, Optional

import warnings

from deeplearning4j_tpu.checkpoint import store
from deeplearning4j_tpu.checkpoint.array_store import (
    CheckpointCorruptError, CheckpointError)
from deeplearning4j_tpu import observability as _obs
from deeplearning4j_tpu.observability import elastic as _elastic
from deeplearning4j_tpu.util.retry import with_retries

_STEP_RE = re.compile(r"^step_(\d+)$")

_M_SAVES = _obs.metrics.counter(
    "dl4j_checkpoint_saves_total", "Committed checkpoint saves")
_M_RESTORES = _obs.metrics.counter(
    "dl4j_checkpoint_restores_total", "Checkpoint restores")
_M_BYTES_W = _obs.metrics.counter(
    "dl4j_checkpoint_bytes_written_total",
    "Array bytes captured into committed checkpoints")
_M_BYTES_R = _obs.metrics.counter(
    "dl4j_checkpoint_bytes_read_total",
    "Committed checkpoint bytes read by restores (manifest sizes)")
_M_QUEUE = _obs.metrics.gauge(
    "dl4j_checkpoint_queue_depth",
    "In-flight async checkpoint writes (bounded to 1 by design)")


def _snap_nbytes(snap) -> int:
    try:
        return sum(chunk[1].nbytes for leaf in snap["leaves"]
                   for chunk in leaf["chunks"])
    except Exception:
        return 0


class CheckpointManager:
    def __init__(self, directory: str, keep_last: int = 3,
                 keep_every: int = 0, async_save: bool = True,
                 mesh=None, model_axis: Optional[str] = None, context=None,
                 save_every: int = 0):
        self.directory = str(directory)
        self.keep_last = int(keep_last)
        self.keep_every = int(keep_every)
        self.async_save = bool(async_save)
        # Cadence for `maybe_save`: a checkpoint every `save_every` steps
        # (0 = cadence disabled, every `maybe_save` is a no-op). The
        # elastic supervisor drives this from its step loop so recovery
        # loses at most `save_every` steps of work.
        self.save_every = int(save_every)
        self.mesh = mesh
        self.model_axis = model_axis
        self.context = context
        os.makedirs(self.directory, exist_ok=True)
        self._inflight: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    # ----------------------------------------------------------- discovery

    def step_path(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{int(step):08d}")

    def all_steps(self) -> List[int]:
        """Committed, validating steps, ascending."""
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if not m:
                continue
            try:
                store.verify_checkpoint(os.path.join(self.directory, name))
            except CheckpointError:
                continue
            steps.append(int(m.group(1)))
        return sorted(steps)

    def latest(self) -> Optional[int]:
        """Newest committed step (None if nothing committed yet). A newer
        corrupt/uncommitted save never shadows an older good one."""
        steps = self.all_steps()
        return steps[-1] if steps else None

    def latest_path(self) -> Optional[str]:
        step = self.latest()
        return None if step is None else self.step_path(step)

    def candidate_steps(self) -> List[int]:
        """Every step-named directory, descending, WITHOUT the validation
        filter of `all_steps()` — the restore-fallback walk wants to *see*
        a corrupt newest step (to warn and count it) rather than have
        discovery silently hide it."""
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps, reverse=True)

    # ---------------------------------------------------------------- save

    def save(self, net, step: Optional[int] = None) -> str:
        """Checkpoint `net` at `step` (default: its iteration counter).
        The device->host snapshot happens here, synchronously; the chunk
        writes + commit run on the background worker unless
        `async_save=False`. Returns the (future) committed path."""
        self.flush()  # bound to one in-flight snapshot; surface old errors
        step = int(net.iteration if step is None else step)
        with _obs.tracer.span("checkpoint.snapshot", cat="checkpoint",
                              step=step):
            snap = store.snapshot_net(net)
        nbytes = _snap_nbytes(snap)
        path = self.step_path(step)

        def write_committed():
            # Transient storage blips (NFS/GCS) must not kill training:
            # retried with backoff; `write_snapshot` clears its stale
            # `.tmp` on entry so a retry restarts from a clean slate.
            with _obs.tracer.span("checkpoint.write", cat="checkpoint",
                                  step=step, bytes=nbytes):
                with_retries(lambda: store.write_snapshot(snap, path),
                             retry_on=(OSError,),
                             describe=f"checkpoint write step {step}")
            _M_BYTES_W.inc(nbytes)
            _M_SAVES.inc()
            self._apply_retention()

        def work():
            try:
                write_committed()
            except BaseException as e:  # surfaced on next save()/flush()
                self._error = e
            finally:
                _M_QUEUE.set(0)

        if self.async_save:
            _M_QUEUE.set(1)
            self._inflight = threading.Thread(target=work, daemon=True)
            self._inflight.start()
        else:
            write_committed()
        return path

    def maybe_save(self, net, step: Optional[int] = None) -> Optional[str]:
        """Cadence hook: checkpoint iff `save_every > 0` and the step
        lands on the cadence. Step 0 never saves (nothing learned yet)."""
        step = int(net.iteration if step is None else step)
        if self.save_every <= 0 or step <= 0 or step % self.save_every:
            return None
        return self.save(net, step)

    def flush(self) -> None:
        """Wait for the in-flight save; re-raise any background failure."""
        if self._inflight is not None:
            self._inflight.join()
            self._inflight = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def _apply_retention(self) -> None:
        steps = self.all_steps()
        if self.keep_last <= 0:
            return
        keep = set(steps[-self.keep_last:])
        if self.keep_every > 0:
            keep.update(s for s in steps if s % self.keep_every == 0)
        for s in steps:
            if s not in keep:
                shutil.rmtree(self.step_path(s), ignore_errors=True)

    # ------------------------------------------------------------- restore

    def restore(self, step: Optional[int] = None, net=None,
                load_updater: bool = True):
        """Restore `step` (default: newest, WITH corruption fallback) onto
        the manager's mesh/context — the ELASTIC path: the mesh here may
        be any shape, not the one that saved.

        When `step` is None the walk starts from the newest step-named
        directory and falls back past every step whose chunks fail the
        corruption checks (truncated chunk, missing COMMIT, torn write) —
        warning and counting `dl4j_elastic_events_total{event=
        restore_fallback}` per damaged step, so "restore quietly served
        yesterday's checkpoint" is visible, not silent. An explicitly
        named bad step still raises `CheckpointCorruptError`: the caller
        asked for THAT step."""
        self.flush()
        if step is not None:
            return self._restore_one(int(step), net, load_updater)
        candidates = self.candidate_steps()
        if not candidates:
            raise CheckpointError(
                f"no committed checkpoint under {self.directory}")
        last_err: Optional[BaseException] = None
        for i, cand in enumerate(candidates):
            try:
                return self._restore_one(cand, net, load_updater)
            except CheckpointCorruptError as e:
                last_err = e
                warnings.warn(
                    f"checkpoint step {cand} failed corruption checks "
                    f"({e}); falling back to previous committed step",
                    RuntimeWarning, stacklevel=2)
                _elastic.record_event(
                    "restore_fallback", step=int(cand),
                    error=f"{type(e).__name__}: {e}")
        raise CheckpointCorruptError(
            f"all {len(candidates)} checkpoint steps under "
            f"{self.directory} failed corruption checks") from last_err

    def _restore_one(self, step: int, net, load_updater: bool):
        path = self.step_path(step)
        # Verify BEFORE loading: a truncated chunk must surface as the
        # clean CheckpointCorruptError the fallback walk routes around,
        # not as a mid-load unpickling crash with device arrays half-set.
        manifest = store.verify_checkpoint(path)
        with _obs.tracer.span("checkpoint.restore", cat="checkpoint",
                              step=int(step)):
            result = store.restore_checkpoint(
                path, net=net, mesh=self.mesh,
                model_axis=self.model_axis, context=self.context,
                load_updater=load_updater)
        try:
            _M_BYTES_R.inc(sum(manifest["files"].values()))
        except Exception:
            pass
        _M_RESTORES.inc()
        return result
