"""Sharded elastic checkpoint store (SURVEY §5: "orbax-style sharded
checkpoint of a params pytree + opt state", "elastic checkpoint-resume").

Three layers:

- `array_store`: each device shard of every leaf is its own raw chunk file;
  `index.json` maps chunks to global coordinates — save I/O parallelizes
  per shard, nothing materializes the full array on one host;
- `store`: atomic commit protocol (`step_N.tmp/` + fsync + COMMIT manifest
  + rename) and elastic restore (assemble chunks straight into the TARGET
  mesh's sharding, whatever shape saved them);
- `manager`: `CheckpointManager` — step naming, keep-last-k / keep-every-m
  retention, async off-thread saves, `latest()` that only ever sees
  committed, validating steps.

`legacy.load_any` opens either this format or the old `model_serializer`
ZIPs; `legacy.migrate_zip` converts old checkpoints forward.
`adapters` persists LoRA deltas (`nn/lora.py`) as tiny base-fingerprint-
pinned checkpoints in the same atomic format.
"""

from deeplearning4j_tpu.checkpoint.adapters import (
    adapter_meta,
    base_fingerprint,
    is_adapter_checkpoint,
    load_adapter,
    save_adapter,
)
from deeplearning4j_tpu.checkpoint.array_store import (
    CheckpointCorruptError,
    CheckpointError,
)
from deeplearning4j_tpu.checkpoint.legacy import load_any, migrate_zip
from deeplearning4j_tpu.checkpoint.manager import CheckpointManager
from deeplearning4j_tpu.checkpoint.quantize import (
    quantize_checkpoint,
    quantize_net,
)
from deeplearning4j_tpu.checkpoint.store import (
    is_sharded_checkpoint,
    restore_checkpoint,
    save_checkpoint,
    verify_checkpoint,
)

__all__ = [
    "CheckpointCorruptError",
    "CheckpointError",
    "CheckpointManager",
    "adapter_meta",
    "base_fingerprint",
    "is_adapter_checkpoint",
    "load_adapter",
    "save_adapter",
    "is_sharded_checkpoint",
    "load_any",
    "migrate_zip",
    "quantize_checkpoint",
    "quantize_net",
    "restore_checkpoint",
    "save_checkpoint",
    "verify_checkpoint",
]
