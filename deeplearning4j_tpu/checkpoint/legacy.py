"""Compat between the sharded store and the legacy `model_serializer` ZIP
format: one loader that opens either, plus a one-shot migrator.

The legacy format (`util/model_serializer.py`) is a single ZIP holding the
FULL flattened float64 param/updater buffers — fine on one host, a wall at
scale. Everything new writes the sharded format; this module keeps every
old checkpoint loadable and offers `migrate_zip` to convert in place-ish
(the ZIP is left untouched; a committed sharded step appears next to it).
"""

from __future__ import annotations

import os
import zipfile
from typing import Optional

from deeplearning4j_tpu.checkpoint import store
from deeplearning4j_tpu.checkpoint.array_store import CheckpointError


def _latest_step_dir(root: str) -> Optional[str]:
    from deeplearning4j_tpu.checkpoint.manager import CheckpointManager

    return CheckpointManager(root).latest_path()


def load_any(path, **restore_kwargs):
    """Open a checkpoint at `path`, whatever it is: a committed sharded
    step directory, a manager root full of steps (picks the latest
    committed), or a legacy `model_serializer`/`util.checkpoint` ZIP.
    Restore kwargs (`mesh`, `context`, ...) apply to the sharded path."""
    path = str(path)
    if os.path.isdir(path):
        if store.is_sharded_checkpoint(path):
            return store.restore_checkpoint(path, **restore_kwargs)
        latest = _latest_step_dir(path)
        if latest is not None:
            return store.restore_checkpoint(latest, **restore_kwargs)
        raise CheckpointError(
            f"{path} is a directory but holds no committed sharded "
            "checkpoint (no COMMIT manifest; half-written .tmp saves are "
            "ignored)")
    if zipfile.is_zipfile(path):
        from deeplearning4j_tpu.util import checkpoint as zip_ckpt

        return zip_ckpt.load_checkpoint(path)
    raise CheckpointError(
        f"{path} is neither a sharded checkpoint directory nor a model ZIP")


def migrate_zip(zip_path: str, directory: str,
                step: Optional[int] = None) -> str:
    """Convert a legacy ZIP checkpoint into a committed sharded step under
    `directory` (default step: the ZIP's iteration counter). Returns the
    new step path; the ZIP is not modified."""
    from deeplearning4j_tpu.checkpoint.manager import CheckpointManager
    from deeplearning4j_tpu.util import checkpoint as zip_ckpt

    net = zip_ckpt.load_checkpoint(zip_path)
    mgr = CheckpointManager(directory, keep_last=0, async_save=False)
    return mgr.save(net, step=step)
