"""Post-training int8 quantization of committed checkpoints (serving).

`quantize_checkpoint(src, dst)` reads a committed sharded checkpoint and
writes a NEW committed checkpoint whose eligible weight leaves are stored
as int8 plus a per-channel f32 scale:

    W  (float32 [in, out])  ->  W        (int8 [in, out])
                                W__scale (float32 [out])

Scheme: per-channel symmetric over the LAST axis — the output-channel axis
for both Dense ([in, out]) and Conv HWIO ([kh, kw, cin, cout]) layouts, so
one scale per output unit. `scale = max|W| / 127` per channel,
`q = clip(round(W / scale), -127, 127)`. Eligible leaves are floating
matrices/tensors (ndim >= 2); biases, gains, and BN running stats stay f32
(negligible bytes, disproportionate accuracy cost).

The quantized checkpoint is a SERVING artifact: updater state is dropped
(you don't resume Adam from int8 weights), and `meta["quantization"]`
marks it so `restore_checkpoint` assembles the params tree from the index
(the f32 template can't pattern-match the extra `__scale` leaves) and so
`serving/host.py` can report the dtype without loading weights. At
inference the int8 tensors live in HBM as-is — ~4x smaller than f32 — and
`nn/params.prep_layer_params` dequantizes `q * scale` at the compute
dtype, fused by XLA into the consuming matmul/conv.

CLI:  python -m deeplearning4j_tpu.checkpoint.quantize <src_step_dir> <dst_step_dir>
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.checkpoint import store as store_mod
from deeplearning4j_tpu.checkpoint.array_store import (
    CheckpointError,
    leaf_chunks,
    read_full,
)

INT8_SCHEME = "int8_per_channel_symmetric"
SCALE_SUFFIX = "__scale"


def quantize_array(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-channel symmetric int8 over the last axis; returns (q, scale)
    with `scale` shaped (w.shape[-1],). All-zero channels get scale 1.0
    (q is zero there anyway) so dequant never divides by zero."""
    w = np.asarray(w, np.float32)
    reduce_axes = tuple(range(w.ndim - 1))
    amax = np.max(np.abs(w), axis=reduce_axes) if reduce_axes else np.abs(w)
    scale = np.where(amax > 0.0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return q, scale


def _eligible(arr: np.ndarray) -> bool:
    return arr.ndim >= 2 and np.issubdtype(arr.dtype, np.floating)


def quantize_tree(params: Dict[str, Any]) -> Dict[str, Any]:
    """In-memory variant: quantize a `{layer: {name: array}}` params tree.
    Eligible leaves become int8 with a `<name>__scale` sibling; everything
    else passes through as f32 host arrays."""
    out: Dict[str, Any] = {}
    for k, v in params.items():
        if isinstance(v, dict):
            out[k] = quantize_tree(v)
            continue
        a = np.asarray(v)
        if _eligible(a):
            q, scale = quantize_array(a)
            out[k] = q
            out[k + SCALE_SUFFIX] = scale
        elif np.issubdtype(a.dtype, np.floating):
            out[k] = a.astype(np.float32)
        else:
            out[k] = a
    return out


def quantize_net(net):
    """Quantize a live net's params IN PLACE for serving (the checkpoint
    path is `quantize_checkpoint`; this covers bench/eval flows that never
    touch disk). Training after this is undefined — serve only."""
    import jax.numpy as jnp

    q = quantize_tree(net.params_tree)
    net.params_tree = {
        lk: {pn: jnp.asarray(a) for pn, a in lp.items()}
        for lk, lp in q.items()
    }
    net._jit_cache = {}
    return net


def quantize_checkpoint(src: str, dst: str,
                        meta_extra: Optional[dict] = None) -> str:
    """Read the committed checkpoint at `src`, write the int8-quantized
    serving checkpoint at `dst` (same atomic commit protocol). Returns
    `dst`."""
    src, dst = str(src), str(dst)
    store_mod.verify_checkpoint(src)
    meta = store_mod.read_meta(src)
    index = store_mod.read_index(src)
    if meta.get("quantization"):
        raise CheckpointError(f"{src} is already quantized")

    leaves = []
    n_quant = 0

    def add(key: str, arr: np.ndarray) -> None:
        chunks = list(leaf_chunks(arr))
        leaves.append({"key": key, "shape": tuple(arr.shape),
                       "dtype": str(arr.dtype), "chunks": chunks})

    for key, entry in index["leaves"].items():
        if key.startswith(store_mod._UPDATER + "/"):
            continue  # serving artifact: optimizer state dropped
        arr = read_full(src, entry)
        if key.startswith(store_mod._PARAMS + "/") and _eligible(arr):
            q, scale = quantize_array(arr)
            add(key, q)
            add(key + SCALE_SUFFIX, scale)
            n_quant += 1
        else:
            arr = np.asarray(arr)
            if np.issubdtype(arr.dtype, np.floating):
                arr = arr.astype(np.float32)
            add(key, arr)

    meta = dict(meta)
    meta["quantization"] = {
        "scheme": INT8_SCHEME,
        "axis": "last",
        "quantized_leaves": n_quant,
    }
    meta.pop("dtype_policy", None)  # weights are int8 now, not policy-typed
    if meta_extra:
        meta.update(meta_extra)
    return store_mod.write_snapshot({"leaves": leaves, "meta": meta}, dst)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.checkpoint.quantize",
        description="Post-training int8 quantization of a committed "
                    "checkpoint (per-channel symmetric, serving-only).")
    ap.add_argument("src", help="committed checkpoint step directory")
    ap.add_argument("dst", help="output directory for the int8 checkpoint")
    args = ap.parse_args(argv)
    out = quantize_checkpoint(args.src, args.dst)
    print(out)
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via CLI test
    raise SystemExit(main())
