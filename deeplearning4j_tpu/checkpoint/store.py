"""Net-level sharded checkpoint: snapshot -> atomic directory commit ->
elastic restore.

On-disk layout of one committed checkpoint step:

    step_00000042/
      COMMIT        <- commit manifest: format/version/step + {file: size};
                       its presence IS the commit — written last, after
                       every other file is fsynced
      meta.json     <- engine kind, full config JSON, iteration/epoch,
                       train-RNG continuation
      index.json    <- per-leaf global shape/dtype + shard->chunk mapping
      chunks/*.bin  <- raw little-endian shard regions (array_store.py)

Atomic commit protocol: everything is written into `step_N.tmp/` and
fsynced, the COMMIT manifest is written (also into the tmp dir, also
fsynced), then ONE `os.rename(step_N.tmp, step_N)` publishes the
checkpoint. A crash at any point leaves either a committed `step_N/` or a
`.tmp` directory that readers ignore — never a readable-looking torn
checkpoint. The manifest records every file's byte size, so a chunk
truncated AFTER commit (disk fault, partial copy) is also detected before
any data is deserialized.

Elastic restore: leaves are assembled from chunks per the index and placed
directly into the sharding the TARGET mesh/`ParallelContext` wants
(`jax.make_array_from_callback` — each device reads only its own region),
so a checkpoint saved on an N-way mesh restores onto an M-way mesh or a
single CPU device without ever building the full model on one host.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.checkpoint.array_store import (
    CHUNK_DIR,
    CheckpointCorruptError,
    CheckpointError,
    leaf_chunks,
    read_full,
    read_region,
    resolve_dtype,
    write_leaf,
    _fsync_write,
)

COMMIT = "COMMIT"
META = "meta.json"
INDEX = "index.json"
FORMAT = "deeplearning4j_tpu/sharded-checkpoint"
VERSION = 1

# Pytree roots captured per checkpoint, keyed by index prefix.
_PARAMS, _UPDATER, _STATE = "params", "updater", "state"


def _path_str(path) -> str:
    """Deterministic string form of a tree_flatten_with_path key path —
    restore matches leaves by this key against the TARGET net's tree, so
    the treedef itself never needs serializing."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:  # pragma: no cover - future key types
            parts.append(str(k))
    return "/".join(parts)


def _flat_items(tree, prefix: str) -> List[Tuple[str, Any]]:
    import jax

    if tree is None:
        return []
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(f"{prefix}/{_path_str(p)}", leaf) for p, leaf in flat]


def _current_rng_key(net) -> np.ndarray:
    """Live RNG continuation (same rule as `util/checkpoint.py`): the
    on-device clock once training has stepped, else the host attribute."""
    if getattr(net, "_clock", None) is not None:
        return np.asarray(net._clock[1])
    return np.asarray(net._train_rng)


# ------------------------------------------------------------------- save


def snapshot_net(net) -> Dict[str, Any]:
    """Host-side snapshot of full training state, taken on the caller's
    thread (it must be — the train step donates its buffers, so the arrays
    are gone one step later). Device->host copies are started async for
    every leaf before any is materialized. The returned dict is pure host
    data; `write_snapshot` can run it on any thread."""
    import jax

    trees = [(_PARAMS, net.params_tree), (_UPDATER, net.opt_state),
             (_STATE, net.state or None)]
    for _, tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            try:
                leaf.copy_to_host_async()
            except AttributeError:
                pass
    leaves = []
    for prefix, tree in trees:
        for key, leaf in _flat_items(tree, prefix):
            chunks = list(leaf_chunks(leaf))
            leaves.append({
                "key": key,
                "shape": tuple(np.shape(leaf)),
                "dtype": str(chunks[0][1].dtype),
                "chunks": chunks,
            })
    meta = {
        "format": FORMAT,
        "version": VERSION,
        "engine": type(net).__name__,
        "conf_json": net.conf.to_json(),
        "iteration": int(net.iteration),
        "epoch": int(net.epoch),
        "rng": np.asarray(_current_rng_key(net)).tolist(),
    }
    pol = getattr(net, "dtype_policy", None)
    if pol is not None and not pol.is_default:
        # Emitted only for non-default policies so default-policy checkpoint
        # bytes (and golden-checkpoint tests) are unchanged. The restore
        # side uses this for the policy-mismatch guard; conf_json carries
        # the same policy for `net=None` rebuilds.
        meta["dtype_policy"] = pol.to_dict()
    return {"leaves": leaves, "meta": meta}


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_snapshot(snap: Dict[str, Any], final_dir: str) -> str:
    """Write a snapshot as a committed checkpoint directory (the atomic
    protocol in the module docstring). Returns `final_dir`."""
    tmp = final_dir + ".tmp"
    if os.path.isdir(tmp):  # stale half-write from a crashed save
        shutil.rmtree(tmp)
    os.makedirs(os.path.join(tmp, CHUNK_DIR))
    files: Dict[str, int] = {}
    index = {"format": FORMAT, "version": VERSION, "leaves": {}}
    for leaf_id, leaf in enumerate(snap["leaves"]):
        index["leaves"][leaf["key"]] = write_leaf(
            tmp, leaf_id, leaf["key"], leaf["chunks"], leaf["shape"],
            leaf["dtype"], files)
    meta = dict(snap["meta"])
    meta["step"] = _step_of(final_dir)
    files[META] = _fsync_write(os.path.join(tmp, META),
                               json.dumps(meta).encode())
    files[INDEX] = _fsync_write(os.path.join(tmp, INDEX),
                                json.dumps(index).encode())
    _fsync_write(os.path.join(tmp, COMMIT), json.dumps({
        "format": FORMAT, "version": VERSION, "step": meta["step"],
        "files": files,
    }).encode())
    _fsync_dir(os.path.join(tmp, CHUNK_DIR))
    _fsync_dir(tmp)
    if os.path.isdir(final_dir):
        # Re-checkpointing the same step (failure-recovery replay): the old
        # committed dir must go before rename; the fully-committed tmp dir
        # survives a crash in between.
        shutil.rmtree(final_dir)
    os.rename(tmp, final_dir)
    _fsync_dir(os.path.dirname(final_dir) or ".")
    return final_dir


def _step_of(path: str) -> Optional[int]:
    import re

    m = re.match(r"^step_(\d+)$", os.path.basename(path))
    return int(m.group(1)) if m else None


def save_checkpoint(net, path: str) -> str:
    """Synchronous sharded save of `net` into the checkpoint directory
    `path` (committed atomically; `CheckpointManager` adds step naming,
    retention, and async writes on top of this)."""
    return write_snapshot(snapshot_net(net), path)


# ---------------------------------------------------------------- restore


def is_sharded_checkpoint(path) -> bool:
    """True if `path` is a COMMITTED sharded checkpoint directory."""
    return os.path.isdir(str(path)) and os.path.isfile(
        os.path.join(str(path), COMMIT))


def verify_checkpoint(path: str) -> dict:
    """Validate commit + file sizes (no array data is read); returns the
    COMMIT manifest. Clean `CheckpointCorruptError` for a missing COMMIT
    (half-written save) or any missing/truncated file."""
    path = str(path)
    if not os.path.isdir(path):
        raise CheckpointError(f"no checkpoint directory at {path}")
    commit_path = os.path.join(path, COMMIT)
    if not os.path.isfile(commit_path):
        raise CheckpointCorruptError(
            f"{path} has no COMMIT manifest — the save never committed "
            "(crash mid-write?); use an earlier committed step")
    try:
        with open(commit_path) as f:
            commit = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(f"unreadable COMMIT in {path}: {e}") from e
    for rel, size in commit.get("files", {}).items():
        full = os.path.join(path, rel)
        try:
            actual = os.path.getsize(full)
        except OSError:
            raise CheckpointCorruptError(f"{path}: missing file {rel}")
        if actual != size:
            raise CheckpointCorruptError(
                f"{path}: {rel} is {actual} bytes, manifest says {size} "
                "(truncated or corrupt)")
    return commit


def read_meta(path: str) -> dict:
    with open(os.path.join(str(path), META)) as f:
        return json.load(f)


def read_index(path: str) -> dict:
    with open(os.path.join(str(path), INDEX)) as f:
        return json.load(f)


def _build_net(meta: dict):
    """Fresh engine from the checkpoint's own config (mirrors
    `model_serializer.load_model`)."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf.neural_net import (
        ComputationGraphConfiguration,
        MultiLayerConfiguration,
    )

    if meta["engine"] == "ComputationGraph":
        conf = ComputationGraphConfiguration.from_json(meta["conf_json"])
        return ComputationGraph(conf).init()
    conf = MultiLayerConfiguration.from_json(meta["conf_json"])
    return MultiLayerNetwork(conf).init()


def _check_leaf_dtype(key: str, entry: dict, like) -> np.dtype:
    """Restore-time dtype contract: f32<->f64 coercion (the pre-policy
    elastic-restore behavior) stays silent; any mismatch involving a
    low-precision float (bf16/f16) or an integer (quantized) leaf raises —
    restoring a bf16-param checkpoint onto a default-policy net must be an
    explicit decision (`.dtype_policy(...)` on the target), never a silent
    upcast that doubles HBM and quietly changes serving numerics."""
    saved = str(entry["dtype"])
    tgt = getattr(like, "dtype", None)
    target = saved if tgt is None else str(tgt)
    if saved != target and not ({saved, target} <= {"float32", "float64"}):
        raise CheckpointError(
            f"leaf {key!r} dtype mismatch: checkpoint stores {saved}, "
            f"target net expects {target} — the checkpoint was saved under "
            "a different dtype policy (or post-training-quantized); build "
            "the target net with a matching .dtype_policy(...) (or restore "
            "with net=None to rebuild from the checkpoint's own config) "
            "instead of relying on a silent cast")
    return resolve_dtype(target)


def _make_leaf(base: str, entry: dict, like, sharding, key: str = "?"):
    """One restored leaf, placed in the target sharding. With a sharding,
    each device's region is read straight from the overlapping chunks;
    without one, the leaf is assembled on host and handed to the default
    device. Dtype coercion is policed by `_check_leaf_dtype`.

    Every path ends in `own_on_device`: the placement primitives may
    zero-copy the transient restore scratch arrays, and a restored param
    that still aliases freed host memory after the train step donates it
    reads back as garbage one allocation burst later (the CPU-CI
    elastic-resume corruption — see `parallel/mesh.py:own_on_device`)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.parallel.mesh import own_on_device

    shape = tuple(entry["shape"])
    if tuple(np.shape(like)) != shape:
        raise CheckpointError(
            f"leaf shape mismatch: checkpoint has {shape}, target net has "
            f"{tuple(np.shape(like))} — config/topology differs")
    dtype = _check_leaf_dtype(key, entry, like)
    if sharding is not None and shape:
        return own_on_device(jax.make_array_from_callback(
            shape, sharding,
            lambda idx: np.ascontiguousarray(
                read_region(base, entry, idx).astype(dtype))))
    arr = read_full(base, entry).astype(dtype)
    if sharding is not None:
        return own_on_device(jax.device_put(arr, sharding))
    return own_on_device(jnp.asarray(arr))


def _restore_tree(tree, prefix: str, index: dict, base: str, shardings):
    """Fill `tree`'s leaves from the index by key; `shardings` is a
    matching pytree of target shardings (or None for host assembly)."""
    import jax

    if tree is None:
        return None
    entries = index["leaves"]
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    flat_sh = (jax.tree_util.tree_leaves(shardings)
               if shardings is not None else [None] * len(flat))
    out = []
    for (path, like), sh in zip(flat, flat_sh):
        key = f"{prefix}/{_path_str(path)}"
        if key not in entries:
            raise CheckpointError(
                f"checkpoint at {base} has no leaf {key!r} — was it saved "
                "from a different model config?")
        out.append(_make_leaf(base, entries[key], like, sh, key=key))
    return jax.tree_util.tree_unflatten(treedef, out)


def _assemble_params_from_index(index: dict, base: str):
    """Params tree taken structurally from the INDEX (not the target net's
    init template): a quantized checkpoint stores int8 leaves plus
    `<name>__scale` companions the f32 template doesn't have, so the
    template-matching `_restore_tree` can't apply. Leaves keep their stored
    dtypes (int8 weights stay int8 in HBM — that IS the serving win;
    `nn/params.prep_layer_params` dequantizes at use)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.parallel.mesh import own_on_device

    params: Dict[str, Any] = {}
    for key, entry in index["leaves"].items():
        if not key.startswith(_PARAMS + "/"):
            continue
        node = params
        parts = key.split("/")[1:]
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        arr = read_full(base, entry)
        node[parts[-1]] = own_on_device(jnp.asarray(
            np.asarray(arr, dtype=resolve_dtype(str(entry["dtype"])))))
    return params


def _check_policy_match(meta: dict, net, path: str) -> None:
    """Fail fast (before any chunk I/O) when the checkpoint's saved dtype
    policy stores params in a different dtype than the explicit target
    net expects — the per-leaf `_check_leaf_dtype` would catch it anyway,
    but this names the actual mismatch: the POLICY."""
    saved = meta.get("dtype_policy")
    if saved is None:
        return
    from deeplearning4j_tpu.nn.conf.dtype_policy import DtypePolicy

    saved_pol = DtypePolicy.of(saved)
    target = getattr(net, "dtype_policy", None) or DtypePolicy()
    if saved_pol.resolved_param_dtype != target.resolved_param_dtype:
        raise CheckpointError(
            f"{path} was saved under dtype policy "
            f"{saved_pol.name!r} (params stored as "
            f"{saved_pol.resolved_param_dtype}), but the target net's "
            f"policy {target.name!r} expects "
            f"{target.resolved_param_dtype} params — refusing to silently "
            "cast. Build the target with "
            f".dtype_policy({saved_pol.name!r}) or restore with net=None "
            "to rebuild from the checkpoint's own config.")


def restore_checkpoint(path: str, net=None, mesh=None,
                       model_axis: Optional[str] = None, context=None,
                       load_updater: bool = True):
    """Restore a committed sharded checkpoint, elastically.

    `net=None` builds the engine from the checkpoint's own config. `mesh`
    (or a `ParallelContext` via `context`) names the TARGET placement —
    which may be a different shape than the mesh that saved: params/opt
    state get the same sharding rules `parallel/mesh.py` applies at train
    time (`param_shardings`; replicated unless `model_axis` splits them),
    state is replicated. With no mesh, leaves restore onto the default
    device — the single-host / CPU case.
    """
    import jax.numpy as jnp

    from deeplearning4j_tpu.parallel import mesh as mesh_mod

    path = str(path)
    verify_checkpoint(path)
    meta = read_meta(path)
    index = read_index(path)
    if context is not None:
        mesh = context.mesh
        model_axis = context.model_axis
    if net is not None:
        _check_policy_match(meta, net, path)
    if net is None:
        net = _build_net(meta)
    elif not net._initialized:
        net.init()

    if meta.get("quantization"):
        # Quantized serving checkpoint: int8 leaves + `__scale` companions
        # don't pattern-match the f32 init template, so the params tree is
        # assembled structurally from the index (dtypes preserved — the
        # int8 weights ARE the HBM savings). Updater state was dropped at
        # quantize time; BN running stats restore normally below.
        net.params_tree = _assemble_params_from_index(index, path)
        if net.state:
            net.state = _restore_tree(net.state, _STATE, index, path, None)
        net.iteration = int(meta.get("iteration", 0))
        net.epoch = int(meta.get("epoch", 0))
        return net

    p_sh = u_sh = s_sh = None
    if mesh is not None:
        p_sh = mesh_mod.param_shardings(net.params_tree, mesh, model_axis,
                                        net=net)
        if net.opt_state is not None:
            u_sh = mesh_mod.param_shardings(net.opt_state, mesh, model_axis,
                                            net=net)
        if net.state:
            import jax

            repl = mesh_mod.replicated(mesh)
            s_sh = jax.tree_util.tree_map(lambda _: repl, net.state)

    net.params_tree = _restore_tree(net.params_tree, _PARAMS, index, path,
                                    p_sh)
    has_updater = any(k.startswith(_UPDATER + "/") for k in index["leaves"])
    if load_updater and net.opt_state is not None and has_updater:
        net.opt_state = _restore_tree(net.opt_state, _UPDATER, index, path,
                                      u_sh)
    if net.state:
        net.state = _restore_tree(net.state, _STATE, index, path, s_sh)
    net.iteration = int(meta.get("iteration", 0))
    net.epoch = int(meta.get("epoch", 0))
    if meta.get("rng") is not None:
        net._train_rng = jnp.asarray(np.asarray(meta["rng"], np.uint32))
        net._clock = None
    return net
