"""Adapter checkpoints: LoRA deltas persisted as tiny sharded snapshots.

A fine-tuned tenant is not a model — it's a rank-r delta over a shared
base (`nn/lora.py`). This module persists EXACTLY that delta: the
`__lora_*` leaves, written through the same atomic commit protocol as
full checkpoints (`store.write_snapshot` — tmp dir + fsync + COMMIT +
rename), typically a few hundred KB against a multi-GB base.

Every adapter save is pinned to `base_fingerprint(net)` — a content hash
of the base (non-LoRA) param leaves. `load_adapter` refuses a mismatched
base: an adapter is only meaningful against the exact weights it was
trained over, and silently merging it onto a different base produces a
plausibly-wrong model rather than an error anywhere else.

The serving side (`serving/host.py`) loads many adapters next to ONE
resident base and merges per request via `lora.merge_adapter` — the
hundreds-of-tenants-per-base layout.
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Dict, Optional

import numpy as np

from deeplearning4j_tpu.checkpoint import store
from deeplearning4j_tpu.checkpoint.array_store import (
    CheckpointError,
    leaf_chunks,
    read_full,
)
from deeplearning4j_tpu.nn import lora as lora_mod

ADAPTER_FORMAT = "deeplearning4j_tpu/lora-adapter"
ADAPTER_VERSION = 1

_PREFIX = "adapter"


def _params_of(net_or_tree) -> Dict[str, Any]:
    tree = getattr(net_or_tree, "params_tree", net_or_tree)
    if tree is None:
        raise CheckpointError("net is not initialized (params_tree is None)")
    return tree


def base_fingerprint(net_or_tree) -> str:
    """Content hash of the BASE param leaves (LoRA leaves excluded, so a
    net with resident adapters fingerprints identically to its bare
    base). Covers key paths, shapes, dtypes and raw bytes — any retrain,
    quantization or surgery of the base changes it."""
    base = lora_mod.strip_adapter(_params_of(net_or_tree))
    h = hashlib.sha256()
    for key, leaf in sorted(store._flat_items(base, store._PARAMS)):
        a = np.asarray(leaf)
        h.update(key.encode())
        h.update(str(a.shape).encode())
        h.update(str(a.dtype).encode())
        h.update(np.ascontiguousarray(a).tobytes())
    return h.hexdigest()[:32]


def save_adapter(net, path: str, *, name: Optional[str] = None) -> str:
    """Write `net`'s LoRA leaves as a committed adapter checkpoint at
    `path`. The meta records the adapter's name/rank/alpha knobs plus the
    base fingerprint the delta was trained against."""
    adapter = lora_mod.extract_adapter(_params_of(net))
    if not adapter:
        raise CheckpointError(
            "net has no LoRA adapter leaves to save (use "
            "TransferLearning(...).add_lora(...) first)")
    leaves = []
    for key, leaf in store._flat_items(adapter, _PREFIX):
        chunks = list(leaf_chunks(leaf))
        leaves.append({
            "key": key,
            "shape": tuple(np.shape(leaf)),
            "dtype": str(chunks[0][1].dtype),
            "chunks": chunks,
        })
    alphas = {
        float(getattr(l, "lora_alpha", None) or 0.0)
        for l in _conf_layers(net) if getattr(l, "lora_rank", None)
    } - {0.0}
    meta = {
        "format": ADAPTER_FORMAT,
        "version": ADAPTER_VERSION,
        "name": name or os.path.basename(os.path.normpath(path)),
        "rank": lora_mod.adapter_rank(adapter),
        "alpha": max(alphas) if alphas else None,
        "base_fingerprint": base_fingerprint(net),
        "engine": type(net).__name__,
    }
    return store.write_snapshot({"leaves": leaves, "meta": meta}, str(path))


def _conf_layers(net):
    conf = getattr(net, "conf", None)
    if conf is None:
        return []
    if hasattr(conf, "vertices"):
        return [v.layer for v in conf.vertices.values()
                if getattr(v, "layer", None) is not None]
    return list(getattr(conf, "layers", []) or [])


def is_adapter_checkpoint(path) -> bool:
    """True for a COMMITTED adapter checkpoint directory (cheap: reads
    meta only after the COMMIT marker exists)."""
    if not store.is_sharded_checkpoint(path):
        return False
    try:
        return store.read_meta(str(path)).get("format") == ADAPTER_FORMAT
    except (OSError, ValueError):
        return False


def adapter_meta(path: str) -> dict:
    """Validated meta of an adapter checkpoint (verifies the commit
    manifest and the format tag; no array data read)."""
    path = str(path)
    store.verify_checkpoint(path)
    meta = store.read_meta(path)
    if meta.get("format") != ADAPTER_FORMAT:
        raise CheckpointError(
            f"{path} is a {meta.get('format')!r} checkpoint, not a LoRA "
            f"adapter ({ADAPTER_FORMAT!r})")
    return meta


def load_adapter(path: str, base_net=None) -> Dict[str, Dict[str, Any]]:
    """Read an adapter checkpoint back into a delta-only tree
    (`{layer: {W__lora_*: array}}`, ready for `lora.merge_adapter`).

    When `base_net` is given, the stored base fingerprint is checked
    against it and a mismatch REFUSES to load — the delta was trained
    against different base weights and merging it would silently corrupt
    outputs."""
    import jax.numpy as jnp

    path = str(path)
    meta = adapter_meta(path)
    if base_net is not None:
        fp = base_fingerprint(base_net)
        want = meta.get("base_fingerprint")
        if fp != want:
            raise CheckpointError(
                f"adapter {meta.get('name')!r} at {path} was trained "
                f"against base {want}, but the resident base fingerprints "
                f"as {fp} — refusing to merge a delta onto different "
                "weights")
    index = store.read_index(path)
    out: Dict[str, Dict[str, Any]] = {}
    for key, entry in index["leaves"].items():
        parts = key.split("/")
        if parts[0] != _PREFIX or len(parts) != 3:
            raise CheckpointError(f"{path}: unexpected adapter leaf {key!r}")
        _, lk, leaf_name = parts
        out.setdefault(lk, {})[leaf_name] = jnp.asarray(
            read_full(path, entry))
    return out
