"""Chunked on-disk array store: the leaf-level layer of the sharded
checkpoint format (orbax-style; SURVEY §5 "sharded checkpoint of a params
pytree + opt state").

Every leaf of a checkpointed pytree is stored as one or more raw
little-endian binary **chunk files**, one per distinct device shard of the
(possibly sharded) global array, plus an entry in `index.json` recording the
global shape, dtype, and each chunk's `[start, stop)` interval per dimension.
Because each shard writes its own file, save I/O parallelizes per shard and
the full array is never materialized on one host; because the index maps
chunks to global coordinates, a reader can assemble ANY region — which is
what makes restore elastic: `jax.make_array_from_callback` asks for exactly
the region each target device owns, regardless of the mesh shape that wrote
the checkpoint.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Tuple

import numpy as np

CHUNK_DIR = "chunks"


class CheckpointError(RuntimeError):
    """Base error for the sharded checkpoint store."""


class CheckpointCorruptError(CheckpointError):
    """A checkpoint that looked present failed validation (truncated chunk,
    missing file, uncovered region, no COMMIT manifest)."""


def resolve_dtype(s: str) -> np.dtype:
    """np.dtype from a saved dtype string, including the ml_dtypes names
    (bfloat16 etc.) a plain `np.dtype(str)` can't parse — bf16-param
    checkpoints (DtypePolicy `bfloat16`/`float16` presets) store those."""
    try:
        return np.dtype(s)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, s))


def leaf_chunks(arr) -> Iterator[Tuple[Tuple[Tuple[int, int], ...], np.ndarray]]:
    """Yield `(index, data)` for each DISTINCT shard region of `arr`:
    `index` is a `((start, stop), ...)` interval per dimension into the
    global array, `data` the host copy of that region. Replicated regions
    (every data-parallel replica holds the same slice) appear exactly once;
    a plain host array yields one chunk covering the whole array."""
    shards = getattr(arr, "addressable_shards", None)
    if not shards:
        yield tuple((0, s) for s in np.shape(arr)), _host_copy(arr)
        return
    seen = set()
    for sh in shards:
        idx = tuple(
            (0 if sl.start is None else int(sl.start),
             dim if sl.stop is None else int(sl.stop))
            for sl, dim in zip(sh.index, arr.shape))
        if idx in seen:
            continue
        seen.add(idx)
        yield idx, _host_copy(sh.data)


def _host_copy(arr) -> np.ndarray:
    """An OWNED host copy of `arr`. On the CPU backend `np.asarray` of a
    jax array is a zero-copy view of the XLA buffer — and the training
    step donates its input buffers, so by the time the async checkpoint
    writer reads the view, the memory may hold a LATER step's values.
    Forcing the copy on the snapshot thread is what makes the snapshot
    actually immutable."""
    a = np.asarray(arr)
    return a.copy() if a.base is not None else a


def _fsync_write(path: str, data: bytes) -> int:
    """Durable file write: the atomic-commit protocol needs every chunk on
    disk BEFORE the COMMIT manifest is, else a crash could commit a
    checkpoint whose chunks are still in the page cache."""
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    return len(data)


def write_leaf(dirpath: str, leaf_id: int, key: str,
               chunks: List[Tuple[Tuple[Tuple[int, int], ...], np.ndarray]],
               shape: Tuple[int, ...], dtype: str,
               files: Dict[str, int]) -> dict:
    """Write one leaf's chunk files under `dirpath/chunks/`; returns its
    index entry and records written file sizes into `files` (the COMMIT
    manifest's validation data)."""
    entry = {"shape": [int(s) for s in shape], "dtype": str(dtype),
             "chunks": []}
    for i, (idx, data) in enumerate(chunks):
        rel = f"{CHUNK_DIR}/l{leaf_id:05d}.c{i:03d}.bin"
        files[rel] = _fsync_write(os.path.join(dirpath, rel),
                                  np.ascontiguousarray(data).tobytes())
        entry["chunks"].append({"file": rel,
                                "index": [[int(a), int(b)] for a, b in idx]})
    return entry


def _open_chunk(dirpath: str, chunk: dict, dtype: np.dtype) -> np.ndarray:
    """Memory-map one chunk (reads page lazily — an elastic restore slices
    only the region the target device owns)."""
    shape = tuple(b - a for a, b in chunk["index"])
    path = os.path.join(dirpath, chunk["file"])
    try:
        if not shape:  # 0-d leaf: memmap requires shape=(1,)
            return np.fromfile(path, dtype=dtype, count=1).reshape(())
        return np.memmap(path, dtype=dtype, mode="r", shape=shape)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"chunk {chunk['file']} unreadable or truncated "
            f"(expected shape {shape}, dtype {dtype}): {e}") from e


def read_region(dirpath: str, entry: dict, region) -> np.ndarray:
    """Assemble the sub-array `entry[region]` from whatever chunks overlap
    it. `region` is a tuple of slices in GLOBAL coordinates (what
    `jax.make_array_from_callback` hands the per-device callback). Raises
    `CheckpointCorruptError` if the chunks don't fully cover the region."""
    shape = tuple(entry["shape"])
    dtype = resolve_dtype(entry["dtype"])
    if not shape:
        return _open_chunk(dirpath, entry["chunks"][0], dtype).copy()
    region = tuple(sl.indices(dim) for sl, dim in zip(region, shape))
    region = tuple(slice(a, b) for a, b, _ in region)
    out_shape = tuple(sl.stop - sl.start for sl in region)
    out = np.empty(out_shape, dtype)
    covered = np.zeros(out_shape, bool)
    for chunk in entry["chunks"]:
        cidx = [(int(a), int(b)) for a, b in chunk["index"]]
        inter = []
        for (a, b), sl in zip(cidx, region):
            lo, hi = max(a, sl.start), min(b, sl.stop)
            if lo >= hi:
                inter = None
                break
            inter.append((lo, hi))
        if inter is None:
            continue
        mm = _open_chunk(dirpath, chunk, dtype)
        src = tuple(slice(lo - a, hi - a)
                    for (a, _), (lo, hi) in zip(cidx, inter))
        dst = tuple(slice(lo - sl.start, hi - sl.start)
                    for sl, (lo, hi) in zip(region, inter))
        out[dst] = mm[src]
        covered[dst] = True
    if not covered.all():
        raise CheckpointCorruptError(
            f"chunks cover only {int(covered.sum())}/{covered.size} elements "
            f"of requested region {region} (global shape {shape})")
    return out


def read_full(dirpath: str, entry: dict) -> np.ndarray:
    """The whole global array (single-host restore path)."""
    shape = tuple(entry["shape"])
    return read_region(dirpath, entry, tuple(slice(0, s) for s in shape))
