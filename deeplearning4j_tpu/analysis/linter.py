"""tpulint driver: walk modules, run rules, apply suppressions + baseline.

The baseline file (`tpulint_baseline.json`, checked in next to this
module) grandfathers pre-existing findings so the tier-1 gate only fails
on *new* violations. Every baseline entry must carry a human-written
``reason``; entries fingerprint on (rule, path, context, message) — not
the line number — so unrelated edits don't churn the file. Stale entries
(baselined findings that no longer fire) are reported so the file shrinks
as debt is paid down.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.analysis.context import ModuleContext
from deeplearning4j_tpu.analysis.findings import Finding
from deeplearning4j_tpu.analysis.rules import get_rules

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_DIR)
DEFAULT_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "tpulint_baseline.json")


def _relpath(path: str) -> str:
    path = os.path.abspath(path)
    try:
        rel = os.path.relpath(path, _REPO_ROOT)
    except ValueError:
        rel = path
    return rel.replace(os.sep, "/")


def lint_source(source: str, path: str = "<snippet>",
                rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint a source string (unit tests use this for good/bad snippets)."""
    rel = path if path.startswith("<") else _relpath(path)
    try:
        ctx = ModuleContext(source, path, rel)
    except SyntaxError as e:
        return [Finding(rule="PARSE", path=rel, line=e.lineno or 0,
                        message=f"syntax error: {e.msg}")]
    out: List[Finding] = []
    for rule in get_rules(rules):
        for f in rule.check(ctx):
            if not ctx.is_suppressed(f.line, f.rule):
                out.append(f)
    return sorted(out, key=Finding.sort_key)


def lint_file(path: str,
              rules: Optional[Sequence[str]] = None) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path, rules)


def iter_py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if not d.startswith((".", "__pycache__")))
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def lint_paths(paths: Sequence[str],
               rules: Optional[Sequence[str]] = None) -> List[Finding]:
    out: List[Finding] = []
    for p in paths:
        if os.path.isdir(p):
            for f in iter_py_files(p):
                out.extend(lint_file(f, rules))
        else:
            out.extend(lint_file(p, rules))
    return sorted(out, key=Finding.sort_key)


def lint_package(rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint every module of deeplearning4j_tpu (what tier-1 enforces)."""
    return lint_paths([_PKG_DIR], rules)


# ----------------------------------------------------------------- baseline

def fingerprint(f: Finding) -> Tuple[str, str, str, str]:
    return (f.rule, f.path, f.context, f.message)


class Baseline:
    """Grandfathered findings; every entry must carry a non-empty reason."""

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries = entries or []
        self._index: Dict[Tuple[str, str, str, str], dict] = {
            (e["rule"], e["path"], e.get("context", "<module>"),
             e["message"]): e
            for e in self.entries
        }

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls([])
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        return cls(data.get("findings", []))

    def save(self, path: str) -> None:
        data = {"version": 1,
                "comment": ("tpulint grandfathered findings; every entry "
                            "needs a `reason`. Regenerate with "
                            "`python -m deeplearning4j_tpu.analysis "
                            "--write-baseline` (reasons are preserved by "
                            "fingerprint)."),
                "findings": self.entries}
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2, sort_keys=False)
            fh.write("\n")
        os.replace(tmp, path)

    def missing_reasons(self) -> List[dict]:
        return [e for e in self.entries
                if not str(e.get("reason", "")).strip()
                or str(e.get("reason", "")).strip().upper().startswith("TODO")]

    def split(self, findings: Sequence[Finding]):
        """Partition into (new, grandfathered) and compute stale entries."""
        new: List[Finding] = []
        matched_keys = set()
        grandfathered: List[Finding] = []
        for f in findings:
            key = fingerprint(f)
            if key in self._index:
                matched_keys.add(key)
                grandfathered.append(f)
            else:
                new.append(f)
        stale = [e for k, e in self._index.items() if k not in matched_keys]
        return new, grandfathered, stale

    @classmethod
    def from_findings(cls, findings: Sequence[Finding],
                      previous: Optional["Baseline"] = None) -> "Baseline":
        entries = []
        for f in sorted(findings, key=Finding.sort_key):
            key = fingerprint(f)
            prev = previous._index.get(key) if previous else None
            entries.append({
                "rule": f.rule, "path": f.path, "context": f.context,
                "message": f.message, "line": f.line,
                "reason": (prev or {}).get(
                    "reason", "TODO: justify or fix this finding"),
            })
        return cls(entries)
