"""Runtime strict-mode guards — the dynamic half of tpulint.

Static rules catch what is visible in the source; these guards catch the
same failure classes at run time, cheaply enough to leave on in CI:

- :func:`strict_mode` — context manager that wraps the step body in
  ``jax.transfer_guard("disallow")`` so any *implicit* host<->device
  transfer (a stray numpy array flowing into a jitted step, a device
  value silently fetched for a Python branch) raises instead of eating
  milliseconds per step. Off by default; ``DL4J_TPU_STRICT=1`` (or
  ``enabled=True``) turns it on, and when given an engine it also
  installs the retrace watch and NaN guard below.

- :class:`RetraceGuard` — fires when one function compiles more than N
  times (``DL4J_TPU_RETRACE_LIMIT``, default 10). ``wrap()`` counts
  traces of a to-be-jitted callable directly; ``watch(net)`` hooks the
  engine's ``_fit_dispatch`` and reads the PR-2 observability counters
  (``dl4j_xla_compiles_total`` via the jax.monitoring hook, plus the
  engine's own jit-program cache) to spot retrace storms in training.

- :func:`install_nan_guard` — patches ``_fit_dispatch`` to settle the
  loss scalar after each staged batch and raise ``FloatingPointError``
  on NaN/inf, so a diverging run dies at the first bad step instead of
  after the TPU hour.
"""

from __future__ import annotations

import contextlib
import functools
import math
import os
import threading
import warnings
from typing import Callable, Dict, Optional


def strict_enabled(default: bool = False) -> bool:
    """Is strict mode requested via the environment (`DL4J_TPU_STRICT`)?"""
    v = os.environ.get("DL4J_TPU_STRICT")
    if v is None:
        return default
    return v.strip().lower() not in ("0", "false", "off", "")


def _default_retrace_limit() -> int:
    try:
        return max(1, int(os.environ.get("DL4J_TPU_RETRACE_LIMIT", "10")))
    except ValueError:
        return 10


class RetraceError(RuntimeError):
    """A function recompiled more often than the strict-mode limit."""


class RetraceGuard:
    """Warn or raise when one function compiles more than `limit` times.

    ``wrap(fn)`` returns a counting proxy to put *inside* ``jax.jit`` —
    each retrace re-executes the Python body, so the count is exact::

        guard = RetraceGuard(limit=3)
        step = jax.jit(guard.wrap(step_fn))

    ``watch(net)`` instruments a live engine instead: after every staged
    batch it compares the growth of the engine's jit-program cache and
    the observability compile counter against the limit.
    """

    def __init__(self, limit: Optional[int] = None,
                 on_violation: Optional[str] = None):
        self.limit = _default_retrace_limit() if limit is None else int(limit)
        if on_violation is None:
            on_violation = "raise" if strict_enabled() else "warn"
        if on_violation not in ("warn", "raise"):
            raise ValueError("on_violation must be 'warn' or 'raise'")
        self.on_violation = on_violation
        self.counts: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._watched = []
        self._warned = set()

    # ------------------------------------------------------------- wrap
    def wrap(self, fn: Callable, name: Optional[str] = None) -> Callable:
        name = name or getattr(fn, "__name__", "<fn>")

        @functools.wraps(fn)
        def traced(*args, **kwargs):
            with self._lock:
                n = self.counts[name] = self.counts.get(name, 0) + 1
            if n > self.limit:
                self._violate(name, n)
            return fn(*args, **kwargs)

        return traced

    # ------------------------------------------------------------ watch
    def _compiles_total(self) -> float:
        try:
            from deeplearning4j_tpu import observability as obs
            fam = obs.metrics.get_family("dl4j_xla_compiles_total")
            if fam is None:
                return 0.0
            return sum(c.get() for c in fam.children())
        except Exception:
            return 0.0

    def watch(self, net, name: Optional[str] = None) -> "RetraceGuard":
        """Instrument a live engine's `_fit_dispatch`; undo with `unwatch()`."""
        try:
            from deeplearning4j_tpu import observability as obs
            obs.install_jax_compile_hook()
        except Exception:
            pass
        name = name or type(net).__name__
        base_programs = len(net._jit_cache)
        base_compiles = self._compiles_total()
        orig = net._fit_dispatch

        def dispatch(batch, *a, **kw):
            out = orig(batch, *a, **kw)
            programs = len(net._jit_cache) - base_programs
            compiles = self._compiles_total() - base_compiles
            n = int(max(programs, compiles))
            with self._lock:
                self.counts[name] = n
            if n > self.limit:
                self._violate(name, n)
            return out

        net._fit_dispatch = dispatch
        self._watched.append((net, orig))
        return self

    def unwatch(self) -> None:
        while self._watched:
            net, orig = self._watched.pop()
            net._fit_dispatch = orig

    def __enter__(self) -> "RetraceGuard":
        return self

    def __exit__(self, *exc) -> bool:
        self.unwatch()
        return False

    # -------------------------------------------------------- violation
    def _violate(self, name: str, n: int) -> None:
        msg = (f"tpulint strict mode: `{name}` has compiled {n} times "
               f"(limit {self.limit}) — likely a retrace storm from "
               "per-step Python scalars/shapes; pad shapes or mark true "
               "statics with static_argnums (see PERF.md §12)")
        if self.on_violation == "raise":
            raise RetraceError(msg)
        if name not in self._warned:  # one warning per function, not per step
            self._warned.add(name)
            warnings.warn(msg, RuntimeWarning, stacklevel=3)


def install_nan_guard(net, check_every: int = 1) -> Callable[[], None]:
    """Patch `net._fit_dispatch` to raise FloatingPointError on a NaN/inf
    loss. Settling the loss scalar syncs the step, so `check_every=k`
    amortizes the sync over k batches. Returns an uninstall callable."""
    orig = net._fit_dispatch
    state = {"n": 0}

    def dispatch(batch, *a, **kw):
        out = orig(batch, *a, **kw)
        state["n"] += 1
        if state["n"] % check_every == 0:
            v = net.score_value
            if v is not None and (math.isnan(v) or math.isinf(v)):
                it = getattr(net, "iteration", "?")
                try:
                    # Forensics before the raise: the bundle holds the ring
                    # of step records leading up to the divergence.
                    from deeplearning4j_tpu import observability as obs

                    obs.flight.record_event(
                        "nan_loss", engine=type(net).__name__,
                        iteration=it, loss=repr(v))
                    obs.flight.dump(reason="nan-loss", force=False)
                except Exception:
                    pass
                raise FloatingPointError(
                    f"tpulint strict mode: non-finite loss ({v}) at "
                    f"iteration {it}")
        return out

    net._fit_dispatch = dispatch

    def uninstall():
        net._fit_dispatch = orig

    return uninstall


@contextlib.contextmanager
def strict_mode(net=None, *, enabled: Optional[bool] = None,
                transfer: str = "disallow",
                retrace_limit: Optional[int] = None,
                nan_guard: bool = True,
                on_violation: str = "raise"):
    """Strict-mode window for a step body (or a whole fit).

    When off (the default unless `DL4J_TPU_STRICT` is set or
    `enabled=True`), this is a no-op that yields None — zero overhead,
    safe to leave in production code paths. When on:

    - implicit host<->device transfers raise (``jax.transfer_guard``),
      so inputs must be staged with an explicit ``jax.device_put``;
    - with an engine passed, a :class:`RetraceGuard` watches its
      dispatches and a NaN guard settles each step's loss.
    """
    on = strict_enabled() if enabled is None else bool(enabled)
    if not on:
        yield None
        return
    import jax

    guard = RetraceGuard(limit=retrace_limit, on_violation=on_violation)
    uninstall = None
    if net is not None:
        guard.watch(net)
        if nan_guard:
            uninstall = install_nan_guard(net)
    try:
        with jax.transfer_guard(transfer):
            yield guard
    finally:
        if uninstall is not None:
            uninstall()
        guard.unwatch()
