"""Per-module AST analysis context shared by all tpulint rules.

One parse + one indexing pass per module; every rule then works off the
same precomputed facts:

- import aliases (which local names mean numpy / jax.numpy / jax / time /
  stdlib random),
- every function/lambda with a dotted qualname, its params, decorators
  and enclosing class,
- a conservative intra-module call graph (plain-name calls and
  ``self.method()`` calls),
- the set of **trace roots** (functions decorated with or passed to
  ``jax.jit`` / ``pmap`` / ``shard_map`` / ``grad`` / ``vmap`` /
  ``lax.scan``-family wrappers) and its transitive closure
  ``jit_reachable`` — the "code that runs under trace" region most rules
  scope themselves to,
- inline suppression comments (``# tpulint: disable=JX001[,JX002|all]``
  on the offending line, or ``# tpulint: disable-file=...`` in the first
  ten lines of the module).

The call graph is intentionally intra-module and name-based: cross-module
dispatch (e.g. the layer-impl registry) is invisible to it. A function
that is traced but not discoverable can be annotated with a
``# tpulint: traced`` comment on its ``def`` line.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

# Wrappers whose function argument executes under trace. `scan`-family
# names are only honored when rooted in a jax-ish alias (see _is_tracer_fn)
# so arbitrary `.cond()` methods on project objects don't count.
TRACE_WRAPPERS = {
    "jit", "pjit", "pmap", "shard_map", "grad", "value_and_grad", "vmap",
    "remat", "checkpoint", "custom_vjp", "custom_jvp",
}
TRACE_WRAPPERS_JAX_ONLY = {
    "scan", "while_loop", "fori_loop", "cond", "switch", "associated_scan",
}

_SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\s]+|all)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*tpulint:\s*disable-file=([A-Za-z0-9_,\s]+|all)")
_TRACED_RE = re.compile(r"#\s*tpulint:\s*traced\b")


class FunctionInfo:
    __slots__ = ("node", "qualname", "name", "params", "class_name",
                 "parent", "decorators", "lineno", "children")

    def __init__(self, node, qualname: str, name: str, params: List[str],
                 class_name: Optional[str], parent: Optional[str],
                 decorators, lineno: int):
        self.node = node
        self.qualname = qualname
        self.name = name
        self.params = params
        self.class_name = class_name
        self.parent = parent          # qualname of enclosing function, if any
        self.decorators = decorators
        self.lineno = lineno
        self.children: List[str] = []  # nested function qualnames


def _dotted(node) -> Optional[str]:
    """'jax.numpy.float64' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def attr_base(node) -> Optional[str]:
    """Root Name of an Attribute chain ('np' for np.random.seed)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def terminal_attr(node) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def walk_body(fn_node) -> Iterator[ast.AST]:
    """Walk a function body without descending into nested defs/lambdas
    (those are separate FunctionInfo entries with their own reachability)."""
    stack = list(ast.iter_child_nodes(fn_node))
    # skip the arguments node of the function itself, keep defaults
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class ModuleContext:
    def __init__(self, source: str, path: str, rel: str):
        self.source = source
        self.path = path
        self.rel = rel
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)

        self.numpy_aliases: Set[str] = set()
        self.jnp_aliases: Set[str] = set()
        self.jax_aliases: Set[str] = set()
        self.lax_aliases: Set[str] = set()
        self.time_aliases: Set[str] = set()
        self.random_aliases: Set[str] = set()
        self.from_jax_names: Set[str] = set()   # `from jax import jit` etc.

        self.functions: Dict[str, FunctionInfo] = {}
        self._by_name: Dict[str, List[str]] = {}
        self.calls: Dict[str, Set[Tuple[str, str]]] = {}
        self.jit_roots: Set[str] = set()
        self.jit_reachable: Set[str] = set()
        self._parents: Dict[int, ast.AST] = {}

        self._file_suppressed: Set[str] = set()
        self._scan_imports()
        self._index_functions()
        self._index_calls_and_roots()
        self._compute_reachability()
        self._scan_file_suppressions()

    # ------------------------------------------------------------ imports
    def _scan_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name, asname = a.name, a.asname or a.name.split(".")[0]
                    if name == "numpy":
                        self.numpy_aliases.add(asname)
                    elif name == "jax.numpy":
                        self.jnp_aliases.add(a.asname or "jnp")
                    elif name == "jax":
                        self.jax_aliases.add(asname)
                    elif name == "jax.lax":
                        self.lax_aliases.add(a.asname or "lax")
                    elif name == "time":
                        self.time_aliases.add(asname)
                    elif name == "random":
                        self.random_aliases.add(asname)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    asname = a.asname or a.name
                    if mod == "jax" and a.name == "numpy":
                        self.jnp_aliases.add(asname)
                    elif mod == "jax" and a.name == "lax":
                        self.lax_aliases.add(asname)
                    elif mod.startswith("jax"):
                        self.from_jax_names.add(asname)

    # ---------------------------------------------------------- functions
    def _index_functions(self):
        ctx = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.stack: List[str] = []       # qualname parts
                self.fn_stack: List[str] = []    # enclosing fn qualnames
                self.class_stack: List[str] = []

            def _add(self, node, name, params):
                qual = ".".join(self.stack + [name]) if self.stack else name
                info = FunctionInfo(
                    node, qual, name, params,
                    self.class_stack[-1] if self.class_stack else None,
                    self.fn_stack[-1] if self.fn_stack else None,
                    getattr(node, "decorator_list", []), node.lineno)
                ctx.functions[qual] = info
                ctx._by_name.setdefault(name, []).append(qual)
                if info.parent:
                    ctx.functions[info.parent].children.append(qual)
                return qual

            def visit_ClassDef(self, node):
                self.stack.append(node.name)
                self.class_stack.append(node.name)
                self.generic_visit(node)
                self.class_stack.pop()
                self.stack.pop()

            def _visit_fn(self, node):
                args = node.args
                params = ([a.arg for a in getattr(args, "posonlyargs", [])]
                          + [a.arg for a in args.args]
                          + [a.arg for a in args.kwonlyargs])
                qual = self._add(node, node.name, params)
                self.stack.extend([node.name, "<locals>"])
                self.fn_stack.append(qual)
                self.generic_visit(node)
                self.fn_stack.pop()
                self.stack = self.stack[:-2]

            visit_FunctionDef = _visit_fn
            visit_AsyncFunctionDef = _visit_fn

            def visit_Lambda(self, node):
                args = node.args
                params = [a.arg for a in args.args]
                name = f"<lambda:{node.lineno}>"
                qual = self._add(node, name, params)
                self.stack.extend([name, "<locals>"])
                self.fn_stack.append(qual)
                self.generic_visit(node)
                self.fn_stack.pop()
                self.stack = self.stack[:-2]

        V().visit(self.tree)

    # -------------------------------------------------------------- calls
    def _is_tracer_fn(self, func) -> bool:
        """Is `func` (the .func of a Call) a trace-introducing wrapper?"""
        term = terminal_attr(func)
        if term is None:
            return False
        base = attr_base(func)
        if term in TRACE_WRAPPERS:
            if isinstance(func, ast.Name):
                # bare `jit` only counts if imported from jax
                return term in self.from_jax_names or term in ("jit", "pjit",
                                                               "pmap")
            return base in self.jax_aliases | self.lax_aliases | {"jax"}
        if term in TRACE_WRAPPERS_JAX_ONLY:
            return base in self.jax_aliases | self.lax_aliases
        return False

    def _decorated_traced(self, info: FunctionInfo) -> bool:
        for dec in info.decorators:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if self._is_tracer_fn(target):
                return True
            # @partial(jax.jit, ...) / @functools.partial(jit, ...)
            if (isinstance(dec, ast.Call)
                    and terminal_attr(dec.func) == "partial" and dec.args
                    and self._is_tracer_fn(dec.args[0])):
                return True
        # explicit annotation for functions traced via dynamic dispatch
        line = self.lines[info.lineno - 1] if info.lineno <= len(
            self.lines) else ""
        return bool(_TRACED_RE.search(line))

    def _owner_of(self, node) -> str:
        """Qualname of the function whose *body* contains `node`."""
        best, best_span = "<module>", None
        for qual, info in self.functions.items():
            n = info.node
            end = getattr(n, "end_lineno", n.lineno)
            if n.lineno <= node.lineno <= end:
                span = end - n.lineno
                if best_span is None or span < best_span:
                    best, best_span = qual, span
        return best

    def _index_calls_and_roots(self):
        # per-function outgoing edges
        for qual, info in self.functions.items():
            edges: Set[Tuple[str, str]] = set()
            for node in walk_body(info.node):
                if isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Name):
                        edges.add(("name", f.id))
                    elif (isinstance(f, ast.Attribute)
                          and isinstance(f.value, ast.Name)
                          and f.value.id == "self"):
                        edges.add(("self", f.attr))
            self.calls[qual] = edges

        # decorated roots + pragma roots
        for qual, info in self.functions.items():
            if self._decorated_traced(info):
                self.jit_roots.add(qual)

        # call-site roots: jax.jit(f), lax.scan(f, ...), executor-free
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and self._is_tracer_fn(node.func)):
                continue
            owner = self._owner_of(node)
            for arg in node.args:
                self._mark_root_expr(arg, owner)
            for kw in node.keywords:
                if kw.arg in ("fun", "f", "body_fun", "cond_fun"):
                    self._mark_root_expr(kw.value, owner)

    def _resolve(self, owner: str, kind: str, name: str) -> Optional[str]:
        """Resolve a called name from `owner`'s scope to a qualname."""
        cands = self._by_name.get(name)
        if not cands:
            return None
        if kind == "self":
            cls = (self.functions[owner].class_name
                   if owner in self.functions else None)
            for c in cands:
                if self.functions[c].class_name and (
                        cls is None
                        or self.functions[c].class_name == cls):
                    return c
            return None
        # nearest lexical scope: prefer a candidate nested in owner, then
        # siblings/ancestors, then module level; fall back to first.
        if owner in self.functions:
            prefix = owner + ".<locals>."
            for c in cands:
                if c.startswith(prefix):
                    return c
        for c in cands:
            if "<locals>" not in c or owner.startswith(
                    c.rsplit(".<locals>.", 1)[0]):
                return c
        return cands[0]

    def _mark_root_expr(self, expr, owner: str):
        qual = None
        if isinstance(expr, ast.Name):
            qual = self._resolve(owner, "name", expr.id)
        elif (isinstance(expr, ast.Attribute)
              and isinstance(expr.value, ast.Name)
              and expr.value.id == "self"):
            qual = self._resolve(owner, "self", expr.attr)
        elif isinstance(expr, ast.Lambda):
            qual = ".".join(filter(None, [
                owner if owner != "<module>" else "",
                "<locals>" if owner != "<module>" else "",
                f"<lambda:{expr.lineno}>"]))
            if qual not in self.functions:
                for q, i in self.functions.items():
                    if i.node is expr:
                        qual = q
                        break
        if qual in self.functions:
            self.jit_roots.add(qual)

    def _host_static(self, qual: str) -> bool:
        """lru_cache/cache-decorated functions take hashable (static) args
        and run once per distinct key — host-side by construction, so trace
        reachability must not propagate into them."""
        info = self.functions.get(qual)
        if info is None:
            return False
        for dec in info.decorators:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if terminal_attr(target) in ("lru_cache", "cache"):
                return True
        return False

    def _compute_reachability(self):
        seen = set(self.jit_roots)
        frontier = list(seen)
        while frontier:
            qual = frontier.pop()
            for kind, name in self.calls.get(qual, ()):
                target = self._resolve(qual, kind, name)
                if (target and target not in seen
                        and not self._host_static(target)):
                    seen.add(target)
                    frontier.append(target)
        self.jit_reachable = seen

    # ------------------------------------------------------- suppressions
    def _scan_file_suppressions(self):
        for line in self.lines[:10]:
            m = _SUPPRESS_FILE_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                self._file_suppressed |= rules

    def is_suppressed(self, line: int, rule: str) -> bool:
        if ("all" in self._file_suppressed
                or rule in self._file_suppressed):
            return True
        if 1 <= line <= len(self.lines):
            m = _SUPPRESS_RE.search(self.lines[line - 1])
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                return "all" in rules or rule in rules
        return False

    # ---------------------------------------------------------- utilities
    def reachable_functions(self) -> Iterator[FunctionInfo]:
        for qual in sorted(self.jit_reachable):
            yield self.functions[qual]

    def ancestors(self, node) -> Iterator[ast.AST]:
        """Lazily build a child->parent map and walk up from `node`."""
        if not self._parents:
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[id(child)] = parent
        cur = node
        while id(cur) in self._parents:
            cur = self._parents[id(cur)]
            yield cur

    def context_of(self, node) -> str:
        return self._owner_of(node)
