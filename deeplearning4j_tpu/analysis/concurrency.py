"""Whole-program concurrency analysis: the static lock model (JX017/JX018).

Built on the same per-module :class:`~deeplearning4j_tpu.analysis.context.
ModuleContext` the other tpulint rules use. One :class:`LockModel` per
module:

- **lock discovery** — ``self._x = threading.Lock()/RLock()/Condition()``
  attributes and module-level ``_X = threading.Lock()`` globals, plus the
  instrumented factory spellings (``locktrace.named_lock/named_rlock/
  named_condition``) so adopting the runtime tracer does not blind the
  static tier. ``threading.Condition(self._lock)`` aliases the wrapped
  lock: acquiring either IS the same mutex (`datasets/staging.py` idiom).
- **acquisition tracking** — ``with self._lock:`` regions (including
  multi-item ``with a, b:``) and explicit ``.acquire()`` calls, carried
  through the intra-module call graph with the same closure style as
  jit-reachability: a function's *acquire summary* is everything it may
  lock transitively, with one witness chain per lock retained for the
  report.
- **JX017** — a cycle in the may-hold→then-acquire graph: two code paths
  that take the same locks in opposite orders deadlock the first time
  the schedules interleave. Reported once per cycle with BOTH witness
  paths (qualnames, not line numbers, so baselines don't churn on edits).
- **JX018** — blocking work inside a held-lock region: device dispatch
  (calls to locally-jitted functions, ``block_until_ready``,
  ``device_put``), outbound HTTP/socket I/O (``urlopen`` and the
  project's ``post_json``/``get_text`` helpers), coordinator/client
  RPCs, ``queue.get``, thread ``join``/runtime ``stop``, ``sleep``, and
  unbounded ``wait`` on foreign events. This is the exact shape of the
  `_reload` stuck-`loading` and rolling-update bugs: one slow call under
  the host lock turns into a fleet-wide stall. Waiting on the held
  lock's own condition (``with self._cond: self._cond.wait()``) is the
  one legal blocking-under-lock and is exempt.

The analysis is deliberately intra-module (same contract as the call
graph it rides on): cross-module lock ordering is the runtime tier's job
(`analysis/locktrace.py`). The CLI merges every module's edges into one
package-wide graph for inspection::

    python -m deeplearning4j_tpu.analysis.concurrency [--dot] [paths...]
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from deeplearning4j_tpu.analysis.context import (
    ModuleContext, attr_base, terminal_attr,
)
from deeplearning4j_tpu.analysis.findings import Severity
from deeplearning4j_tpu.analysis.rules import Rule, register_rule

# Constructors that create a lock object. The factory names keep the
# static tier seeing locks after modules adopt the runtime tracer.
_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition",
               "named_lock": "lock", "named_rlock": "rlock",
               "named_condition": "condition"}

_HTTP_FNS = {"urlopen", "post_json", "get_text"}
_SOCKET_ATTRS = {"create_connection", "getaddrinfo"}
_STOPPY_ATTRS = {"stop", "shutdown"}  # join worker threads by convention


class _LockRegion:
    """One ``with <lock>:`` region: the held lock plus its body."""

    __slots__ = ("lock_id", "node", "owner", "outer")

    def __init__(self, lock_id: str, node, owner: str,
                 outer: List[str]):
        self.lock_id = lock_id
        self.node = node          # the With node (line anchor)
        self.owner = owner        # qualname of the enclosing function
        self.outer = outer        # locks already held when this one taken


class _Edge:
    """One may-hold→then-acquire observation with its witness."""

    __slots__ = ("src", "dst", "node", "owner", "chain")

    def __init__(self, src: str, dst: str, node, owner: str, chain: str):
        self.src = src
        self.dst = dst
        self.node = node
        self.owner = owner
        self.chain = chain

    def witness(self) -> str:
        return f"{self.owner}: {self.chain}"


class _Blocked:
    """One blocking call observed inside a held-lock region."""

    __slots__ = ("lock_id", "node", "owner", "category", "chain")

    def __init__(self, lock_id: str, node, owner: str, category: str,
                 chain: str):
        self.lock_id = lock_id
        self.node = node
        self.owner = owner
        self.category = category
        self.chain = chain


class LockModel:
    """Interprocedural (intra-module) lock model for one ModuleContext."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        #: canonical lock id ("Class.attr" / "name") -> kind
        self.locks: Dict[str, str] = {}
        #: alias lock id -> canonical id (Condition wrapping a lock)
        self.aliases: Dict[str, str] = {}
        #: per-function direct acquisitions
        self._direct_acq: Dict[str, Set[str]] = {}
        #: per-function direct blocking calls [(category, label)]
        self._direct_blk: Dict[str, List[Tuple[str, str]]] = {}
        #: closures with one witness chain each
        self.acq_closure: Dict[str, Dict[str, str]] = {}
        self.blk_closure: Dict[str, Dict[Tuple[str, str], str]] = {}
        self.edges: List[_Edge] = []
        self.blocked: List[_Blocked] = []
        self._find_locks()
        if self.locks:
            self._summarize_functions()
            self._close_summaries()
            self._scan_regions()

    # ------------------------------------------------------------ discovery

    def _lock_ctor_kind(self, value) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        term = terminal_attr(value.func)
        if term not in _LOCK_CTORS:
            return None
        base = attr_base(value.func)
        if term in ("Lock", "RLock", "Condition"):
            if base not in ("threading", term):  # threading.Lock / bare Lock
                return None
        return _LOCK_CTORS[term]

    def _find_locks(self):
        # First pass: creations. Second pass handles Condition(self._lock)
        # aliases (the wrapped lock may be assigned later in source order,
        # so aliasing resolves after all creations are known).
        pending_alias: List[Tuple[str, str]] = []
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            kind = self._lock_ctor_kind(value)
            if kind is None:
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            owner = self.ctx.context_of(node)
            cls = (self.ctx.functions[owner].class_name
                   if owner in self.ctx.functions else None)
            for tgt in targets:
                lock_id = self._target_id(tgt, cls)
                if lock_id is None:
                    continue
                self.locks[lock_id] = kind
                if kind == "condition" and value.args:
                    wrapped = self._expr_id(value.args[0], cls)
                    if wrapped is not None:
                        pending_alias.append((lock_id, wrapped))
        for cond_id, wrapped in pending_alias:
            if wrapped in self.locks:
                # the condition and its wrapped lock are one mutex
                self.aliases[cond_id] = wrapped
                self.locks.pop(cond_id, None)

    def _target_id(self, tgt, cls: Optional[str]) -> Optional[str]:
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self" and cls is not None):
            return f"{cls}.{tgt.attr}"
        if isinstance(tgt, ast.Name):
            return tgt.id
        return None

    def _expr_id(self, expr, cls: Optional[str]) -> Optional[str]:
        """Resolve a lock-valued expression to a canonical lock id."""
        lock_id = None
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and cls is not None):
            lock_id = f"{cls}.{expr.attr}"
        elif isinstance(expr, ast.Name):
            lock_id = expr.id
        if lock_id is None:
            return None
        lock_id = self.aliases.get(lock_id, lock_id)
        return lock_id if lock_id in self.locks else None

    def _class_of(self, qual: str) -> Optional[str]:
        info = self.ctx.functions.get(qual)
        return info.class_name if info is not None else None

    # ----------------------------------------------------------- summaries

    def _summarize_functions(self):
        for qual, info in self.ctx.functions.items():
            cls = info.class_name
            acq: Set[str] = set()
            blk: List[Tuple[str, str]] = []
            for node in _walk_no_defs(info.node):
                if isinstance(node, ast.With):
                    for item in node.items:
                        lid = self._expr_id(item.context_expr, cls)
                        if lid is not None:
                            acq.add(lid)
                elif isinstance(node, ast.Call):
                    f = node.func
                    if (isinstance(f, ast.Attribute)
                            and f.attr == "acquire"):
                        lid = self._expr_id(f.value, cls)
                        if lid is not None:
                            acq.add(lid)
                    cat = self._classify_blocking(node, cls, held=None)
                    if cat is not None:
                        blk.append(cat)
            self._direct_acq[qual] = acq
            self._direct_blk[qual] = blk

    def _close_summaries(self):
        """Fixpoint propagation of acquire/blocking summaries along the
        intra-module call graph, keeping one witness chain per fact —
        the same closure style `jit_reachable` uses, but per function."""
        acq = {q: {lid: f"with {lid}" for lid in s}
               for q, s in self._direct_acq.items()}
        blk = {q: {key: f"{key[1]}" for key in lst}
               for q, lst in self._direct_blk.items()}
        for _ in range(len(self.ctx.functions) + 1):
            changed = False
            for qual in self.ctx.functions:
                for kind, name in self.ctx.calls.get(qual, ()):
                    target = self.ctx._resolve(qual, kind, name)
                    if target is None or target == qual:
                        continue
                    for lid, chain in acq.get(target, {}).items():
                        if lid not in acq[qual]:
                            acq[qual][lid] = f"{name}() -> {chain}"
                            changed = True
                    for key, chain in blk.get(target, {}).items():
                        if key not in blk[qual]:
                            blk[qual][key] = f"{name}() -> {chain}"
                            changed = True
            if not changed:
                break
        self.acq_closure = acq
        self.blk_closure = blk

    # ------------------------------------------------------------- regions

    def _scan_regions(self):
        for qual, info in self.ctx.functions.items():
            body = info.node.body
            if not isinstance(body, list):
                continue  # lambda: expression body, no with-regions
            self._scan_stmts(body, qual, info.class_name, [])

    def _scan_stmts(self, stmts, qual: str, cls: Optional[str],
                    held: List[str]):
        for stmt in stmts:
            self._scan_node(stmt, qual, cls, held)

    def _scan_node(self, node, qual: str, cls: Optional[str],
                   held: List[str]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, ast.With):
            inner = list(held)
            for item in node.items:
                lid = self._expr_id(item.context_expr, cls)
                if lid is not None:
                    for outer in inner:
                        self._note_edge(outer, lid, node, qual,
                                        f"holds {outer}, takes {lid}")
                    inner = inner + [lid]
                else:
                    self._scan_expr(item.context_expr, qual, cls, held)
            self._scan_stmts(node.body, qual, cls, inner)
            return
        if isinstance(node, ast.Call):
            self._scan_call(node, qual, cls, held)
            # still descend: nested calls in args are separate call nodes
        for child in ast.iter_child_nodes(node):
            self._scan_node(child, qual, cls, held)

    def _scan_expr(self, expr, qual: str, cls: Optional[str],
                   held: List[str]):
        for child in ast.walk(expr):
            if isinstance(child, ast.Call):
                self._scan_call(child, qual, cls, held)

    def _scan_call(self, node, qual: str, cls: Optional[str],
                   held: List[str]):
        if not held:
            return
        f = node.func
        # explicit .acquire() of another known lock while holding one
        if isinstance(f, ast.Attribute) and f.attr == "acquire":
            lid = self._expr_id(f.value, cls)
            if lid is not None:
                for outer in held:
                    self._note_edge(outer, lid, node, qual,
                                    f"holds {outer}, acquires {lid}")
                return
        # direct blocking call inside the held region
        cat = self._classify_blocking(node, cls, held=held)
        if cat is not None:
            self.blocked.append(_Blocked(held[-1], node, qual, cat[0],
                                         cat[1]))
            return
        # interprocedural: the callee's transitive acquires/blocking
        target = self._resolve_call(node, qual)
        if target is None or target == qual:
            return
        if target in self.ctx.jit_roots:
            self.blocked.append(_Blocked(
                held[-1], node, qual, "device dispatch",
                f"call to jitted `{_short(target)}`"))
            return
        for lid, chain in self.acq_closure.get(target, {}).items():
            for outer in held:
                if lid != outer:
                    self._note_edge(outer, lid, node, qual,
                                    f"holds {outer}, calls "
                                    f"{_short(target)}() -> {chain}")
        for (category, label), chain in self.blk_closure.get(
                target, {}).items():
            self.blocked.append(_Blocked(
                held[-1], node, qual, category,
                f"{_short(target)}() -> {chain}"))

    def _resolve_call(self, node, qual: str) -> Optional[str]:
        f = node.func
        if isinstance(f, ast.Name):
            return self.ctx._resolve(qual, "name", f.id)
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self"):
            return self.ctx._resolve(qual, "self", f.attr)
        return None

    def _note_edge(self, src: str, dst: str, node, owner: str, chain: str):
        if src == dst:
            return  # reentrancy, not ordering
        self.edges.append(_Edge(src, dst, node, owner, chain))

    # ------------------------------------------------------ blocking calls

    def _classify_blocking(self, node, cls: Optional[str],
                           held: Optional[List[str]]
                           ) -> Optional[Tuple[str, str]]:
        """(category, label) when `node` is a call that can block the
        thread; None otherwise. `held` enables the same-lock wait
        exemption (a summary pass passes None and keeps waits out — a
        callee's `cond.wait()` belongs to the callee's own lock)."""
        ctx = self.ctx
        f = node.func
        term = terminal_attr(f)
        base = attr_base(f)
        kwargs = {kw.arg for kw in node.keywords}
        if term == "block_until_ready":
            return ("device sync", ".block_until_ready()")
        if term == "device_put" and base in (ctx.jax_aliases | {"jax"}):
            return ("device dispatch", f"{base}.device_put()")
        if isinstance(f, ast.Name) and f.id in _HTTP_FNS:
            return ("network I/O", f"{f.id}()")
        if isinstance(f, ast.Attribute) and term in _HTTP_FNS:
            return ("network I/O", f".{term}()")
        if term in _SOCKET_ATTRS and base == "socket":
            return ("network I/O", f"socket.{term}()")
        if term == "sleep" and (base in ctx.time_aliases
                                or isinstance(f, ast.Name)):
            return ("sleep", "sleep()")
        if isinstance(f, ast.Attribute):
            recv = terminal_attr(f.value) or ""
            if term == "join" and not node.args:
                return ("thread join", f"{recv}.join()")
            if (term in _STOPPY_ATTRS and recv
                    and recv not in ("self", "cls")):
                return ("worker stop/join", f"{recv}.{term}()")
            if term == "get" and "queue" in recv.lower():
                return ("queue wait", f"{recv}.get()")
            if (term in ("wait", "wait_for")
                    and held is not None):
                lid = self._expr_id(f.value, cls)
                if lid is not None and lid in held:
                    return None  # waiting on the held lock's condition
                if "timeout" not in kwargs and len(node.args) < (
                        2 if term == "wait_for" else 1):
                    return ("blocking wait", f"{recv}.{term}()")
            # coordinator/client RPCs: any method on a *client handle
            if "client" in recv.lower() or "coordinator" in recv.lower():
                return ("coordinator RPC", f"{recv}.{term}()")
        return None

    # ------------------------------------------------------------- queries

    def order_edges(self) -> Dict[Tuple[str, str], List[_Edge]]:
        out: Dict[Tuple[str, str], List[_Edge]] = {}
        for e in self.edges:
            out.setdefault((e.src, e.dst), []).append(e)
        return out

    def cycles(self) -> List[List[Tuple[str, str]]]:
        """Distinct cycles in the order graph as edge lists, each edge a
        (src, dst) key into :meth:`order_edges`. Deterministic order."""
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.order_edges():
            adj.setdefault(a, set()).add(b)
        seen_cycles: Set[frozenset] = set()
        out: List[List[Tuple[str, str]]] = []
        for (a, b) in sorted(self.order_edges()):
            path = _find_path(adj, b, a)
            if path is None:
                continue
            nodes = frozenset([a] + path)
            if nodes in seen_cycles:
                continue
            seen_cycles.add(nodes)
            cycle_nodes = [a, b] + path[1:]  # a -> b -> ... -> a
            out.append([(cycle_nodes[i], cycle_nodes[i + 1])
                        for i in range(len(cycle_nodes) - 1)])
        return out


def _find_path(adj: Dict[str, Set[str]], src: str, dst: str
               ) -> Optional[List[str]]:
    """Shortest path src..dst (inclusive) over `adj`, None when absent."""
    if src == dst:
        return [src]
    frontier = [[src]]
    seen = {src}
    while frontier:
        nxt: List[List[str]] = []
        for path in frontier:
            for peer in sorted(adj.get(path[-1], ())):
                if peer == dst:
                    return path + [peer]
                if peer not in seen:
                    seen.add(peer)
                    nxt.append(path + [peer])
        frontier = nxt
    return None


def _walk_no_defs(fn_node) -> Iterator[ast.AST]:
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _short(qual: str) -> str:
    return qual.rsplit(".<locals>.", 1)[-1].rsplit(".", 1)[-1]


# ----------------------------------------------------------------- rules

# One LockModel per (ModuleContext) — JX017 and JX018 share the pass.
_MODEL_CACHE: Dict[int, LockModel] = {}


def model_for(ctx: ModuleContext) -> LockModel:
    model = _MODEL_CACHE.get(id(ctx))
    if model is None or model.ctx is not ctx:
        _MODEL_CACHE.clear()  # one module in flight at a time
        model = LockModel(ctx)
        _MODEL_CACHE[id(ctx)] = model
    return model


def _skip(ctx: ModuleContext) -> bool:
    rel = ctx.rel.replace("\\", "/")
    return "/analysis/" in rel or rel.startswith("analysis/")


@register_rule
class LockOrderRule(Rule):
    """JX017: potential lock-order inversion (deadlock on interleave).

    Two code paths acquire the same locks in opposite orders: the
    may-hold→then-acquire graph built from every ``with``/``acquire``
    region (closed over the intra-module call graph) contains a cycle.
    The first schedule that interleaves the two paths deadlocks — the
    bug ships silently because each path is correct alone. Reported once
    per cycle with a witness path for every edge.
    """

    id = "JX017"
    description = ("lock-order inversion: two paths acquire the same "
                   "locks in opposite orders")

    example = '''\
import threading

class Transfer:
    def __init__(self):
        self._src = threading.Lock()
        self._dst = threading.Lock()

    def push(self):
        with self._src:
            with self._dst:
                pass

    def pull(self):
        with self._dst:
            with self._src:
                pass
'''

    def check(self, ctx):
        if _skip(ctx):
            return
        model = model_for(ctx)
        if not model.locks:
            return
        edge_map = model.order_edges()
        for cycle in model.cycles():
            witnesses = "; ".join(
                edge_map[key][0].witness() for key in cycle)
            ring = " -> ".join([cycle[0][0]] + [b for _, b in cycle])
            anchor = edge_map[cycle[0]][0]
            yield self.finding(
                ctx, anchor.node,
                f"lock-order inversion {ring}: {witnesses} — opposite "
                "acquisition orders deadlock when the paths interleave")


@register_rule
class BlockingUnderLockRule(Rule):
    """JX018: blocking call while holding a lock.

    Device dispatch (jitted-program calls, ``block_until_ready``,
    ``device_put``), outbound HTTP/socket I/O, coordinator RPCs,
    ``queue.get``, thread ``join`` / worker ``stop()``, ``sleep`` and
    unbounded foreign ``wait`` inside a held-lock region serialize every
    other thread behind one slow operation — the `_reload` and
    rolling-update bug shape: the lock is held for the duration of I/O
    that can take seconds, so health checks, admission and unrelated
    models all stall. Waiting on the held lock's own condition is
    exempt. Move the slow call off the lock: snapshot under the lock,
    do the work outside, re-take the lock to publish.
    """

    id = "JX018"
    description = ("blocking call (device dispatch / network / join / "
                   "sleep / RPC) while holding a lock")

    example = '''\
import threading
import time

class Registry:
    def __init__(self):
        self._lock = threading.Lock()

    def refresh(self):
        with self._lock:
            time.sleep(1.0)
'''

    def check(self, ctx):
        if _skip(ctx):
            return
        model = model_for(ctx)
        if not model.locks:
            return
        for b in model.blocked:
            yield self.finding(
                ctx, b.node,
                f"{b.category} while holding {b.lock_id}: {b.chain} — "
                "blocks every thread contending this lock for the "
                "call's duration; snapshot under the lock and do the "
                "slow work outside",
                Severity.WARNING)


# ------------------------------------------------------------------- CLI


def package_graph(paths: Optional[Sequence[str]] = None):
    """(edges, cycles, lock_kinds) merged across modules, lock ids
    qualified by repo-relative path so the graph is package-wide."""
    import os

    from deeplearning4j_tpu.analysis.linter import (
        _PKG_DIR, _relpath, iter_py_files,
    )

    files: List[str] = []
    for p in (paths or [_PKG_DIR]):
        if os.path.isdir(p):
            files.extend(iter_py_files(p))
        else:
            files.append(p)
    edges: Dict[Tuple[str, str], List[str]] = {}
    kinds: Dict[str, str] = {}
    cycles: List[Tuple[str, List[str]]] = []
    for path in files:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            rel = _relpath(path)
            ctx = ModuleContext(src, path, rel)
        except (OSError, SyntaxError):
            continue
        if _skip(ctx):
            continue
        model = LockModel(ctx)
        if not model.locks:
            continue
        mod = rel.rsplit("/", 1)[-1].rsplit(".py", 1)[0]
        for lid, kind in model.locks.items():
            kinds[f"{mod}.{lid}"] = kind
        for (a, b), es in model.order_edges().items():
            edges.setdefault((f"{mod}.{a}", f"{mod}.{b}"), []).extend(
                f"{rel}:{e.node.lineno} {e.witness()}" for e in es)
        edge_map = model.order_edges()
        for cycle in model.cycles():
            ring = " -> ".join([cycle[0][0]] + [bb for _, bb in cycle])
            cycles.append((f"{mod}: {ring}",
                           [edge_map[k][0].witness() for k in cycle]))
    return edges, cycles, kinds


def to_dot(edges, cycles, kinds) -> str:
    cyclic_nodes = set()
    for desc, _ in cycles:
        ring = desc.split(": ", 1)[1]
        mod = desc.split(":", 1)[0]
        cyclic_nodes.update(f"{mod}.{n}" for n in ring.split(" -> "))
    lines = ["digraph lock_order {", '  rankdir="LR";',
             '  node [shape=box, fontsize=10];']
    for node in sorted(kinds):
        attrs = [f'label="{node}\\n({kinds[node]})"']
        if node in cyclic_nodes:
            attrs.append('color="red"')
        lines.append(f'  "{node}" [{", ".join(attrs)}];')
    for (a, b), witnesses in sorted(edges.items()):
        color = ', color="red"' if a in cyclic_nodes and b in cyclic_nodes \
            else ""
        lines.append(f'  "{a}" -> "{b}" [label="{len(witnesses)}"{color}];')
    lines.append("}")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis.concurrency",
        description="Static lock-order graph + witness paths "
                    "(JX017/JX018 model)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs (default: the whole package)")
    ap.add_argument("--dot", action="store_true",
                    help="emit the graph as Graphviz DOT on stdout")
    args = ap.parse_args(argv)

    edges, cycles, kinds = package_graph(args.paths or None)
    if args.dot:
        print(to_dot(edges, cycles, kinds), end="")
        return 0
    print(f"lock-order graph: {len(kinds)} lock(s), "
          f"{len(edges)} ordered edge(s), {len(cycles)} cycle(s)")
    for (a, b), witnesses in sorted(edges.items()):
        print(f"  {a} -> {b}  [{len(witnesses)} path(s)]")
        for w in witnesses[:3]:
            print(f"      {w}")
    if cycles:
        print("cycles (JX017):")
        for desc, witnesses in cycles:
            print(f"  {desc}")
            for w in witnesses:
                print(f"      {w}")
    return 1 if cycles else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
