"""tpulint — JAX/TPU-aware static analysis + runtime strict-mode guards.

The reference DL4J stack validated configuration on the JVM side
(`MultiLayerConfiguration` sanity checks) before any native kernel ran.
This package is the JAX port's equivalent, split in two:

- **Static** (`linter.py`, `rules.py`): an AST pass over every module in
  the package with framework-aware rules (JX001-JX010) for the failure
  modes that are *silent* on TPU — host syncs inside traced code, Python
  side effects baked in at trace time, retrace storms, accidental
  float64, unlocked cross-thread mutation, dtype-sniffing on user input,
  AOT machinery outside `compilation/`, metrics family creation in hot
  paths, hardcoded compute dtypes in layer kernels, and Pallas
  imports outside the kernel registry (`kernels/`, JX010).
  Run it with ``python -m deeplearning4j_tpu.analysis`` (or the
  ``tpulint`` console script); findings are suppressible inline
  (``# tpulint: disable=JX001``) or grandfathered in a checked-in
  baseline where every entry carries a reason.

- **Runtime** (`runtime.py`): ``strict_mode()`` wraps a step body in
  ``jax.transfer_guard("disallow")``; ``RetraceGuard`` fires when one
  function compiles more than N times (wired to the engines' jit-cache
  counters from the observability core); ``install_nan_guard`` hooks the
  engines' ``_fit_dispatch`` to fail fast on a NaN loss.

Tier-1 runs the full-package lint (`tests/test_static_analysis.py`), so a
new violation fails CI before it costs a TPU hour.
"""

from __future__ import annotations

from deeplearning4j_tpu.analysis.findings import Finding, Severity
from deeplearning4j_tpu.analysis.rules import ALL_RULES, Rule, get_rules
from deeplearning4j_tpu.analysis.linter import (
    DEFAULT_BASELINE_PATH,
    Baseline,
    fingerprint,
    lint_file,
    lint_package,
    lint_paths,
    lint_source,
)
from deeplearning4j_tpu.analysis.runtime import (
    RetraceError,
    RetraceGuard,
    install_nan_guard,
    strict_enabled,
    strict_mode,
)

__all__ = [
    "Finding", "Severity", "Rule", "ALL_RULES", "get_rules",
    "lint_source", "lint_file", "lint_paths", "lint_package",
    "Baseline", "fingerprint", "DEFAULT_BASELINE_PATH",
    "strict_mode", "strict_enabled", "RetraceGuard", "RetraceError",
    "install_nan_guard",
]
