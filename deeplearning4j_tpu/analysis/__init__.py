"""tpulint — JAX/TPU-aware static analysis + runtime strict-mode guards.

The reference DL4J stack validated configuration on the JVM side
(`MultiLayerConfiguration` sanity checks) before any native kernel ran.
This package is the JAX port's equivalent, split in two:

- **Static** (`linter.py`, `rules.py`, `concurrency.py`): an AST pass
  over every module in the package with framework-aware rules
  (JX001-JX019) for the failure modes that are *silent* on TPU:

  ========  ========================================================
  JX001     host sync (.item/.block_until_ready/np.asarray) under jit
  JX002     Python side effects (print/time/random) baked at trace
  JX003     retrace hazards (jit-in-loop, jit(lambda), static arrays)
  JX004     float64 literals in traced code (TPU emulates f64)
  JX005     cross-thread attribute mutation without the class lock
  JX006     dtype-sniffing outside nn/conf/preprocessors.py
  JX007     AOT machinery (.lower/.compile/jax.export) outside
            compilation/
  JX008     metrics family creation in jit-reachable or looped code
  JX009     hardcoded f32 compute dtype in nn/layers/ kernels
  JX010     Pallas imports outside the kernel registry (kernels/)
  JX011     synchronous host->device staging in fit/dispatch loops
  JX012     blocking socket/HTTP without a timeout in serving/parallel
  JX013     outbound HTTP hop that drops the X-DL4J-Trace context
  JX014     dense full-length KV buffers outside the paged pool
  JX015     grad/updater work over frozen/LoRA leaves outside the seam
  JX016     metric labels fed from unbounded per-request data
  JX017     lock-order inversion across code paths (deadlock cycle)
  JX018     blocking call (dispatch/HTTP/join/sleep/RPC) under a lock
  JX019     residual add + activation unfused next to a conv in
            nn/layers/ (route through the bottleneck_block seam)
  ========  ========================================================

  JX017/JX018 come from the interprocedural lock model in
  `concurrency.py` (``python -m deeplearning4j_tpu.analysis.concurrency
  [--dot]`` prints the package-wide lock-order graph). Run the linter
  with ``python -m deeplearning4j_tpu.analysis`` (or the ``tpulint``
  console script); ``--explain JXnnn`` prints a rule's docstring and a
  minimal true-positive example. Findings are suppressible inline
  (``# tpulint: disable=JX001``) or grandfathered in a checked-in
  baseline where every entry carries a reason.

- **Runtime** (`runtime.py`, `locktrace.py`): ``strict_mode()`` wraps a
  step body in ``jax.transfer_guard("disallow")``; ``RetraceGuard``
  fires when one function compiles more than N times (wired to the
  engines' jit-cache counters from the observability core);
  ``install_nan_guard`` hooks the engines' ``_fit_dispatch`` to fail
  fast on a NaN loss. `locktrace.py` is JX017/JX018's runtime twin: an
  opt-in (``DL4J_TPU_LOCKTRACE=1``) traced-lock factory adopted by the
  serving/fleet/observability packages, with online lock-order cycle
  detection and a stall watchdog that dumps one rate-limited flight
  bundle (``locks.json``: thread stacks + the lock graph) when an
  acquire blocks past ``DL4J_TPU_LOCK_STALL_S``.

Tier-1 runs the full-package lint (`tests/test_static_analysis.py`), so a
new violation fails CI before it costs a TPU hour.
"""

from __future__ import annotations

from deeplearning4j_tpu.analysis.findings import Finding, Severity
from deeplearning4j_tpu.analysis.rules import ALL_RULES, Rule, get_rules
from deeplearning4j_tpu.analysis.linter import (
    DEFAULT_BASELINE_PATH,
    Baseline,
    fingerprint,
    lint_file,
    lint_package,
    lint_paths,
    lint_source,
)
from deeplearning4j_tpu.analysis.runtime import (
    RetraceError,
    RetraceGuard,
    install_nan_guard,
    strict_enabled,
    strict_mode,
)

__all__ = [
    "Finding", "Severity", "Rule", "ALL_RULES", "get_rules",
    "lint_source", "lint_file", "lint_paths", "lint_package",
    "Baseline", "fingerprint", "DEFAULT_BASELINE_PATH",
    "strict_mode", "strict_enabled", "RetraceGuard", "RetraceError",
    "install_nan_guard",
]
