"""Bench regression sentinel: diff two BENCH_out.json-shaped documents.

    python -m deeplearning4j_tpu.analysis.benchdiff BENCH_out.json BASELINE.json

Matches metrics BY NAME between the two documents — the headline entry
(top-level ``metric``/``value``) plus every named entry under ``extra``
(either ``{"name": number}`` or ``{"name": {"value": ..., "unit": ...}}``)
— computes ``current/baseline`` per shared metric, and exits non-zero
when any ratio regresses beyond its tolerance. A metric present in only
one document is reported and skipped: the sentinel gates CHANGE, it
doesn't demand identical coverage (the committed BASELINE.json predates
most configs).

Direction is inferred per metric: latency-like metrics (unit ``ms``/
``s``, or a name mentioning latency/p50/p99/ttft/itl/overhead/seconds,
or bytes-moved-per-step traffic) regress UP; everything else
(throughput, accept rates, hit ratios) regresses DOWN. Tolerance
defaults to 5% and is overridable globally (``--tolerance 0.1``) or per
metric (``--tol name=0.2``, repeatable) — noisy microbenches get wide
bands without loosening the rest. ``DEFAULT_TOLS`` below carries the
repo's standing per-metric bands (known-noisy configs); CLI ``--tol``
overrides win over it.

Exit codes: 0 ok (including "no shared metrics"), 1 regression,
2 usage/IO error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Optional, Tuple

#: Substrings marking a lower-is-better metric name.
_LOWER_IS_BETTER_HINTS = ("latency", "p50", "p90", "p99", "ttft", "itl",
                          "seconds", "overhead", "_ms", "wait", "stall",
                          "bytes_per_step", "per_chip_bytes")
_LOWER_IS_BETTER_UNITS = ("ms", "s", "seconds", "us", "ns")

#: Standing per-metric tolerance bands, merged beneath CLI --tol
#: overrides. The fused-bottleneck config runs a deliberately small
#: model (BENCH_*_RESNET50_FUSED) so its absolute throughput is noisy
#: run-to-run — the stable signal is the in-entry
#: vs_xla_fallback_same_run ratio, which this sentinel doesn't gate.
#: The bytes-per-step entries come from XLA cost analysis and only move
#: when lowering changes, so they get a tight band: silent HBM-traffic
#: growth is exactly what the fused kernel exists to prevent.
DEFAULT_TOLS: Dict[str, float] = {
    "resnet50_fused_bottleneck_fit_samples_per_sec_per_chip": 0.25,
    "resnet50_fused_bottleneck_bytes_per_step": 0.10,
    "resnet50_train_bytes_per_step": 0.10,
    # Sharded decode runs on an emulated CPU host-device mesh, so its
    # tokens/sec is scheduler+collective overhead and noisy run-to-run;
    # the per-chip bytes ratio is a pure layout property and only moves
    # when the sharding rules change, so it gets the tight band (it
    # regresses UP — growth means weights/KV stopped splitting).
    "lm_sharded_decode_tokens_per_sec": 0.25,
    "lm_sharded_decode_per_chip_bytes_ratio": 0.10,
}


def extract_metrics(doc: dict) -> Dict[str, float]:
    """``{metric_name: value}`` from one bench document: the headline
    pair plus the named ``extra`` entries. Non-numeric values (prose
    metrics in paper-metadata baselines) are skipped."""
    out: Dict[str, float] = {}

    def put(name, value):
        if not isinstance(name, str) or not name:
            return
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return
        out[name] = float(value)

    if isinstance(doc.get("metric"), str):
        put(doc["metric"], doc.get("value"))
    extra = doc.get("extra")
    if isinstance(extra, dict):
        for name, entry in extra.items():
            if isinstance(entry, dict):
                put(name, entry.get("value"))
            else:
                put(name, entry)
    return out


def units_of(doc: dict) -> Dict[str, str]:
    out: Dict[str, str] = {}
    if isinstance(doc.get("metric"), str) and doc.get("unit"):
        out[doc["metric"]] = str(doc["unit"])
    extra = doc.get("extra")
    if isinstance(extra, dict):
        for name, entry in extra.items():
            if isinstance(entry, dict) and entry.get("unit"):
                out[str(name)] = str(entry["unit"])
    return out


def lower_is_better(name: str, unit: Optional[str]) -> bool:
    if unit and unit.lower() in _LOWER_IS_BETTER_UNITS:
        return True
    low = name.lower()
    return any(h in low for h in _LOWER_IS_BETTER_HINTS)


def diff(current: dict, baseline: dict, tolerance: float = 0.05,
         per_metric: Optional[Dict[str, float]] = None
         ) -> Tuple[list, list]:
    """Compare two bench documents. Returns ``(rows, regressions)``:
    every shared metric's row, and the subset that regressed beyond
    tolerance. A row is ``{metric, current, baseline, ratio, direction,
    tolerance, regressed}``."""
    per_metric = dict(DEFAULT_TOLS, **(per_metric or {}))
    cur = extract_metrics(current)
    base = extract_metrics(baseline)
    units = dict(units_of(baseline), **units_of(current))
    rows, regressions = [], []
    for name in sorted(set(cur) & set(base)):
        b = base[name]
        if b == 0:
            continue  # a zero baseline has no ratio
        ratio = cur[name] / b
        lower = lower_is_better(name, units.get(name))
        tol = float(per_metric.get(name, tolerance))
        regressed = (ratio > 1.0 + tol) if lower else (ratio < 1.0 - tol)
        row = {"metric": name, "current": cur[name], "baseline": b,
               "ratio": ratio,
               "direction": "lower_is_better" if lower
               else "higher_is_better",
               "tolerance": tol, "regressed": regressed}
        rows.append(row)
        if regressed:
            regressions.append(row)
    return rows, regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analysis.benchdiff",
        description="Exit non-zero when a bench metric regressed "
                    "beyond tolerance vs a baseline document.")
    ap.add_argument("current", help="BENCH_out.json from this run")
    ap.add_argument("baseline", help="baseline document to gate against")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="default tolerated relative regression "
                         "(0.05 = 5%%)")
    ap.add_argument("--tol", action="append", default=[],
                    metavar="METRIC=FRACTION",
                    help="per-metric tolerance override (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)

    per_metric: Dict[str, float] = {}
    for spec in args.tol:
        name, sep, frac = spec.partition("=")
        if not sep:
            print(f"bad --tol {spec!r} (want METRIC=FRACTION)",
                  file=sys.stderr)
            return 2
        try:
            per_metric[name] = float(frac)
        except ValueError:
            print(f"bad --tol fraction in {spec!r}", file=sys.stderr)
            return 2
    docs = []
    for path in (args.current, args.baseline):
        try:
            with open(path) as fh:
                docs.append(json.load(fh))
        except (OSError, ValueError) as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 2

    rows, regressions = diff(docs[0], docs[1], tolerance=args.tolerance,
                             per_metric=per_metric)
    if args.json:
        print(json.dumps({"rows": rows,
                          "regressions": [r["metric"]
                                          for r in regressions]}))
    else:
        if not rows:
            print("benchdiff: no shared metrics between "
                  f"{args.current} and {args.baseline}; nothing to gate")
        for r in rows:
            flag = "REGRESSED" if r["regressed"] else "ok"
            print(f"{flag:9s} {r['metric']}: {r['current']:g} vs "
                  f"{r['baseline']:g} (ratio {r['ratio']:.4f}, "
                  f"{r['direction']}, tol {r['tolerance']:.2%})")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
