"""`python -m deeplearning4j_tpu.analysis` / `tpulint` CLI.

Prints findings and exits non-zero on any *new* (non-baseline) violation
or on a baseline entry without a reason — the contract tier-1 enforces.
"""

from __future__ import annotations

import argparse
import json
import sys

from deeplearning4j_tpu.analysis.findings import Severity
from deeplearning4j_tpu.analysis.linter import (
    DEFAULT_BASELINE_PATH, Baseline, lint_package, lint_paths,
)
from deeplearning4j_tpu.analysis.rules import ALL_RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint",
        description="JAX/TPU-aware static analysis for deeplearning4j_tpu")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the whole package)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE_PATH,
                    help="baseline JSON of grandfathered findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(existing reasons are preserved by fingerprint)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable JSON output")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--explain", metavar="JXnnn",
                    help="print a rule's full docstring and a minimal "
                         "true-positive example, then exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in sorted(ALL_RULES):
            print(f"{rid}  {ALL_RULES[rid].description}")
        return 0

    if args.explain:
        rid = args.explain.upper()
        cls = ALL_RULES.get(rid)
        if cls is None:
            print(f"tpulint: unknown rule {args.explain!r}; known: "
                  f"{', '.join(sorted(ALL_RULES))}", file=sys.stderr)
            return 2
        import inspect

        print(f"{rid}  {cls.description}")
        print()
        print(inspect.cleandoc(cls.__doc__ or "(no docstring)"))
        if cls.example:
            print()
            print("Minimal true positive:")
            print()
            for line in cls.example.rstrip("\n").split("\n"):
                print(f"    {line}")
        return 0

    rules = args.rules.split(",") if args.rules else None
    unknown = [r for r in (rules or []) if r not in ALL_RULES]
    if unknown:
        print(f"tpulint: unknown rule(s): {', '.join(unknown)}",
              file=sys.stderr)
        return 2

    findings = (lint_paths(args.paths, rules) if args.paths
                else lint_package(rules))

    baseline = (Baseline([]) if args.no_baseline
                else Baseline.load(args.baseline))
    if args.write_baseline:
        Baseline.from_findings(findings, previous=baseline).save(
            args.baseline)
        print(f"tpulint: wrote {len(findings)} finding(s) to "
              f"{args.baseline}")
        return 0

    new, grandfathered, stale = baseline.split(findings)
    unreasoned = baseline.missing_reasons()

    if args.as_json:
        print(json.dumps({
            "new": [f.to_json() for f in new],
            "grandfathered": [f.to_json() for f in grandfathered],
            "stale_baseline": stale,
            "baseline_missing_reasons": unreasoned,
        }, indent=2))
    else:
        for f in new:
            print(f.format())
        if grandfathered:
            print(f"tpulint: {len(grandfathered)} grandfathered finding(s) "
                  f"suppressed by baseline ({args.baseline})")
        for e in stale:
            print("tpulint: stale baseline entry (no longer fires): "
                  f"{e['rule']} {e['path']} ({e.get('context')})")
        for e in unreasoned:
            print("tpulint: baseline entry missing a reason: "
                  f"{e['rule']} {e['path']} ({e.get('context')})")

    errors = sum(1 for f in new if f.severity == Severity.ERROR)
    warnings = len(new) - errors
    if not args.as_json:
        print(f"tpulint: {errors} error(s), {warnings} warning(s), "
              f"{len(grandfathered)} baselined, {len(stale)} stale")
    if new or unreasoned:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
