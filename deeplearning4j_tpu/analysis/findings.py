"""Finding/severity types shared by the linter, rules, CLI and baseline."""

from __future__ import annotations

from dataclasses import dataclass, field


class Severity:
    ERROR = "error"
    WARNING = "warning"

    ORDER = {ERROR: 0, WARNING: 1}


@dataclass(frozen=True)
class Finding:
    """One typed lint finding: ``rule id, path:line, message, severity``.

    ``context`` is the dotted qualname of the enclosing function/class
    (``<module>`` at top level); the baseline fingerprints on
    (rule, path, context, message) rather than the line number so
    unrelated edits above a grandfathered finding don't churn the
    baseline file.
    """

    rule: str
    path: str          # repo-relative posix path
    line: int
    message: str
    severity: str = Severity.ERROR
    context: str = field(default="<module>")

    def sort_key(self):
        return (self.path, self.line, self.rule)

    def format(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{self.severity}] {self.message} (in {self.context})")

    def to_json(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "message": self.message, "severity": self.severity,
            "context": self.context,
        }
