"""Runtime lock tracer: held-set tracking, online cycle detection, and a
stall watchdog that turns silent hangs into flight bundles.

The static tier (`analysis/concurrency.py`) sees one module at a time;
this is the cross-module truth. Opt-in via ``DL4J_TPU_LOCKTRACE=1``: the
``named_lock``/``named_rlock``/``named_condition`` factory — adopted by
the serving, fleet and observability packages — then returns traced
wrappers instead of plain ``threading`` primitives (disabled, it returns
the plain primitive: the off cost is one env check at construction, zero
per acquire).

Traced locks record, per thread, the stack of locks currently held and,
at every acquire *start*, the observed may-hold→then-acquire edges. A
new edge runs online cycle detection over the observed graph — an AB/BA
interleave is flagged the moment the second order is *attempted*, not
when it deadlocks. Metrics: ``dl4j_lock_order_edges`` (gauge, distinct
observed edges) and ``dl4j_lock_cycles_total`` (counter).

The **watchdog** (daemon thread, started with the first traced lock)
fires when an acquire has been blocked, or a lock held, longer than
``DL4J_TPU_LOCK_STALL_S`` (default 30): it dumps ONE flight bundle
(reason ``lock_stall``, subject to the recorder's per-reason rate limit,
so a stalled fleet produces forensics, not a disk full) and writes
``locks.json`` into the bundle: every thread's stack, its held locks and
the lock it is waiting for, the full acquisition-order graph, and any
detected cycles — enough to read a deadlock off one file.

`lock_inversion_drill` is the chaos probe (`util/faultinject.py` kind
``lock_invert``): two threads forced into AB/BA acquisition, asserting
the cycle is flagged and the watchdog produces exactly one bundle.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

ENV_ENABLE = "DL4J_TPU_LOCKTRACE"
ENV_STALL_S = "DL4J_TPU_LOCK_STALL_S"

STALL_REASON = "lock_stall"


def enabled() -> bool:
    return os.environ.get(ENV_ENABLE, "0").lower() in ("1", "true", "on")


def stall_threshold_s() -> float:
    try:
        return float(os.environ.get(ENV_STALL_S, "30"))
    except ValueError:
        return 30.0


class _Held:
    __slots__ = ("lock", "since")

    def __init__(self, lock: "TracedLock", since: float):
        self.lock = lock
        self.since = since


class _Registry:
    """Process-global tracer state. Its internal lock is a PLAIN lock and
    every metrics/flight call happens OUTSIDE it — the tracer must never
    take a traced lock (or anything that takes one) while holding its own
    state, or instrumenting the metrics registry would deadlock the
    instrumentation."""

    def __init__(self):
        self._lock = threading.Lock()
        # (src_name, dst_name) -> observation count
        self.edges: Dict[Tuple[str, str], int] = {}
        self._adj: Dict[str, set] = {}
        self.cycles: List[List[str]] = []   # detected rings, capped
        self.cycles_total = 0
        # thread ident -> held stack (the list object is shared with the
        # owning thread's TLS; only the owner mutates it)
        self.held_by_thread: Dict[int, List[_Held]] = {}
        # thread ident -> (lock name, blocked-since monotonic)
        self.pending: Dict[int, Tuple[str, float]] = {}
        self.last_stall_bundle: Optional[str] = None
        self.stall_dumps = 0
        self._watchdog: Optional[threading.Thread] = None
        self._metrics_wired = False

    # ------------------------------------------------------------ edges

    def record_edges(self, held_names: List[str], dst: str
                     ) -> Optional[List[str]]:
        """Record held->dst edges; returns a cycle ring when the newest
        edge closes one. Cycle bookkeeping happens inside the state lock;
        the CALLER emits metrics/events after release."""
        ring: Optional[List[str]] = None
        with self._lock:
            for src in held_names:
                if src == dst:
                    continue
                key = (src, dst)
                fresh = key not in self.edges
                self.edges[key] = self.edges.get(key, 0) + 1
                self._adj.setdefault(src, set()).add(dst)
                if fresh:
                    path = self._path(dst, src)
                    if path is not None:
                        ring = [src] + path
                        self.cycles_total += 1
                        if len(self.cycles) < 32:
                            self.cycles.append(ring)
        return ring

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        if src == dst:
            return [src]
        frontier, seen = [[src]], {src}
        while frontier:
            nxt = []
            for path in frontier:
                for peer in self._adj.get(path[-1], ()):
                    if peer == dst:
                        return path + [peer]
                    if peer not in seen:
                        seen.add(peer)
                        nxt.append(path + [peer])
            frontier = nxt
        return None

    # ---------------------------------------------------------- pending

    def note_pending(self, ident: int, name: str) -> None:
        with self._lock:
            self.pending[ident] = (name, time.monotonic())

    def clear_pending(self, ident: int) -> None:
        with self._lock:
            self.pending.pop(ident, None)

    def held_stack(self, ident: int) -> List[_Held]:
        with self._lock:
            stack = self.held_by_thread.get(ident)
            if stack is None:
                stack = []
                self.held_by_thread[ident] = stack
            return stack

    # ---------------------------------------------------------- watchdog

    def ensure_watchdog(self) -> None:
        with self._lock:
            if self._watchdog is not None and self._watchdog.is_alive():
                return
            self._watchdog = threading.Thread(
                target=self._watch_loop, name="dl4j-lock-watchdog",
                daemon=True)
            self._watchdog.start()

    def _watch_loop(self) -> None:
        _tls.internal = True  # the watchdog's own locking is not traced
        while True:
            stall = stall_threshold_s()
            time.sleep(min(1.0, max(0.02, stall / 4.0)))
            try:
                detail = self._find_stall(stall)
                if detail is not None:
                    self._dump_stall(detail)
            except Exception:
                pass  # forensics must never kill the process

    def _find_stall(self, stall_s: float) -> Optional[Dict[str, Any]]:
        now = time.monotonic()
        with self._lock:
            for ident, (name, since) in self.pending.items():
                if now - since > stall_s:
                    return {"kind": "acquire_blocked", "lock": name,
                            "thread": ident,
                            "seconds": round(now - since, 3)}
            for ident, stack in self.held_by_thread.items():
                for h in stack:
                    if now - h.since > stall_s:
                        return {"kind": "held_too_long",
                                "lock": h.lock.name, "thread": ident,
                                "seconds": round(now - h.since, 3)}
        return None

    def _dump_stall(self, detail: Dict[str, Any]) -> None:
        """One rate-limited bundle per stall episode: the flight
        recorder's per-reason limiter is the dedupe — every watchdog
        tick re-detects the same stall, only the first write lands."""
        try:
            from deeplearning4j_tpu.observability.flight import recorder
        except Exception:
            return
        recorder.record_event(
            "lock_stall",
            **{("stall_kind" if k == "kind" else k): v
               for k, v in detail.items()})
        bundle = recorder.dump(reason=STALL_REASON, force=False)
        if bundle is None:
            return
        payload = snapshot(stall=detail)
        try:
            with open(os.path.join(bundle, "locks.json"), "w") as f:
                json.dump(payload, f, indent=2, default=str)
        except OSError:
            pass
        with self._lock:
            self.last_stall_bundle = bundle
            self.stall_dumps += 1

    # ----------------------------------------------------------- metrics

    def wire_metrics(self) -> None:
        if self._metrics_wired:
            return
        self._metrics_wired = True
        try:
            from deeplearning4j_tpu import observability as _obs

            _obs.metrics.gauge(
                "dl4j_lock_order_edges",
                "Distinct observed lock acquisition-order edges",
            ).set_function(lambda: float(len(self.edges)))
        except Exception:
            self._metrics_wired = False

    def on_cycle(self, ring: List[str]) -> None:
        """Metric + flight event for one fresh cycle. Called with NO
        tracer state held; nested metric locking is untraced via the
        thread-local guard."""
        prev = getattr(_tls, "internal", False)
        _tls.internal = True
        try:
            try:
                from deeplearning4j_tpu import observability as _obs

                _obs.metrics.counter(
                    "dl4j_lock_cycles_total",
                    "Observed lock-order cycles (potential deadlocks)",
                ).inc()
            except Exception:
                pass
            try:
                from deeplearning4j_tpu.observability.flight import recorder

                recorder.record_event("lock_cycle",
                                      ring=" -> ".join(ring))
            except Exception:
                pass
        finally:
            _tls.internal = prev

    def reset(self) -> None:
        """Test hook: drop graph/cycle/stall state (held/pending stacks
        belong to live threads and are left alone)."""
        with self._lock:
            self.edges.clear()
            self._adj.clear()
            self.cycles.clear()
            self.cycles_total = 0
            self.last_stall_bundle = None
            self.stall_dumps = 0


_registry = _Registry()
_tls = threading.local()


def _internal() -> bool:
    return getattr(_tls, "internal", False)


def _stack() -> List[_Held]:
    """This thread's held stack, cached in TLS so the acquire hot path
    touches the global registry lock only on first use per thread."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _registry.held_stack(threading.get_ident())
        _tls.stack = stack
    return stack


class TracedLock:
    """Wrapper around a ``threading.Lock``/``RLock`` that records per-
    thread held sets and acquisition-order edges. API-compatible with
    the wrapped primitive, including the private condition-variable
    protocol (``_release_save``/``_acquire_restore``/``_is_owned``) so
    ``threading.Condition`` can drive it."""

    def __init__(self, name: str, inner=None):
        self.name = str(name)
        self._inner = inner if inner is not None else threading.Lock()
        _registry.ensure_watchdog()
        _registry.wire_metrics()

    # ------------------------------------------------------------- core

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _internal():
            return self._inner.acquire(blocking, timeout)
        ident = threading.get_ident()
        stack = _stack()
        reentrant = any(h.lock is self for h in stack)
        if not reentrant and stack:
            ring = _registry.record_edges(
                [h.lock.name for h in stack], self.name)
            if ring is not None:
                _registry.on_cycle(ring)
        if not reentrant:
            _registry.note_pending(ident, self.name)
        try:
            ok = self._inner.acquire(blocking, timeout)
        finally:
            if not reentrant:
                _registry.clear_pending(ident)
        if ok:
            stack.append(_Held(self, time.monotonic()))
        return ok

    def release(self) -> None:
        if not _internal():
            stack = _stack()
            for i in range(len(stack) - 1, -1, -1):
                if stack[i].lock is self:
                    del stack[i]
                    break
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        fn = getattr(self._inner, "locked", None)
        return bool(fn()) if fn is not None else False

    # ------------------------------------- condition-variable protocol

    def _release_save(self):
        stack = _stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].lock is self:
                del stack[i]
        inner = self._inner
        if hasattr(inner, "_release_save"):
            return inner._release_save()
        inner.release()
        return None

    def _acquire_restore(self, state) -> None:
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        _stack().append(_Held(self, time.monotonic()))

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __repr__(self) -> str:
        return f"<TracedLock {self.name!r} {self._inner!r}>"


# ----------------------------------------------------------------- factory


def named_lock(name: str):
    """A mutex for `name`: plain ``threading.Lock`` normally, a traced
    wrapper under ``DL4J_TPU_LOCKTRACE=1`` (checked at construction, so
    long-lived objects pin the mode they were built under)."""
    if not enabled():
        return threading.Lock()
    return TracedLock(name, threading.Lock())


def named_rlock(name: str):
    if not enabled():
        return threading.RLock()
    return TracedLock(name, threading.RLock())


def named_condition(name: str, lock=None):
    """A condition variable whose underlying mutex is traced when the
    tracer is on. Pass `lock` to share an existing (traced or plain)
    mutex, mirroring ``threading.Condition(lock)``."""
    if lock is None:
        lock = named_rlock(name)
    return threading.Condition(lock)


# ---------------------------------------------------------------- snapshot


def snapshot(stall: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The ``locks.json`` payload: all threads' stacks + held/waiting
    lock state + the observed order graph. Safe to call from any thread
    (including the watchdog while other threads are deadlocked)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    frames = sys._current_frames()
    with _registry._lock:
        held = {ident: [{"lock": h.lock.name,
                         "held_s": round(time.monotonic() - h.since, 3)}
                        for h in stack]
                for ident, stack in _registry.held_by_thread.items()
                if stack}
        pending = {ident: {"lock": name,
                           "blocked_s": round(
                               time.monotonic() - since, 3)}
                   for ident, (name, since) in _registry.pending.items()}
        edges = [{"from": a, "to": b, "count": n}
                 for (a, b), n in sorted(_registry.edges.items())]
        cycles = [" -> ".join(ring) for ring in _registry.cycles]
        cycles_total = _registry.cycles_total
    threads = []
    for ident, frame in frames.items():
        threads.append({
            "ident": ident,
            "name": names.get(ident, "?"),
            "held": held.get(ident, []),
            "waiting_for": pending.get(ident),
            "stack": traceback.format_stack(frame),
        })
    doc: Dict[str, Any] = {
        "format": 1,
        "threads": threads,
        "edges": edges,
        "cycles": cycles,
        "cycles_total": cycles_total,
    }
    if stall is not None:
        doc["stall"] = stall
    return doc


def stats() -> Dict[str, Any]:
    with _registry._lock:
        return {
            "enabled": enabled(),
            "edges": len(_registry.edges),
            "cycles_total": _registry.cycles_total,
            "stall_dumps": _registry.stall_dumps,
            "last_stall_bundle": _registry.last_stall_bundle,
        }


def reset() -> None:
    _registry.reset()


# ------------------------------------------------------------------ drill


def lock_inversion_drill(acquire_timeout_s: float = 2.0,
                         settle_s: float = 2.0) -> Dict[str, Any]:
    """Chaos drill (`faultinject` kind ``lock_invert``): two threads
    forced into AB/BA acquisition. Thread 1 holds A and tries B; thread
    2 holds B and tries A — a real (bounded) deadlock for up to
    `acquire_timeout_s`, long enough for the watchdog to observe a stall
    past ``DL4J_TPU_LOCK_STALL_S`` and dump its one bundle. Returns what
    the tracer saw; raises if the tracer is disabled (the drill proves
    the detection machinery, there is nothing to prove without it)."""
    if not enabled():
        raise RuntimeError(
            f"lock_inversion_drill needs {ENV_ENABLE}=1")
    before = stats()
    lock_a = named_lock("drill.a")
    lock_b = named_lock("drill.b")
    barrier = threading.Barrier(2, timeout=max(5.0, acquire_timeout_s))
    acquired: Dict[str, bool] = {}

    def leg(first, second, key):
        with first:
            try:
                barrier.wait()
            except threading.BrokenBarrierError:
                return
            got = second.acquire(timeout=acquire_timeout_s)
            acquired[key] = got
            if got:
                second.release()

    t1 = threading.Thread(target=leg, args=(lock_a, lock_b, "ab"),
                          name="dl4j-drill-ab", daemon=True)
    t2 = threading.Thread(target=leg, args=(lock_b, lock_a, "ba"),
                          name="dl4j-drill-ba", daemon=True)
    t1.start()
    t2.start()
    t1.join(timeout=acquire_timeout_s + 10.0)
    t2.join(timeout=acquire_timeout_s + 10.0)
    # the watchdog may still be writing locks.json; give it a moment
    deadline = time.monotonic() + settle_s
    while (time.monotonic() < deadline
           and stats()["stall_dumps"] == before["stall_dumps"]):
        time.sleep(0.02)
    after = stats()
    return {
        "cycle_flagged": after["cycles_total"] > before["cycles_total"],
        "stall_dumps": after["stall_dumps"] - before["stall_dumps"],
        "bundle": after["last_stall_bundle"],
        "acquired": dict(acquired),
    }
