"""tpulint rules JX001-JX016, JX019 and JX020 (JX017/JX018 live in
concurrency.py).

Each rule is a class with a stable ``id``; registration is
registry-driven (`@register_rule`) so satellite PRs add rules without
touching the linter core. Rules receive a fully-indexed
:class:`~deeplearning4j_tpu.analysis.context.ModuleContext` and yield
:class:`~deeplearning4j_tpu.analysis.findings.Finding`s; suppression and
baseline matching happen in the linter, not here.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Type

from deeplearning4j_tpu.analysis.context import (
    ModuleContext, attr_base, terminal_attr, walk_body,
)
from deeplearning4j_tpu.analysis.findings import Finding, Severity

ALL_RULES: Dict[str, Type["Rule"]] = {}


def register_rule(cls: Type["Rule"]) -> Type["Rule"]:
    ALL_RULES[cls.id] = cls
    return cls


def get_rules(only=None) -> List["Rule"]:
    ids = sorted(ALL_RULES) if only is None else list(only)
    return [ALL_RULES[i]() for i in ids]


class Rule:
    id: str = ""
    description: str = ""
    #: minimal true-positive snippet, printed by ``tpulint --explain`` and
    #: asserted to fire by the test suite; path-scoped rules set
    #: ``example_path`` to a virtual in-scope path.
    example: str = ""
    example_path: str = "<snippet>"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node, message: str,
                severity: str = Severity.ERROR) -> Finding:
        return Finding(rule=self.id, path=ctx.rel, line=node.lineno,
                       message=message, severity=severity,
                       context=ctx.context_of(node))


def _rooted_at_param(node, info) -> bool:
    """Does the expression reference one of the function's own params
    (excluding self)? Params of a traced function hold traced values;
    `float(layer.l1)`-style config access does not sync anything."""
    params = set(info.params) - {"self", "cls"}
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in params:
            return True
    return False


def _is_shape_derived(node) -> bool:
    """int(x.shape[0])-style: static under trace, not a host sync."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape", "ndim",
                                                           "size", "dtype"):
            return True
    return False


@register_rule
class HostSyncRule(Rule):
    """JX001: device->host synchronization inside trace-reachable code.

    `.block_until_ready()`, `.item()`, `float()/int()` on a traced value,
    and `np.asarray/np.array` on device values all force the async
    dispatch queue to drain (or fail outright under `jit`). Over a
    high-latency TPU transport one stray sync costs more than the step.
    """

    id = "JX001"
    description = "host sync (.item/.block_until_ready/np.asarray/float) in jit-reachable code"
    example = """\
import jax

@jax.jit
def step(x):
    y = x + 1
    y.block_until_ready()   # JX001: drains the dispatch queue mid-trace
    return y
"""

    _SYNC_ATTRS = {"block_until_ready": "drains the dispatch queue",
                   "item": "device->host scalar transfer"}

    def check(self, ctx):
        for info in ctx.reachable_functions():
            for node in walk_body(info.node):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                term = terminal_attr(f)
                if isinstance(f, ast.Attribute) and term in self._SYNC_ATTRS:
                    yield self.finding(
                        ctx, node,
                        f"`.{term}()` in traced/hot code "
                        f"({self._SYNC_ATTRS[term]})")
                elif (isinstance(f, ast.Attribute)
                      and term in ("asarray", "array")
                      and attr_base(f) in ctx.numpy_aliases):
                    yield self.finding(
                        ctx, node,
                        f"`{attr_base(f)}.{term}()` in traced code forces a "
                        "device->host transfer; use jnp or hoist to the host "
                        "side")
                elif (isinstance(f, ast.Name) and f.id in ("float", "int")
                      and len(node.args) == 1
                      and not isinstance(node.args[0], ast.Constant)
                      and not _is_shape_derived(node.args[0])
                      and _rooted_at_param(node.args[0], info)):
                    yield self.finding(
                        ctx, node,
                        f"`{f.id}()` on a traced value concretizes it "
                        "(host sync, or ConcretizationTypeError under jit)",
                        Severity.WARNING)


@register_rule
class SideEffectRule(Rule):
    """JX002: Python side effects under `jit` run once at trace time.

    `print` silently stops printing after the first call; `time.*` and
    `random.*`/`np.random.*` freeze to their trace-time value — the
    classic "my dropout mask never changes" bug.
    """

    id = "JX002"
    description = "Python side effects (print/time/random/np.random) under jit"
    example = """\
import jax
import time

@jax.jit
def step(x):
    t0 = time.perf_counter()   # JX002: frozen at trace time
    return x * 2
"""

    _TIME_FNS = {"time", "perf_counter", "monotonic", "process_time",
                 "clock", "time_ns", "perf_counter_ns"}

    def check(self, ctx):
        for info in ctx.reachable_functions():
            for node in walk_body(info.node):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Name) and f.id == "print":
                    yield self.finding(
                        ctx, node,
                        "`print` under jit executes at trace time only; use "
                        "jax.debug.print",
                        Severity.WARNING)
                    continue
                if not isinstance(f, ast.Attribute):
                    continue
                base = attr_base(f)
                if base in ctx.time_aliases and f.attr in self._TIME_FNS:
                    yield self.finding(
                        ctx, node,
                        f"`{base}.{f.attr}()` under jit freezes to its "
                        "trace-time value")
                elif base in ctx.random_aliases:
                    yield self.finding(
                        ctx, node,
                        f"stdlib `{base}.{f.attr}()` under jit is baked in at "
                        "trace time; thread a jax.random key instead")
                elif (base in ctx.numpy_aliases
                      and isinstance(f.value, ast.Attribute)
                      and f.value.attr == "random"):
                    yield self.finding(
                        ctx, node,
                        f"`{base}.random.{f.attr}()` under jit is baked in at "
                        "trace time; thread a jax.random key instead")


_ARRAYISH_PARAMS = {
    "x", "xs", "y", "ys", "inputs", "input", "batch", "features", "labels",
    "params", "state", "arr", "array", "data", "weights", "grads", "logits",
    "probs", "mask", "targets",
}


@register_rule
class RetraceHazardRule(Rule):
    """JX003: patterns that defeat the jit cache and retrace every step.

    (a) `jax.jit(...)` called inside a for/while loop builds a fresh
    compiled callable per iteration; (b) `jax.jit(lambda ...)` inside a
    function body gets a new identity per call, so the cache never hits;
    (c) `static_argnums`/`static_argnames` pointing at array-valued
    params recompiles on every distinct batch.
    """

    id = "JX003"
    description = "retrace hazards: jit-in-loop, jit(lambda) per call, static_argnums on arrays"
    example = """\
import jax

def train(steps, x):
    for _ in range(steps):
        f = jax.jit(lambda v: v + 1)   # JX003: fresh program per iteration
        x = f(x)
    return x
"""

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and ctx._is_tracer_fn(node.func)):
                continue
            term = terminal_attr(node.func)
            if term not in ("jit", "pjit", "pmap"):
                continue
            in_loop = any(isinstance(a, (ast.For, ast.While, ast.AsyncFor))
                          for a in ctx.ancestors(node))
            if in_loop:
                yield self.finding(
                    ctx, node,
                    f"`{term}` called inside a loop compiles a fresh program "
                    "every iteration; hoist it or cache the jitted callable")
            if (node.args and isinstance(node.args[0], ast.Lambda)
                    and ctx.context_of(node) != "<module>"):
                yield self.finding(
                    ctx, node,
                    f"`{term}(lambda ...)` inside a function creates a new "
                    "callable identity per call, so the jit cache never hits",
                    Severity.WARNING)
            for kw in node.keywords:
                if kw.arg not in ("static_argnums", "static_argnames"):
                    continue
                target = node.args[0] if node.args else None
                params = self._target_params(ctx, node, target)
                for name in self._static_params(kw, params):
                    if name in _ARRAYISH_PARAMS:
                        yield self.finding(
                            ctx, kw.value,
                            f"`{kw.arg}` marks array-like param `{name}` "
                            "static: every distinct batch recompiles (and "
                            "arrays are unhashable under jit)")

    def _target_params(self, ctx, call, target):
        qual = None
        if isinstance(target, ast.Name):
            qual = ctx._resolve(ctx.context_of(call), "name", target.id)
        elif (isinstance(target, ast.Attribute)
              and isinstance(target.value, ast.Name)
              and target.value.id == "self"):
            qual = ctx._resolve(ctx.context_of(call), "self", target.attr)
        info = ctx.functions.get(qual) if qual else None
        return info.params if info else None

    def _static_params(self, kw, params):
        vals = (kw.value.elts
                if isinstance(kw.value, (ast.Tuple, ast.List))
                else [kw.value])
        for v in vals:
            if not isinstance(v, ast.Constant):
                continue
            if isinstance(v.value, int) and params is not None:
                idx = v.value
                names = [p for p in params if p != "self"]
                if 0 <= idx < len(names):
                    yield names[idx]
            elif isinstance(v.value, str):
                yield v.value


@register_rule
class Float64Rule(Rule):
    """JX004: float64 in traced kernel code.

    TPUs have no f64 ALU: XLA software-emulates it at ~1/10th throughput,
    and with `jax_enable_x64` off the dtype silently downgrades — either
    way the literal is wrong. Host-side numpy f64 (metrics, serializers)
    is fine and not flagged; explicitly x64-gated code
    (`... if jax.config.jax_enable_x64 else ...`) is skipped.
    """

    id = "JX004"
    description = "float64 literal / implicit x64 promotion in jit-reachable code"
    example = """\
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    return x.astype(jnp.float64)   # JX004: TPUs emulate f64 ~10x slower
"""

    def _x64_guarded(self, ctx, node) -> bool:
        for anc in ctx.ancestors(node):
            test = getattr(anc, "test", None)
            if test is not None and isinstance(anc, (ast.If, ast.IfExp)):
                try:
                    if "x64" in ast.unparse(test):
                        return True
                except Exception:
                    pass
        return False

    def check(self, ctx):
        for info in ctx.reachable_functions():
            for node in walk_body(info.node):
                if (isinstance(node, ast.Attribute)
                        and node.attr == "float64"
                        and attr_base(node) in
                        ctx.jnp_aliases | ctx.numpy_aliases):
                    if not self._x64_guarded(ctx, node):
                        yield self.finding(
                            ctx, node,
                            f"`{attr_base(node)}.float64` in traced code: "
                            "TPUs emulate f64 (~10x slower) and x64 mode is "
                            "usually off — use float32/bfloat16 or gate on "
                            "jax_enable_x64")
                elif isinstance(node, ast.Call):
                    for kw in node.keywords:
                        if (kw.arg == "dtype"
                                and isinstance(kw.value, ast.Constant)
                                and kw.value.value == "float64"
                                and not self._x64_guarded(ctx, node)):
                            yield self.finding(
                                ctx, kw.value,
                                "dtype='float64' in traced code promotes the "
                                "kernel to emulated f64")


_THREADY_ATTR_SKIP = ("thread", "lock", "executor", "future", "queue",
                      "event", "cond", "semaphore")


@register_rule
class ThreadSafetyRule(Rule):
    """JX005: unlocked cross-thread attribute mutation.

    Heuristic: in a class that spawns threads (`threading.Thread(...)`,
    executor `.submit(...)`, or subclassing `Thread`), an attribute
    assigned both from a thread-entry method (or anything it calls) and
    from other methods, where at least one of those assignments is not
    under a `with self.<lock-ish>:` block. `__init__` assignments are
    exempt (construction happens-before thread start), as are attributes
    that are themselves threading primitives.
    """

    id = "JX005"
    description = "attribute mutated across threads without holding the class lock"
    example = """\
import threading

class Worker:
    def start(self):
        threading.Thread(target=self._work).start()

    def _work(self):
        self.count = self.count + 1   # JX005: raced with reset() below

    def reset(self):
        self.count = 0
"""

    def check(self, ctx):
        classes: Dict[str, List] = {}
        for qual, info in ctx.functions.items():
            if info.class_name and "<locals>" not in qual:
                classes.setdefault(info.class_name, []).append(info)
        for cls_name, methods in sorted(classes.items()):
            yield from self._check_class(ctx, cls_name, methods)

    def _check_class(self, ctx, cls_name, methods):
        # analysis units: methods + functions nested inside them (thread
        # bodies are typically `def work(): ...` locals of the spawner)
        units: Dict[str, object] = {m.qualname: m for m in methods}
        by_name = {m.name: m for m in methods}
        for qual, info in ctx.functions.items():
            if any(qual.startswith(m.qualname + ".<locals>.")
                   for m in methods):
                units[qual] = info

        entries = set()  # unit qualnames that run on a spawned thread
        for qual, u in units.items():
            for node in walk_body(u.node):
                if not isinstance(node, ast.Call):
                    continue
                term = terminal_attr(node.func)
                if not (term == "Thread"
                        or (term in ("submit", "map")
                            and isinstance(node.func, ast.Attribute))):
                    continue
                cands = list(node.args)
                cands += [kw.value for kw in node.keywords
                          if kw.arg == "target"]
                for c in cands:
                    if (isinstance(c, ast.Attribute)
                            and isinstance(c.value, ast.Name)
                            and c.value.id == "self"
                            and c.attr in by_name):
                        entries.add(by_name[c.attr].qualname)
                    elif isinstance(c, ast.Name):
                        t = ctx._resolve(qual, "name", c.id)
                        if t in units:
                            entries.add(t)
        cls_node = self._class_node(ctx, cls_name)
        if cls_node is not None and any(
                terminal_attr(b) == "Thread" for b in cls_node.bases):
            if "run" in by_name:
                entries.add(by_name["run"].qualname)
        if not entries:
            return

        # thread side = closure of entry units over self-/local-name calls
        thread_side = set(entries)
        frontier = list(entries)
        while frontier:
            qual = frontier.pop()
            for kind, callee in ctx.calls.get(qual, ()):
                if kind == "self" and callee in by_name:
                    t = by_name[callee].qualname
                else:
                    t = ctx._resolve(qual, "name", callee) \
                        if kind == "name" else None
                if t in units and t not in thread_side:
                    thread_side.add(t)
                    frontier.append(t)

        stores: Dict[str, List] = {}  # attr -> [(unit_qual, node, guarded)]
        for qual, u in units.items():
            if u.name == "__init__":
                continue
            for node in walk_body(u.node):
                if isinstance(node, ast.Assign):
                    tgts = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    tgts = [node.target]
                else:
                    continue
                tgts = [e for t in tgts for e in
                        (t.elts if isinstance(t, (ast.Tuple, ast.List))
                         else (t,))]
                for tgt in tgts:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        attr = tgt.attr
                        if any(k in attr.lower()
                               for k in _THREADY_ATTR_SKIP):
                            continue
                        guarded = self._locked(ctx, node)
                        stores.setdefault(attr, []).append(
                            (qual, node, guarded))

        def short(qual):
            return qual.replace(cls_name + ".", "", 1).replace(
                ".<locals>.", "/")

        for attr, sites in sorted(stores.items()):
            t_sites = [s for s in sites if s[0] in thread_side]
            o_sites = [s for s in sites if s[0] not in thread_side]
            if not t_sites or not o_sites:
                continue
            unguarded = [s for s in t_sites + o_sites if not s[2]]
            if not unguarded:
                continue
            site = min(unguarded, key=lambda s: s[1].lineno)
            t_names = sorted({short(s[0]) for s in t_sites})
            o_names = sorted({short(s[0]) for s in o_sites})
            yield Finding(
                rule=self.id, path=ctx.rel, line=site[1].lineno,
                severity=Severity.WARNING,
                context=f"{cls_name}.{short(site[0])}",
                message=(f"`self.{attr}` is written from thread-side "
                         f"{t_names} and caller-side {o_names} without "
                         "holding the class lock"))

    def _class_node(self, ctx, name):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) and node.name == name:
                return node
        return None

    def _locked(self, ctx, node) -> bool:
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call):
                        expr = expr.func
                    try:
                        src = ast.unparse(expr).lower()
                    except Exception:
                        src = ""
                    if any(k in src for k in ("lock", "mutex", "cond",
                                              "cv")):
                        return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        return False


@register_rule
class DtypeSniffRule(Rule):
    """JX006: dtype-sniffing on user input outside an explicit preprocessor.

    `x.dtype == uint8` as a semantic switch ("bytes must be an image")
    corrupts any other uint8 payload — the motivating bug fed uint8
    embedding ids through a /255 scaler, flooring every id to 0. The
    policy decision belongs in `nn/conf/preprocessors.py` (the allowed
    location), keyed on declared model structure, not on the dtype alone.
    """

    id = "JX006"
    description = "dtype-sniffing (x.dtype == uint8) outside nn/conf/preprocessors.py"
    example = """\
import numpy as np

def ingest(x):
    if x.dtype == np.uint8:   # JX006: uint8 embedding ids get /255'd too
        x = x / 255.0
    return x
"""

    ALLOWED_SUFFIXES = ("nn/conf/preprocessors.py",)

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if rel.endswith(self.ALLOWED_SUFFIXES) or "/analysis/" in rel:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Compare):
                continue
            sides = [node.left] + list(node.comparators)
            has_dtype = any(isinstance(s, ast.Attribute)
                            and s.attr == "dtype" for s in sides)
            sniffs = any(
                (isinstance(s, ast.Attribute) and s.attr == "uint8")
                or (isinstance(s, ast.Constant) and s.value == "uint8")
                for s in sides)
            if has_dtype and sniffs:
                yield self.finding(
                    ctx, node,
                    "dtype-sniffing `.dtype == uint8` decides semantics from "
                    "the wire format; route through an explicit preprocessor "
                    "(nn/conf/preprocessors.py) keyed on model structure")


@register_rule
class AotOutsideCompilationRule(Rule):
    """JX007: AOT compilation machinery outside `compilation/`.

    `fn.lower(...)` / `lowered.compile()` / `jax.export` /
    `serialize_executable` call sites scattered through the codebase each
    reinvent fingerprinting, version pinning, and fallback-on-corrupt
    behavior — and silently miss the executable store, so their compiles
    never become warm starts. The one sanctioned home is the
    `compilation/` package (plus the profiler's cost-analysis probe, which
    lowers only to read FLOPs).
    """

    id = "JX007"
    description = ("AOT compile machinery (.lower()/.compile()/jax.export) "
                   "outside compilation/")
    example = """\
import jax

def warm(fn, x):
    lowered = jax.jit(fn).lower(x)   # JX007: bypasses the executable store
    return lowered.compile()
"""

    ALLOWED_SUFFIXES = ("observability/profiler.py",)

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if (rel.endswith(self.ALLOWED_SUFFIXES) or "/compilation/" in rel
                or rel.startswith("compilation/") or "/analysis/" in rel):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                mod = getattr(node, "module", "") or ""
                names = [a.name for a in node.names]
                if ("serialize_executable" in mod
                        or "serialize_executable" in names):
                    yield self.finding(
                        ctx, node,
                        "serialize_executable import outside compilation/: "
                        "executable (de)serialization belongs to the AOT "
                        "store (compilation/store.py)")
                continue
            if (isinstance(node, ast.Attribute) and node.attr == "export"
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "jax"):
                yield self.finding(
                    ctx, node,
                    "jax.export outside compilation/: exported artifacts "
                    "bypass the fingerprinted executable store")
                continue
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            # `.lower(...)` WITH arguments: jit lowering takes the example
            # args (str.lower() takes none). `.compile()` with NO
            # arguments: Lowered.compile (re.compile always has some).
            if node.func.attr == "lower" and (node.args or node.keywords):
                yield self.finding(
                    ctx, node,
                    ".lower(...) outside compilation/: AOT-compile through "
                    "the executable store (compilation/program.py) so the "
                    "artifact is fingerprinted and reused")
            elif node.func.attr == "compile" and not (node.args
                                                      or node.keywords):
                yield self.finding(
                    ctx, node,
                    ".compile() outside compilation/: AOT-compile through "
                    "the executable store (compilation/program.py) so the "
                    "artifact is fingerprinted and reused")


@register_rule
class MetricsInHotPathRule(Rule):
    """JX008: metrics family creation in jit- or hot-loop-reachable code.

    `registry.counter/gauge/histogram(...)` resolves or creates a family
    under the registry lock — cheap once, but a per-step call site adds a
    lock acquire + dict lookups to every iteration, and under `jit` it is
    a trace-time side effect that silently stops firing. The convention
    (observability/metrics.py) is to resolve families and `.labels(...)`
    children ONCE at module import and call `.inc()/.observe()` on the
    cached child in the hot path. Flags family-creation calls whose
    receiver looks like a registry (`metrics`, `registry`, `reg`, `_reg`,
    `_registry`) when they sit inside a jit-reachable function or inside
    a for/while loop of any function; module-level registration (the
    sanctioned pattern) is exempt.
    """

    id = "JX008"
    description = ("metrics family creation (registry.counter/gauge/"
                   "histogram) in jit-reachable or looped code")
    example = """\
def serve(batches, registry):
    for b in batches:
        c = registry.counter("dl4j_batches_total", "batches")  # JX008
        c.inc()
"""

    _FACTORY = ("counter", "gauge", "histogram")
    _REGISTRY_NAMES = ("metrics", "registry", "reg", "_reg", "_registry")

    def _in_loop(self, ctx, node) -> bool:
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
        return False

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._FACTORY
                    and terminal_attr(node.func.value)
                    in self._REGISTRY_NAMES):
                continue
            context = ctx.context_of(node)
            if context == "<module>":
                continue  # import-time registration is the convention
            in_jit = context in ctx.jit_reachable
            in_loop = self._in_loop(ctx, node)
            if not (in_jit or in_loop):
                continue
            where = ("jit-reachable code" if in_jit
                     else "a per-iteration loop")
            yield self.finding(
                ctx, node,
                f"`.{node.func.attr}(...)` family creation in {where}: "
                "resolve the family and its `.labels(...)` child once at "
                "module import and call the cached child here "
                "(registry lock + dict lookups per step"
                + (", and a trace-time-only side effect under jit"
                   if in_jit else "") + ")")


@register_rule
class HardcodedComputeDtypeRule(Rule):
    """JX009: hardcoded float32 compute dtype in layer forward code.

    Layer kernels (`nn/layers/`) receive params already cast to the
    model's DtypePolicy compute dtype (`nn/params.prep_layer_params`); a
    literal `jnp.float32` / `astype(jnp.float32)` / `dtype='float32'`
    inside them silently pins that op back to f32, defeating
    `mixed_bfloat16` (the cast re-materializes f32 copies and the MXU
    runs the wide path). The sanctioned idiom for accumulator widening is
    `jnp.promote_types(x.dtype, jnp.float32)` — it WIDENS relative to the
    incoming dtype instead of pinning it, so bf16 inputs still get f32
    accumulation without forcing f32 math elsewhere — and is exempt, as
    is anything under an explicit `# tpulint: disable=JX009` with the
    reason on the line.
    """

    id = "JX009"
    description = ("hardcoded float32 literal / astype in nn/layers/ "
                   "forward code (defeats DtypePolicy compute dtype)")
    example = """\
import jax.numpy as jnp

def forward(params, x):
    h = x.astype(jnp.float32)   # JX009: pins the op to f32 under bf16 policy
    return h @ params["W"]
"""
    example_path = "deeplearning4j_tpu/nn/layers/_example.py"

    def _in_promote_types(self, ctx, node) -> bool:
        for anc in ctx.ancestors(node):
            if (isinstance(anc, ast.Call)
                    and isinstance(anc.func, ast.Attribute)
                    and anc.func.attr == "promote_types"):
                return True
        return False

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if "nn/layers/" not in rel:
            return
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Attribute)
                    and node.attr in ("float32", "float16")
                    and attr_base(node)
                    in ctx.jnp_aliases | ctx.numpy_aliases):
                if self._in_promote_types(ctx, node):
                    continue  # accumulator widening: the sanctioned idiom
                yield self.finding(
                    ctx, node,
                    f"hardcoded `{attr_base(node)}.{node.attr}` in a layer "
                    "kernel pins the op to one dtype and defeats the "
                    "model's DtypePolicy compute dtype — derive the dtype "
                    "from the incoming arrays (x.dtype) or widen with "
                    "jnp.promote_types(x.dtype, jnp.float32)")
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (kw.arg == "dtype"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value in ("float32", "float16")):
                        yield self.finding(
                            ctx, kw.value,
                            f"dtype={kw.value.value!r} string literal in a "
                            "layer kernel defeats the DtypePolicy compute "
                            "dtype — derive it from the incoming arrays")


@register_rule
class PallasOutsideKernelsRule(Rule):
    """JX010: direct Pallas import/use outside `kernels/`.

    Mirror of JX007 for the accelerated-kernel layer: a `pallas_call`
    scattered outside `deeplearning4j_tpu/kernels/` bypasses the kernel
    registry — no `DL4J_TPU_KERNELS` fallback policy, no per-jit-
    signature availability probe, no `dl4j_kernel_dispatch_total`
    accounting, no parity-test enforcement, and the jit-cache/AOT
    fingerprints don't know the program's kernel selection. The one
    sanctioned home for `jax.experimental.pallas` is the `kernels/`
    package; everything else dispatches through `kernels.registry`.
    """

    id = "JX010"
    description = ("direct pallas import / pl.pallas_call outside "
                   "kernels/ (bypasses the kernel registry)")
    example = """\
from jax.experimental import pallas as pl   # JX010: outside kernels/

def fused(x):
    return pl.pallas_call(_kernel, out_shape=x)(x)
"""

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if ("/kernels/" in rel or rel.startswith("kernels/")
                or "/analysis/" in rel):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if "pallas" in a.name.split("."):
                        yield self.finding(
                            ctx, node,
                            f"`import {a.name}` outside kernels/: Pallas "
                            "kernels live behind the registry "
                            "(kernels/registry.py) so they carry a "
                            "fallback policy and parity tests")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                names = [a.name for a in node.names]
                if ("pallas" in mod.split(".")
                        or "pallas" in names):
                    yield self.finding(
                        ctx, node,
                        "pallas import outside kernels/: add the kernel "
                        "under kernels/ with an XLA fallback and resolve "
                        "it through kernels.registry")
            elif (isinstance(node, ast.Attribute)
                  and node.attr == "pallas_call"):
                yield self.finding(
                    ctx, node,
                    "`.pallas_call` outside kernels/: raw kernel "
                    "invocations bypass the registry's availability "
                    "probe, mode knobs, and dispatch metric")


@register_rule
class SyncStagingInFitLoopRule(Rule):
    """JX011: synchronous host->device staging inside a fit/dispatch loop.

    A `stage_to_device(...)` or `jax.device_put(...)` issued from an
    engine fit loop or a ParallelWrapper dispatch path serializes the
    transfer with compute: the device idles while the batch crosses the
    link, which is exactly the stall `datasets/staging.py`'s DeviceStager
    exists to hide (PERF.md §20). Hot-path code consumes already-staged
    batches; the puts belong in staging.py (or a helper the stager calls
    off-thread). Scalar puts (`jax.device_put(np.float32(...))` — the
    engines' device-clock/effective-batch constants) are exempt: they
    move bytes, not batches.
    """

    id = "JX011"
    description = ("synchronous stage_to_device/device_put in a fit/"
                   "dispatch hot path (staging belongs in "
                   "datasets/staging.py)")
    example = """\
from deeplearning4j_tpu.datasets.staging import stage_to_device

class Net:
    def fit(self, iterator):
        for ds in iterator:
            staged = stage_to_device(ds)   # JX011: device idles on the link
            self._step(staged)
"""
    example_path = "deeplearning4j_tpu/nn/_example_engine.py"

    _SCALAR_CTORS = {"float32", "float64", "int32", "int64"}

    def _hot(self, name: str) -> bool:
        return (name in ("fit", "flush") or name.startswith("_fit")
                or "dispatch" in name)

    def _scalar_put(self, call: ast.Call) -> bool:
        if not call.args:
            return False
        arg = call.args[0]
        if isinstance(arg, ast.Constant):
            return True
        if isinstance(arg, ast.Call):
            fn = arg.func
            name = fn.attr if isinstance(fn, ast.Attribute) else getattr(
                fn, "id", None)
            return name in self._SCALAR_CTORS
        return False

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if ("datasets/staging.py" in rel or "/analysis/" in rel
                or rel.startswith("analysis/")):
            return
        if not any(seg in rel for seg in ("nn/", "parallel/", "datasets/")):
            return
        for qual, info in ctx.functions.items():
            if not self._hot(info.name):
                continue
            for node in walk_body(info.node):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                if isinstance(fn, ast.Attribute):
                    name = fn.attr
                elif isinstance(fn, ast.Name):
                    name = fn.id
                else:
                    continue
                if name == "stage_to_device":
                    yield self.finding(
                        ctx, node,
                        f"synchronous stage_to_device in `{info.name}`: "
                        "the fit loop blocks on the transfer; feed it "
                        "staged batches via datasets/staging.py "
                        "(DeviceStager / maybe_stage)")
                elif name == "device_put" and not self._scalar_put(node):
                    yield self.finding(
                        ctx, node,
                        f"jax.device_put in hot path `{info.name}`: batch "
                        "transfers in a fit/dispatch loop serialize the "
                        "link with compute — stage off-thread through "
                        "datasets/staging.py")


@register_rule
class UnboundedBlockingIORule(Rule):
    """JX012: blocking socket/HTTP call without an explicit timeout on a
    serving or coordination request path.

    A socket call with no timeout blocks forever; in `serving/` and
    `parallel/` that default turns one hung peer into a hung fleet — the
    router's failover, the coordinator's reaper, and the drain path all
    assume every network wait is bounded (the fleet design budgets each
    attempt against the request deadline). The timeout must be at the
    CALL SITE: `socket.setdefaulttimeout` is process-global action at a
    distance, and "the caller probably set one" is not auditable.

    Flagged (when no `timeout=` kwarg and no positional in the timeout
    slot): `socket.create_connection`, `urllib.request.urlopen`,
    `http.client.HTTP(S)Connection`, and `requests.<verb>`.
    """

    id = "JX012"
    description = ("blocking socket/HTTP call without an explicit timeout "
                   "in serving/ or parallel/ (one hung peer hangs the "
                   "fleet)")
    example = """\
from urllib.request import urlopen

def scrape_peer(url):
    return urlopen(url).read()   # JX012: blocks forever on a hung peer
"""
    example_path = "deeplearning4j_tpu/serving/_example.py"

    # callable name -> index of the positional timeout slot (a call with
    # more positionals than this has passed a timeout positionally)
    _TIMEOUT_SLOT = {
        "create_connection": 1,   # socket.create_connection(addr, timeout)
        "urlopen": 2,             # urlopen(url, data, timeout)
        "HTTPConnection": 2,      # HTTPConnection(host, port, timeout)
        "HTTPSConnection": 2,
    }
    _REQUESTS_VERBS = {"get", "post", "put", "delete", "head", "patch",
                       "request"}

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if "/analysis/" in rel or rel.startswith("analysis/"):
            return
        if not any(seg in rel for seg in ("serving/", "parallel/")):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else getattr(
                fn, "id", None)
            if name is None:
                continue
            has_timeout_kw = any(kw.arg == "timeout" or kw.arg == "timeout_s"
                                 for kw in node.keywords)
            if has_timeout_kw:
                continue
            slot = self._TIMEOUT_SLOT.get(name)
            if slot is not None:
                if len(node.args) > slot:
                    continue  # timeout passed positionally
                yield self.finding(
                    ctx, node,
                    f"`{name}(...)` without an explicit timeout on a "
                    "request path: this blocks forever on a hung peer — "
                    "pass `timeout=` (budgeted against the request "
                    "deadline, like util/retry.Backoff.max_elapsed_s)")
            elif (name in self._REQUESTS_VERBS
                  and isinstance(fn, ast.Attribute)
                  and attr_base(fn) == "requests"):
                yield self.finding(
                    ctx, node,
                    f"`requests.{name}(...)` without `timeout=`: requests "
                    "has NO default timeout — a silent hang on a dead "
                    "replica; every serving/parallel HTTP call must carry "
                    "an explicit deadline")


@register_rule
class TracePropagationRule(Rule):
    """JX013: outbound HTTP on a serving/coordination path that does not
    forward the trace context.

    A request hop made without the ``X-DL4J-Trace`` header breaks the
    request's cross-process span tree exactly where it matters — at the
    process boundary the federated timeline (`observability/federation`)
    exists to stitch. In `serving/` and `parallel/`, every outbound HTTP
    call must either route through a propagating helper (`serving/
    router.py`'s `post_json` reads the thread-current context via
    `propagate.trace_headers`) or attach the header itself.

    Heuristic: a raw HTTP call (`urlopen` / `Request` /
    `HTTP(S)Connection` / `requests.<verb>`) is flagged unless its
    enclosing function shows trace-propagation evidence — any name or
    attribute containing ``trace`` (e.g. ``trace_headers``,
    ``TRACE_HEADER``) or the literal header string. Allowlisted by
    function name: ``get_text`` and anything containing ``scrape`` —
    metrics scrapes (the router's load poll, the federation aggregator)
    are trace ROOTS, not request hops; there is no context to forward.
    """

    id = "JX013"
    description = ("outbound HTTP in serving/ or parallel/ not forwarding "
                   "the X-DL4J-Trace context (breaks the cross-process "
                   "span tree)")
    example = """\
from urllib.request import urlopen

def forward_request(url, body):
    return urlopen(url, body, 5.0).read()   # JX013: hop drops the trace
"""
    example_path = "deeplearning4j_tpu/serving/_example.py"

    _OUTBOUND = {"urlopen", "Request", "HTTPConnection", "HTTPSConnection"}
    _REQUESTS_VERBS = {"get", "post", "put", "delete", "head", "patch",
                       "request"}

    @staticmethod
    def _has_trace_evidence(fn_node) -> bool:
        for sub in walk_body(fn_node):
            if isinstance(sub, ast.Name) and "trace" in sub.id.lower():
                return True
            if isinstance(sub, ast.Attribute) and "trace" in sub.attr.lower():
                return True
            if isinstance(sub, ast.Constant) and sub.value == "X-DL4J-Trace":
                return True
        return False

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if "/analysis/" in rel or rel.startswith("analysis/"):
            return
        if not any(seg in rel for seg in ("serving/", "parallel/")):
            return
        for qual, info in sorted(ctx.functions.items()):
            fname = info.name
            if fname == "get_text" or "scrape" in fname.lower():
                continue  # metrics scrapes are trace roots, not hops
            evidence = None  # lazily computed per function
            for node in walk_body(info.node):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = (f.attr if isinstance(f, ast.Attribute)
                        else getattr(f, "id", None))
                if name in self._OUTBOUND:
                    flagged = True
                elif (name in self._REQUESTS_VERBS
                      and isinstance(f, ast.Attribute)
                      and attr_base(f) == "requests"):
                    flagged = True
                else:
                    flagged = False
                if not flagged:
                    continue
                if evidence is None:
                    evidence = self._has_trace_evidence(info.node)
                if evidence:
                    break  # this function propagates; skip its other calls
                yield self.finding(
                    ctx, node,
                    f"outbound `{name}(...)` in `{fname}` without trace "
                    "propagation: forward the thread-current context "
                    "(propagate.trace_headers / the X-DL4J-Trace header) "
                    "or route through serving/router.py's post_json — a "
                    "hop without it falls off the request's federated "
                    "span tree")


@register_rule
class DenseKVAllocationRule(Rule):
    """JX014: dense full-length KV buffer allocation outside the paged
    pool.

    `jnp.zeros((..., decode_cache_length, ...))` pins `slots x capacity`
    KV rows per layer whether a sequence is two tokens deep or two
    hundred — the padding/duplication HBM the paged pool
    (`models/kv_pool.py` + `models.zoo.PagedDecodeStepper`) exists to
    reclaim. Any new decode-cache state should be page-granular: sized by
    the pool's `(pages, page_size)` geometry, addressed through the
    per-slot page table.

    Heuristic: an array-allocation call (`zeros` / `ones` / `empty` /
    `full` on `jnp` / `jax.numpy` / `np` / `numpy`) whose arguments
    reference ``decode_cache_length`` anywhere in their expression trees —
    directly, or through one level of local aliasing
    (``L = conf.decode_cache_length`` then ``jnp.zeros((..., L, ...))``).
    The pool module itself and `analysis/` are exempt; the attention
    layer's cache priming uses `jnp.pad` (sized by the incoming block,
    not a fresh full-length allocation) and stays clean by construction.
    """

    id = "JX014"
    description = ("dense full-length KV buffer (jnp.zeros sized by "
                   "decode_cache_length) allocated outside the paged "
                   "pool module")
    example = """\
import jax.numpy as jnp

def init_cache(conf, slots, heads, dim):
    return jnp.zeros(   # JX014: slots x capacity rows pinned regardless of depth
        (slots, conf.decode_cache_length, heads, dim))
"""

    _ALLOCS = {"zeros", "ones", "empty", "full"}
    _MODULES = {"jnp", "jax", "np", "numpy"}

    @staticmethod
    def _mentions(node, aliases) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and (
                    sub.id == "decode_cache_length" or sub.id in aliases):
                return True
            if (isinstance(sub, ast.Attribute)
                    and sub.attr == "decode_cache_length"):
                return True
        return False

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if "/analysis/" in rel or rel.startswith("analysis/"):
            return
        if "kv_pool" in rel:
            return  # the pool module owns page-granular allocation
        # One aliasing hop: names assigned from an expression that
        # mentions decode_cache_length taint the allocation check.
        aliases = set()
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and self._mentions(node.value, ())):
                aliases.add(node.targets[0].id)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute) and f.attr in self._ALLOCS
                    and attr_base(f) in self._MODULES):
                continue
            if any(self._mentions(a, aliases)
                   for a in list(node.args)
                   + [kw.value for kw in node.keywords]):
                yield self.finding(
                    ctx, node,
                    f"dense KV allocation `{attr_base(f)}.{f.attr}(...)` "
                    "sized by decode_cache_length: full-length per-slot "
                    "buffers pin capacity x slots HBM rows regardless of "
                    "sequence depth — back decode state with "
                    "models/kv_pool.py pages (pages x page_size geometry "
                    "through the per-slot page table) instead")


@register_rule
class FrozenLeafTrainingRule(Rule):
    """JX015: grad/updater work over frozen or LoRA-base leaves outside
    the transfer-learning seam.

    The freeze contract (`nn/transfer.py`) is that frozen leaves get NO
    updater state and NO gradient: `frozen_spec` names them,
    `split_tree` carves the trainable subtree, and both engines build
    their Adam moments and `jax.value_and_grad` closures over that
    subtree only. Code that handles frozen/LoRA leaves by hand AND
    allocates updater state or differentiates in the same function is
    re-implementing that seam — it will silently pay updater HBM for
    leaves that never move, and `jax.grad` hard-fails on int8 base
    leaves the spec would have excluded.

    Heuristic: within one function body, a frozen/LoRA *marker* (a
    string literal containing ``__lora_``, or an attribute access
    ``.frozen`` / ``.lora_rank``) co-occurring with a *training op* (a
    `jax.grad` / `jax.value_and_grad` call, or an ``.init(...)`` call
    whose receiver mentions an updater). `nn/transfer.py` and
    `nn/lora.py` ARE the seam and are exempt; the engines stay clean by
    construction because they consume the spec through
    `transfer.frozen_spec` / `split_tree` and never spell the marker
    names.
    """

    id = "JX015"
    description = ("updater-state allocation or grad computation over "
                   "frozen/LoRA leaves outside nn/transfer.py + "
                   "nn/lora.py")
    example = """\
import jax

def finetune_step(params, batch, loss_fn):
    trainable = {k: v for k, v in params.items() if "__lora_" in k}
    return jax.grad(loss_fn)(trainable, batch)   # JX015: hand-rolled seam
"""

    _ALLOW = ("nn/transfer.py", "nn/lora.py")
    _GRAD_FNS = {"grad", "value_and_grad"}
    _MARKER_ATTRS = {"frozen", "lora_rank"}

    @classmethod
    def _is_marker(cls, node) -> bool:
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and "__lora_" in node.value):
            return True
        return (isinstance(node, ast.Attribute)
                and node.attr in cls._MARKER_ATTRS)

    @staticmethod
    def _mentions_updater(node) -> bool:
        for sub in ast.walk(node):
            name = (sub.id if isinstance(sub, ast.Name)
                    else sub.attr if isinstance(sub, ast.Attribute)
                    else None)
            if name is not None and "updater" in name.lower():
                return True
        return False

    def _train_op(self, node):
        """Label of the grad/updater op a Call node performs, else None."""
        if not isinstance(node, ast.Call):
            return None
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in self._GRAD_FNS
                and attr_base(f) == "jax"):
            return f"jax.{f.attr}(...)"
        if isinstance(f, ast.Name) and f.id in self._GRAD_FNS:
            return f"{f.id}(...)"
        if (isinstance(f, ast.Attribute) and f.attr == "init"
                and self._mentions_updater(f.value)):
            return "updater-state .init(...)"
        return None

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if "/analysis/" in rel or rel.startswith("analysis/"):
            return
        if any(rel.endswith(a) for a in self._ALLOW):
            return
        for info in ctx.functions.values():
            ops = []
            marked = False
            for node in walk_body(info.node):
                if self._is_marker(node):
                    marked = True
                op = self._train_op(node)
                if op is not None:
                    ops.append((node, op))
            if not (marked and ops):
                continue
            for node, op in ops:
                yield self.finding(
                    ctx, node,
                    f"`{op}` in a function that handles frozen/LoRA "
                    "leaves by hand: frozen leaves must get no updater "
                    "state and no grad — compute the exclusion with "
                    "nn/transfer.frozen_spec and build the op over "
                    "split_tree's trainable half instead")


@register_rule
class UnboundedLabelCardinalityRule(Rule):
    """JX016: metric label values fed from unbounded per-request data.

    Prometheus-style registries (`observability/metrics.py`) keep one
    child PER DISTINCT LABEL TUPLE forever — a label fed from a request
    id, a prompt, a trace/span id, or an exception message mints a new
    series per request and grows the registry (and every scrape body)
    without bound. Per-request detail belongs in the request ledger
    (`observability/ledger.py`) or the span tracer, which are rings;
    labels are for BOUNDED vocabularies (model names, routes, outcome
    enums — `dl4j_requests_total{outcome}` is the shape to copy).

    Heuristic: a ``.labels(k=v)`` keyword whose value expression
    mentions (a) an obviously per-request name (``request_id``,
    ``prompt``, ``trace_id``, ...) or (b) a variable bound by an
    ``except ... as e`` handler in the same function (``str(e)``,
    f-strings over it — exception text embeds addresses, shapes, paths).
    Derivations that BOUND the value first (``reason.split(":", 1)[0]``
    in flight.py caps the vocabulary at the callers' reason prefixes;
    ``str(adapter)`` draws from the loaded-adapter registry) mention
    neither and stay clean.
    """

    id = "JX016"
    description = ("metric .labels(...) fed from unbounded per-request "
                   "data (per-request series = cardinality explosion)")
    example = """\
def record(counter, request_id):
    counter.labels(request=request_id).inc()   # JX016: one series per request
"""

    _SUSPECT = {"request_id", "req_id", "prompt", "prompt_ids",
                "trace_id", "span_id", "user_id", "session_id"}

    @staticmethod
    def _names_in(node) -> Iterator[str]:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                yield sub.id
            elif isinstance(sub, ast.Attribute):
                yield sub.attr

    @classmethod
    def _stringified_exc(cls, value, exc_names) -> List[str]:
        """Except-bound names whose TEXT reaches the label: the bare
        name as the whole value (labels stringify it), `str(e)` /
        `repr(e)` / `format(e)`, or an f-string over it. Passing `e` to
        a classifier that returns an enum is the fix, not a finding."""
        hits = set()
        if isinstance(value, ast.Name) and value.id in exc_names:
            hits.add(value.id)
        for sub in ast.walk(value):
            args = ()
            if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                    and sub.func.id in ("str", "repr", "format")):
                args = sub.args
            elif (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "format"):
                args = list(sub.args) + [k.value for k in sub.keywords]
            elif isinstance(sub, ast.JoinedStr):
                args = (sub,)
            for a in args:
                for n in ast.walk(a):
                    if isinstance(n, ast.Name) and n.id in exc_names:
                        hits.add(n.id)
        return sorted(hits)

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if "/analysis/" in rel or rel.startswith("analysis/"):
            return  # the linter's own fixtures/tests spell the patterns
        for info in ctx.functions.values():
            exc_names = {
                node.name for node in walk_body(info.node)
                if isinstance(node, ast.ExceptHandler) and node.name}
            for node in walk_body(info.node):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "labels"):
                    continue
                for kw in node.keywords:
                    if kw.arg is None:
                        continue
                    names = set(self._names_in(kw.value))
                    suspect = sorted(names & self._SUSPECT)
                    from_exc = self._stringified_exc(kw.value, exc_names)
                    if suspect:
                        yield self.finding(
                            ctx, node,
                            f"label `{kw.arg}=` is fed from per-request "
                            f"data ({', '.join(suspect)}): every request "
                            "mints a new series and the registry grows "
                            "without bound — record per-request detail "
                            "in the request ledger or a span, keep "
                            "labels to bounded vocabularies")
                    elif from_exc:
                        yield self.finding(
                            ctx, node,
                            f"label `{kw.arg}=` embeds an exception "
                            f"value ({', '.join(from_exc)}): error text "
                            "is unbounded (addresses, shapes, paths) — "
                            "label with the exception CLASS or an "
                            "outcome enum and put the message in the "
                            "ledger/flight bundle")


@register_rule
class UnfusedResidualTailRule(Rule):
    """JX019: residual add + activation left as separate ops next to a conv.

    The residual tail of a conv block — `out = conv_out + shortcut` then
    `act(out)` as standalone statements — is exactly the elementwise
    traffic the fused `bottleneck_block` kernel exists to eliminate
    (PERF.md §27): each standalone op reads and writes the full activation
    tensor through HBM, and at ResNet shapes the tail's bytes rival the
    convs' FLOP time. A layer forward that convolves and then stitches
    the residual/activation by hand should route the whole block through
    the `BottleneckBlock` layer (`nn/layers/bottleneck.py`) — or another
    `kernels.registry` seam — so the Pallas path can keep the
    intermediates in VMEM and the XLA fallback stays the single fusion
    candidate XLA already handles.

    Bias adds (`out + params["b"]`) are exempt: one operand names the
    param leaf, and XLA folds them into the conv epilogue. The rule keys
    on an add of two LOCAL intermediates (both bare names) whose result —
    or the add expression itself — feeds an activation call, in a
    function that also calls a convolution.
    """

    id = "JX019"
    description = ("residual add + activation as separate ops adjacent to "
                   "a conv in nn/layers/ forward code (unfused block tail; "
                   "route through the bottleneck_block kernel seam)")
    example = """\
import jax

def forward(params, x, shortcut):
    y = jax.lax.conv_general_dilated(x, params["W"], (1, 1), "SAME")
    out = y + shortcut   # JX019: residual tail outside the fused block
    return jax.nn.relu(out)
"""
    example_path = "deeplearning4j_tpu/nn/layers/_example.py"

    _ACT_NAMES = ("relu", "relu6", "gelu", "sigmoid", "tanh", "silu",
                  "swish", "elu", "leaky_relu", "softplus", "hard_swish")

    def _is_conv_call(self, node) -> bool:
        if not isinstance(node, ast.Call):
            return False
        name = (terminal_attr(node.func)
                if isinstance(node.func, ast.Attribute)
                else node.func.id if isinstance(node.func, ast.Name)
                else None)
        return bool(name) and ("conv" in name)

    def _residual_add(self, node):
        """The `a + b` BinOp where both operands are bare local names —
        a residual merge, not a bias/param epilogue."""
        if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add)
                and isinstance(node.left, ast.Name)
                and isinstance(node.right, ast.Name)
                and node.left.id != node.right.id):
            return node
        return None

    def _is_activation_call(self, node, act_aliases) -> bool:
        if not isinstance(node, ast.Call):
            return False
        if isinstance(node.func, ast.Attribute):
            return terminal_attr(node.func) in self._ACT_NAMES
        if isinstance(node.func, ast.Name):
            return node.func.id in act_aliases
        # `activations.resolve(conf.activation)(out)`: calling the call
        return (isinstance(node.func, ast.Call)
                and isinstance(node.func.func, ast.Attribute)
                and terminal_attr(node.func.func) == "resolve")

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if "nn/layers/" not in rel:
            return
        for info in ctx.functions.values():
            body = list(walk_body(info.node))
            if not any(self._is_conv_call(n) for n in body):
                continue
            # Names bound to resolved activation fns and to residual adds.
            act_aliases, residual = set(), {}
            for node in body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    tgt = node.targets[0].id
                    if (isinstance(node.value, ast.Call)
                            and isinstance(node.value.func, ast.Attribute)
                            and terminal_attr(node.value.func) == "resolve"):
                        act_aliases.add(tgt)
                    add = self._residual_add(node.value)
                    if add is not None:
                        residual[tgt] = add
            for node in body:
                if not self._is_activation_call(node, act_aliases):
                    continue
                for arg in node.args:
                    hit = None
                    if isinstance(arg, ast.Name) and arg.id in residual:
                        hit = residual[arg.id]
                    elif self._residual_add(arg) is not None:
                        hit = arg
                    if hit is None:
                        continue
                    yield self.finding(
                        ctx, hit,
                        "residual add + activation run as standalone "
                        "elementwise ops next to a conv: each one "
                        "round-trips the full activation tensor through "
                        "HBM — route the block through the fused "
                        "`bottleneck_block` kernel seam "
                        "(nn/layers/bottleneck.py) so the tail stays "
                        "in VMEM on the Pallas path")
                    break


@register_rule
class ShardingOutsideParallelRule(Rule):
    """JX020: NamedSharding/PartitionSpec constructed outside `parallel/`.

    Mirror of JX007/JX010 for the partitioning layer: a `NamedSharding(
    mesh, P(...))` hand-built in model/serving/checkpoint code hardcodes
    one mesh topology at the construction site — it bypasses
    `parallel/mesh.py`'s layout rules (`param_shardings`' head-aware
    attention specs, `kv_page_sharding`'s head-dim pin, `replicated`),
    silently disagrees with what `shard_params` installed on the same
    tree, and leaves no single place to audit which axes a subsystem
    partitions over. Spec construction lives in `parallel/`; everything
    else asks it (`mesh.replicated(...)`, `mesh.axis_sharding(...)`,
    `mesh.kv_page_sharding(...)`, `param_shardings(...)`) — callers then
    inherit rule fixes (and the PERF.md §28 layout model) for free.
    """

    id = "JX020"
    description = ("NamedSharding/PartitionSpec constructed (or imported) "
                   "outside parallel/ — layout decisions bypass the mesh "
                   "rule layer (use parallel.mesh helpers)")
    example = """\
from jax.sharding import NamedSharding, PartitionSpec  # JX020

def place(mesh, tree):
    s = NamedSharding(mesh, PartitionSpec(None, "model"))
    return s
"""
    example_path = "deeplearning4j_tpu/serving/_example.py"

    _NAMES = ("NamedSharding", "PartitionSpec")

    def check(self, ctx):
        rel = ctx.rel.replace("\\", "/")
        if ("/parallel/" in rel or rel.startswith("parallel/")
                or "/analysis/" in rel):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                names = [a.name for a in node.names]
                hit = [n for n in self._NAMES if n in names]
                if hit:
                    yield self.finding(
                        ctx, node,
                        f"importing {', '.join(hit)} outside parallel/: "
                        "sharding specs are built by parallel/mesh.py's "
                        "rule layer — call mesh.replicated / "
                        "mesh.axis_sharding / param_shardings instead")
            elif isinstance(node, ast.Call):
                func = node.func
                name = (func.id if isinstance(func, ast.Name)
                        else terminal_attr(func)
                        if isinstance(func, ast.Attribute) else None)
                if name in self._NAMES:
                    yield self.finding(
                        ctx, node,
                        f"`{name}(...)` constructed outside parallel/: "
                        "this hardcodes a mesh layout at the call site; "
                        "route it through a parallel.mesh helper so the "
                        "layout rules stay auditable in one place")


# The concurrency rules (JX017/JX018) live in their own module with the
# interprocedural lock model; importing it here registers them so every
# entry point that pulls in ALL_RULES sees the full rule set.
from deeplearning4j_tpu.analysis import concurrency  # noqa: E402,F401
