"""Persistent XLA compilation-cache wiring (tentpole layer 1).

Every process used to pay full cold XLA compilation on its first batch.
This module points JAX's persistent compilation cache at a per-user
directory so the *backend compile* of a previously-seen program is a disk
read instead of an XLA invocation — across process restarts, across jobs
sharing the machine. (Layer 2, the AOT executable store in `store.py`,
additionally skips tracing/lowering; this layer alone already removes the
dominant cost.)

Knob: ``DL4J_TPU_COMPILE_CACHE`` — opt-OUT semantics:

- unset           -> per-user default (``$XDG_CACHE_HOME`` or
                     ``~/.cache``)/deeplearning4j_tpu/compile, falling back
                     to ``./.dl4j_compile_cache`` when the home cache is
                     not writable (that fallback name is gitignored);
- ``<dir>``       -> cache there;
- ``0``/``off``/``false``/``none``/empty -> disabled entirely.

Configuration happens once at package import (``deeplearning4j_tpu/
__init__.py``): the engines compile a flock of small helper programs
during ``net.init()`` — before any `_get_jit` — and a warm process should
replay those from disk too, not just the big training programs. The
warmup CLI's ``--cache-dir`` re-points it post-import via
`compilation.reset()`. Concurrent processes are safe: jax writes cache
entries via tmp-file + atomic rename, and the AOT store does the same
(`store.py`), so readers never observe a half-written artifact.
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Optional

ENV_KNOB = "DL4J_TPU_COMPILE_CACHE"
_OFF_VALUES = {"", "0", "false", "off", "none", "disabled"}

# Repo-local fallback when the per-user cache dir is unwritable (e.g. a
# read-only $HOME in a container). Listed in .gitignore.
LOCAL_FALLBACK_DIRNAME = ".dl4j_compile_cache"

_lock = threading.Lock()
_configured = False
_configured_root: Optional[str] = None


def default_cache_dir() -> str:
    """Per-user default: XDG cache dir, or the repo-local fallback when no
    home directory resolves."""
    base = os.environ.get("XDG_CACHE_HOME")
    if not base:
        home = os.path.expanduser("~")
        base = os.path.join(home, ".cache") if home and home != "~" else None
    if base:
        return os.path.join(base, "deeplearning4j_tpu", "compile")
    return os.path.abspath(LOCAL_FALLBACK_DIRNAME)


def cache_root() -> Optional[str]:
    """The configured cache root (None = caching disabled). Reads the env
    knob on every call so tests can re-point it; `configure_persistent_cache`
    latches the first answer for the jax side."""
    raw = os.environ.get(ENV_KNOB)
    if raw is None:
        return default_cache_dir()
    if raw.strip().lower() in _OFF_VALUES:
        return None
    return os.path.abspath(os.path.expanduser(raw.strip()))


def _ensure_dir(path: str) -> bool:
    try:
        os.makedirs(path, exist_ok=True)
        return os.access(path, os.W_OK)
    except OSError:
        return False


def configure_persistent_cache() -> Optional[str]:
    """Point jax's persistent compilation cache at `cache_root()`/xla
    (idempotent; first call wins). Returns the active root, or None when
    caching is disabled or the directory is unusable.

    The size/time floors are dropped to "cache everything": the default
    min-compile-time floor (1s) would skip exactly the many small programs
    an engine run compiles (per-shape train steps, superstep tails), and
    entry dedup across processes is the whole point of the directory.
    """
    global _configured, _configured_root
    with _lock:
        if _configured:
            return _configured_root
        root = cache_root()
        if root is None:
            _configured, _configured_root = True, None
            return None
        if not _ensure_dir(root):
            fallback = os.path.abspath(LOCAL_FALLBACK_DIRNAME)
            if fallback != root and _ensure_dir(fallback):
                root = fallback
            else:
                warnings.warn(
                    f"compile cache dir {root!r} is not writable and neither "
                    f"is the {LOCAL_FALLBACK_DIRNAME!r} fallback; persistent "
                    f"compilation caching is disabled for this process "
                    f"(set {ENV_KNOB} to a writable dir)")
                _configured, _configured_root = True, None
                return None
        try:
            import jax

            xla_dir = os.path.join(root, "xla")
            os.makedirs(xla_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", xla_dir)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
        except Exception as e:  # unknown flag on an exotic jax: degrade
            warnings.warn(
                f"could not configure jax's persistent compilation cache "
                f"({type(e).__name__}: {e}); continuing without it")
            _configured, _configured_root = True, None
            return None
        _configured, _configured_root = True, root
        return root


def reset_for_tests() -> None:
    """Drop the latched configuration (and jax's in-memory cache handle) so
    a test can re-point ``DL4J_TPU_COMPILE_CACHE`` at a fresh tmpdir."""
    global _configured, _configured_root
    with _lock:
        _configured, _configured_root = False, None
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass
