"""Framework-level AOT executable store (tentpole layer 2).

The persistent XLA cache (`cache.py`) only skips the *backend compile*; a
fresh process still pays jaxpr tracing + MLIR lowering for every program.
This store serializes the whole compiled executable
(`jax.experimental.serialize_executable`) keyed by a **fingerprint** of
everything that determines the program:

- the model configuration JSON (layer topology, dtypes, updaters, ...);
- the batch signature (pytree structure + per-leaf shape/dtype/weak-type
  and sharding of every argument);
- jit kind + static args (incl. the superstep ``k``/``scan`` shape);
- the active mesh/sharding from ``context_cache_key()`` (axis roles, mesh
  topology, device ids/kinds/platform);
- jax + jaxlib versions, backend platform + device kind + device count,
  and the x64 flag.

Any field changing changes the hash -> a miss -> live compile + write-back.
A hit deserializes the executable directly: **zero tracing, zero XLA**.
Loads that fail for any reason (corrupt file, incompatible jaxlib, device
mismatch) warn once and fall back to live compilation — the store can only
ever cost a disk read, never correctness.

Writes go through tmp-file + ``os.replace`` so concurrent processes
populating the same directory never expose half-written artifacts.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
import threading
import time
import warnings
from typing import Any, Dict, Optional, Tuple

from deeplearning4j_tpu import observability as _obs

FORMAT_VERSION = 1

_M_HITS = _obs.metrics.counter(
    "dl4j_compile_cache_hits_total",
    "Compile-cache hits by layer (aot = framework executable store, "
    "persistent = jax/XLA persistent compilation cache)",
    label_names=("source",))
_M_MISSES = _obs.metrics.counter(
    "dl4j_compile_cache_misses_total",
    "Compile-cache misses by layer (see dl4j_compile_cache_hits_total)",
    label_names=("source",))
_M_SECONDS = _obs.metrics.histogram(
    "dl4j_compile_seconds",
    "Seconds to make one program runnable, by source (trace = full "
    "lowering + backend compile, persistent = XLA cache retrieval, "
    "aot = executable deserialization)",
    label_names=("source",))
_M_HITS_AOT = _M_HITS.labels(source="aot")
_M_MISSES_AOT = _M_MISSES.labels(source="aot")
_M_SECONDS_AOT = _M_SECONDS.labels(source="aot")


def _leaf_desc(leaf) -> Tuple:
    import jax

    try:
        aval = jax.core.get_aval(leaf)
        shape = tuple(int(d) for d in aval.shape)
        dtype = str(aval.dtype)
        weak = bool(getattr(aval, "weak_type", False))
    except Exception:
        shape, dtype, weak = (), str(type(leaf).__name__), False
    sharding = getattr(leaf, "sharding", None)
    return (shape, dtype, weak, None if sharding is None else str(sharding))


def tree_signature(args) -> Dict[str, Any]:
    """JSON-able description of the argument pytree: structure string plus
    per-leaf (shape, dtype, weak_type, sharding). `None` masks live in the
    structure, so a masked batch fingerprints differently from an unmasked
    one — exactly like the programs they trace."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    return {
        "tree": str(treedef),
        "leaves": [list(_leaf_desc(leaf)) for leaf in leaves],
    }


def _context_desc(key) -> Optional[Dict[str, Any]]:
    """Stable (JSON-able) description of a `ParallelContext.cache_key()`.
    The Mesh hashes by device identity in-process; across processes the
    equivalent identity is the ordered device (id, platform, kind) list
    plus the axis names/shape and roles."""
    if key is None:
        return None
    mesh, *axis_roles = key
    return {
        "axis_roles": list(axis_roles),
        "axis_names": list(mesh.axis_names),
        "mesh_shape": [int(s) for s in mesh.devices.shape],
        "devices": [
            [int(d.id), str(d.platform),
             str(getattr(d, "device_kind", ""))]
            for d in mesh.devices.flat
        ],
    }


def build_fingerprint_doc(net, kind: str, static: Dict[str, Any],
                          args) -> Dict[str, Any]:
    """The full (pre-hash) fingerprint document for one program at one
    batch signature. Kept JSON-able so the store can write it next to the
    artifact for debuggability."""
    import jax
    import jaxlib

    from deeplearning4j_tpu.kernels import registry as _kernels_registry
    from deeplearning4j_tpu.parallel.context import context_cache_key

    dev = jax.devices()
    return {
        "format": FORMAT_VERSION,
        "engine": type(net).__name__,
        "model": net.conf.to_json(),
        "kind": kind,
        "static": sorted((str(k), repr(v)) for k, v in static.items()),
        "signature": tree_signature(args),
        "context": _context_desc(context_cache_key()),
        # Kernel-registry selection (kernels/registry.py): a knob flip
        # resolves different kernel impls inside the traced program, so a
        # cached executable from another config must not be served.
        "kernels": _kernels_registry.config_fingerprint(),
        # Paged-KV pool geometry (models/zoo.PagedDecodeStepper stamps
        # this on the engine): the page size / pool depth shape the decode
        # program's state arrays, so warmup must ship the real paged
        # executable, never a dense-geometry one. None for dense decode.
        "decode_pool": getattr(net, "_decode_pool_geometry", None),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": str(dev[0].platform) if dev else "none",
        "device_kind": str(getattr(dev[0], "device_kind", "")) if dev else "",
        "num_devices": len(dev),
        "x64": bool(jax.config.jax_enable_x64),
    }


def fingerprint(doc: Dict[str, Any]) -> str:
    """sha256 over the canonical JSON of the fingerprint document."""
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


class AOTStore:
    """Directory of serialized executables: ``<root>/aot/<fp>.jaxec``
    (pickled ``{format, fingerprint, jax, jaxlib, payload}``) with a
    ``<fp>.json`` metadata sidecar holding the fingerprint document."""

    def __init__(self, root: str):
        self.root = os.path.join(root, "aot")
        self._lock = threading.Lock()
        self._warned: set = set()
        self._save_warned = False

    def _path(self, fp: str) -> str:
        return os.path.join(self.root, fp + ".jaxec")

    def _warn_once(self, key: str, message: str) -> None:
        with self._lock:
            if key in self._warned:
                return
            self._warned.add(key)
        warnings.warn(message)

    def load(self, fp: str):
        """Deserialize + load the executable for `fp`, or None on miss OR
        any failure (corruption, version/device mismatch — the fallback is
        always a live compile)."""
        path = self._path(fp)
        try:
            with open(path, "rb") as f:
                blob = pickle.load(f)
            if (not isinstance(blob, dict)
                    or blob.get("format") != FORMAT_VERSION
                    or blob.get("fingerprint") != fp):
                raise ValueError("artifact metadata mismatch")
            import jax
            import jaxlib

            if (blob.get("jax") != jax.__version__
                    or blob.get("jaxlib") != jaxlib.__version__):
                # The fingerprint already keys on versions; a mismatch here
                # means the file was renamed or hand-edited. Treat as miss.
                raise ValueError(
                    f"artifact built on jax {blob.get('jax')}/"
                    f"jaxlib {blob.get('jaxlib')}")
            from jax.experimental.serialize_executable import (
                deserialize_and_load)

            payload, in_tree, out_tree = blob["payload"]
            t0 = time.perf_counter()
            loaded = deserialize_and_load(payload, in_tree, out_tree)
            _M_SECONDS_AOT.observe(time.perf_counter() - t0)
            return loaded
        except FileNotFoundError:
            return None
        except Exception as e:
            self._warn_once(fp, (
                f"discarding unusable AOT compile-cache artifact "
                f"{os.path.basename(path)} ({type(e).__name__}: {e}); "
                f"falling back to live compilation — delete the file to "
                f"silence this warning"))
            return None

    def save(self, fp: str, compiled, doc: Dict[str, Any]) -> bool:
        """Serialize `compiled` under `fp` (atomic). Failures are
        non-fatal: the in-process executable keeps working, the artifact
        just isn't shared. Returns True when the artifact was written."""
        try:
            from jax.experimental.serialize_executable import serialize

            payload = serialize(compiled)
            blob = {
                "format": FORMAT_VERSION,
                "fingerprint": fp,
                "jax": doc.get("jax"),
                "jaxlib": doc.get("jaxlib"),
                "payload": payload,
            }
            os.makedirs(self.root, exist_ok=True)
            data = pickle.dumps(blob, protocol=pickle.HIGHEST_PROTOCOL)
            final = self._path(fp)
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(data)
                os.replace(tmp, final)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            meta = json.dumps(doc, sort_keys=True, indent=1)
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            with os.fdopen(fd, "w") as f:
                f.write(meta)
            os.replace(tmp, final[:-len(".jaxec")] + ".json")
            return True
        except Exception as e:
            if not self._save_warned:
                self._save_warned = True
                warnings.warn(
                    f"could not serialize a compiled executable into the "
                    f"AOT store ({type(e).__name__}: {e}); this process "
                    f"keeps its in-memory program, later processes will "
                    f"recompile (further save failures are silent)")
            return False
