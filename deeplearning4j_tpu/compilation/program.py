"""CachedProgram: the store-aware wrapper the engines' jit cache holds.

`nn/jit_cache.py` wraps every program built by `_build_jit` in a
`CachedProgram` (when the compile cache is enabled). The wrapper keys each
call on the ABSTRACT signature of its arguments — shapes/dtypes/structure/
shardings, the same identity jit itself dispatches on — and on the first
call of each signature:

1. fingerprints (model config, signature, kind/static, mesh context,
   versions — `store.build_fingerprint_doc`) and consults the AOT store;
2. on a hit, uses the deserialized executable: no trace, no lowering, no
   XLA — the cold-start cost is one disk read;
3. on a miss, compiles via ``fn.lower(*args).compile()`` (same cost as the
   jit call would have paid), writes the artifact back, and uses the
   compiled executable from then on.

Any failure in the store path degrades to the plain jitted callable with a
warning. `warm(*args)` does step 1-3 *without executing* the program —
donation-safe pre-compilation for the warmup API. `lower(*args)` delegates
to the underlying jit fn (the profiler's cost-analysis probe relies on
it).
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Any, Dict, Optional, Tuple

from deeplearning4j_tpu.compilation import cache as _cache
from deeplearning4j_tpu.compilation import store as _store
from deeplearning4j_tpu.observability import memory as _obsmem

_store_lock = threading.Lock()
_store_singleton: Optional[_store.AOTStore] = None
_store_root: Optional[str] = None


def get_store() -> Optional[_store.AOTStore]:
    """Process-wide `AOTStore` under the configured cache root (configures
    the persistent XLA cache as a side effect of first use). None when
    caching is disabled."""
    global _store_singleton, _store_root
    root = _cache.configure_persistent_cache()
    if root is None:
        return None
    with _store_lock:
        if _store_singleton is None or _store_root != root:
            _store_singleton = _store.AOTStore(root)
            _store_root = root
        return _store_singleton


def reset_for_tests() -> None:
    global _store_singleton, _store_root
    with _store_lock:
        _store_singleton, _store_root = None, None
    _cache.reset_for_tests()


def wrap_program(fn, net, kind: str, static: Dict[str, Any]):
    """Wrap a freshly built jit program for the executable store; returns
    `fn` unchanged when the compile cache is disabled (zero overhead)."""
    if _cache.configure_persistent_cache() is None:
        return fn
    return CachedProgram(fn, net, kind, static)


class CachedProgram:
    """See module docstring. One instance per engine jit-cache entry, so
    the (kind, static, context) identity is fixed; per-call identity is the
    argument signature."""

    def __init__(self, fn, net, kind: str, static: Dict[str, Any]):
        self._fn = fn
        self._net = net
        self.kind = kind
        self.static = dict(static)
        self._entries: Dict[Tuple, Any] = {}
        self._lock = threading.Lock()
        self._fallback_warned = False

    # ------------------------------------------------------------ identity

    def _signature(self, args) -> Tuple:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(args)
        descs = []
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            if shape is None:
                descs.append((type(leaf).__name__,))
                continue
            descs.append((
                tuple(shape), str(getattr(leaf, "dtype", "?")),
                bool(getattr(leaf, "weak_type", False)),
                getattr(leaf, "sharding", None),
            ))
        return (treedef, tuple(descs))

    # ------------------------------------------------------------ dispatch

    def __call__(self, *args):
        return self._entry_for(args)(*args)

    def _entry_for(self, args):
        sig = self._signature(args)
        entry = self._entries.get(sig)
        if entry is not None:
            return entry
        with self._lock:
            entry = self._entries.get(sig)
            if entry is None:
                entry = self._acquire(args)
                self._entries[sig] = entry
            return entry

    def _acquire(self, args):
        store = get_store()
        if store is None:
            return self._fn
        try:
            doc = _store.build_fingerprint_doc(self._net, self.kind,
                                               self.static, args)
            fp = _store.fingerprint(doc)
        except Exception as e:
            self._warn_fallback("fingerprinting failed", e)
            return self._fn
        loaded = store.load(fp)
        if loaded is not None:
            _store._M_HITS_AOT.inc()
            self._record_memory(loaded)
            return loaded
        _store._M_MISSES_AOT.inc()
        try:
            t0 = time.perf_counter()
            compiled = self._fn.lower(*args).compile()
            # dl4j_compile_seconds{source=trace|persistent} for the backend
            # part is observed by the jax.monitoring hook; this histogram
            # entry is intentionally NOT duplicated here.
            dt = time.perf_counter() - t0
        except Exception as e:
            self._warn_fallback("AOT compilation failed", e)
            return self._fn
        store.save(fp, compiled, dict(doc, compile_seconds=dt))
        self._record_memory(compiled)
        return compiled

    def _record_memory(self, compiled) -> None:
        """Static HBM accounting: every executable that materializes here
        (AOT hit or live compile) reports its memory_analysis() into
        `dl4j_program_hbm_bytes{program,kind}`. Never raises."""
        _obsmem.record_program_memory(
            _obsmem.program_label(self.kind, self.static), compiled,
            net=self._net)

    def _warn_fallback(self, what: str, e: Exception) -> None:
        if not self._fallback_warned:
            self._fallback_warned = True
            warnings.warn(
                f"{what} for program {self.kind!r} "
                f"({type(e).__name__}: {e}); using the plain jit path for "
                f"this program")

    # ------------------------------------------------------------- warmup

    def warm(self, *args) -> str:
        """Ensure an executable exists for this argument signature WITHOUT
        running it (safe with donated buffers). Returns where it came
        from: 'ready' (already warm), 'aot' (store hit), 'compiled'
        (live compile + write-back), or 'jit' (store unavailable — the
        program will trace on first call)."""
        sig = self._signature(args)
        with self._lock:
            if sig in self._entries:
                return "ready"
            store = get_store()
            if store is None:
                return "jit"
            try:
                doc = _store.build_fingerprint_doc(self._net, self.kind,
                                                  self.static, args)
                fp = _store.fingerprint(doc)
            except Exception as e:
                self._warn_fallback("fingerprinting failed", e)
                self._entries[sig] = self._fn
                return "jit"
            loaded = store.load(fp)
            if loaded is not None:
                _store._M_HITS_AOT.inc()
                self._entries[sig] = loaded
                return "aot"
            _store._M_MISSES_AOT.inc()
            try:
                t0 = time.perf_counter()
                compiled = self._fn.lower(*args).compile()
                dt = time.perf_counter() - t0
            except Exception as e:
                self._warn_fallback("AOT compilation failed", e)
                self._entries[sig] = self._fn
                return "jit"
            store.save(fp, compiled, dict(doc, compile_seconds=dt))
            self._entries[sig] = compiled
            return "compiled"

    # ----------------------------------------------------------- plumbing

    def lower(self, *args, **kwargs):
        """Delegate to the underlying jit fn (cost-analysis probes)."""
        return self._fn.lower(*args, **kwargs)

    def __repr__(self) -> str:
        return (f"CachedProgram({self.kind!r}, static={self.static}, "
                f"entries={len(self._entries)})")
