"""Warmup API + CLI (tentpole layer 3): pre-compile before traffic.

`warmup_net(net, data)` builds the exact argument tuples the engines'
dispatch paths pass (`_fit_one` / `output` / `score` / `_fit_superstep`)
and warms each program through `CachedProgram.warm` — AOT-store hit, or
live compile + write-back — WITHOUT executing anything: parameters,
optimizer state, RNG stream, and iteration counters are untouched.
`MultiLayerNetwork.warmup` / `ComputationGraph.warmup` /
`ParallelWrapper.warmup` delegate here; `background=True` runs it on a
daemon thread so compilation overlaps data loading.

The CLI pre-populates a cache directory for deploy pipelines::

    python -m deeplearning4j_tpu.compilation.warmup <checkpoint> \
        [--batch-size N] [--shape H,W,C] [--kinds output,train_step] \
        [--cache-dir DIR]

It loads the checkpoint (sharded dir / manager root / legacy ZIP —
`checkpoint.load_any`), synthesizes a batch from the model's declared
input type, and warms the requested programs; a later process pointed at
the same ``DL4J_TPU_COMPILE_CACHE`` starts with zero cold compiles for
those programs.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

DEFAULT_KINDS = ("train_step", "output", "score")


def infer_feature_shape(net) -> Optional[Tuple[int, ...]]:
    """Per-example feature shape from the model's declared input type
    (`set_input_type`), or from the first layer's `n_in` as a fallback.
    None when the model doesn't declare enough (multi-input graphs without
    input types) — callers must then provide an example batch."""
    conf = getattr(net, "conf", None)
    itypes: List[Any] = []
    if conf is not None:
        single = getattr(conf, "input_type", None)
        if single is not None:
            itypes = [single]
        else:
            named = getattr(conf, "input_types", None) or {}
            inputs = getattr(conf, "network_inputs", list(named))
            if named and len(inputs) == 1 and inputs[0] in named:
                itypes = [named[inputs[0]]]
    if itypes:
        t = itypes[0]
        if t.kind == "cnn":
            return (t.height, t.width, t.channels)
        if t.kind in ("ff", "cnnflat"):
            return (t.flat_size(),)
        if t.kind == "rnn":
            return (t.timeseries_length or 8, t.size)
    layers = getattr(net, "layers", None)
    if layers:
        n_in = getattr(layers[0], "n_in", None)
        if n_in:
            return (int(n_in),)
    return None


def _label_shape(net, batch: int) -> Optional[Tuple[int, ...]]:
    """Synthetic one-hot label shape from the net's last layer `n_out`."""
    layers = getattr(net, "layers", None)
    if layers:
        n_out = getattr(layers[-1], "n_out", None)
        if n_out:
            if type(layers[-1]).__name__ == "RnnOutputLayer":
                shape = infer_feature_shape(net)
                t = shape[0] if shape and len(shape) == 2 else 8
                return (batch, t, int(n_out))
            return (batch, int(n_out))
    return None


def synthetic_dataset(net, batch_size: int,
                      shape: Optional[Sequence[int]] = None,
                      dtype=np.float32):
    """A zeros DataSet matching the model's declared input (and, when the
    output layer declares `n_out`, labels) — enough to warm every default
    program kind. `dtype` must match what live traffic will send: an
    int32-ids model warmed with float32 features is a DIFFERENT compiled
    program, and the warmup buys nothing."""
    from deeplearning4j_tpu.datasets.dataset import DataSet

    fshape = tuple(shape) if shape else infer_feature_shape(net)
    if fshape is None:
        raise ValueError(
            "cannot infer the model's input shape (no set_input_type on "
            "the config and no first-layer n_in); pass an example batch "
            "or an explicit shape")
    x = np.zeros((batch_size,) + fshape, dtype)
    lshape = _label_shape(net, batch_size)
    y = None if lshape is None else np.zeros(lshape, np.float32)
    return DataSet(x, y)


def warmup_buckets(net, batch_sizes: Sequence[int],
                   shape: Optional[Sequence[int]] = None,
                   dtype=np.float32,
                   param_variants: Optional[Sequence[Any]] = None
                   ) -> Dict[int, Dict[str, Any]]:
    """Bucket-ladder warmup for the serving tier: warm the inference
    program (`output`, train=False — the exact static signature
    `net.output` dispatches) at EVERY padded batch-size bucket, so no
    admitted request shape ever triggers an XLA compile. Features-only —
    parameters, optimizer state and RNG are untouched.

    `param_variants`: substitute params trees (adapter-merged serving
    trees — `nn/lora.py`) to warm IN ADDITION to the net's own at every
    bucket. A merged tree carries `__lora_*` leaves, a different jit
    signature than the bare base, so per-adapter dispatch only stays
    compile-free after warming a variant-shaped program per bucket.
    Returns `{bucket: warmup summary}`."""
    from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet

    fshape = tuple(shape) if shape else infer_feature_shape(net)
    if fshape is None:
        raise ValueError(
            "cannot infer the model's input shape for bucket warmup; pass "
            "shape=(...)")
    is_graph = type(net).__name__ == "ComputationGraph"
    out: Dict[int, Dict[str, Any]] = {}
    for b in sorted({int(b) for b in batch_sizes}):
        x = np.zeros((b,) + fshape, dtype)
        ds = (MultiDataSet(features=[x], labels=None) if is_graph
              else DataSet(x, None))
        out[b] = warmup_net(net, ds, kinds=("output",),
                            param_variants=param_variants)
    return out


def summarize_bucket_warmup(out: Dict[int, Dict[str, Any]]
                            ) -> Dict[str, Any]:
    """Collapse a `warmup_buckets` result into the rollout ledger the
    serving fleet records per drained-replica warm: how many buckets were
    driven, how many programs actually COMPILED (vs landed from the AOT
    store — the number that must be zero once the compile cache is hot),
    and the wall seconds the drain window spent warming."""
    buckets = sorted(out)
    return {
        "buckets": len(buckets),
        "compiled": sum(int(s.get("compiled", 0)) for s in out.values()),
        "aot": sum(int(s.get("aot", 0)) for s in out.values()),
        "seconds": round(sum(float(s.get("seconds", 0.0))
                             for s in out.values()), 4),
    }


# ----------------------------------------------------------- program args


def _clock_like(net):
    """Same avals as `net._device_clock()` — a float32 scalar step counter
    and a PRNGKey — without touching the net's live clock."""
    import jax
    import jax.numpy as jnp

    return (jnp.asarray(np.float32(0.0)), jax.random.PRNGKey(0))


def _mln_args(net, ds, kind: str):
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(ds.features)
    y = None if ds.labels is None else jnp.asarray(ds.labels)
    fm = None if ds.features_mask is None else jnp.asarray(ds.features_mask)
    lm = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask)
    if kind in ("train_step", "train_step_stats"):
        return (net.params_tree, net.state, net.opt_state, x, y, fm, lm,
                _clock_like(net))
    if kind == "output":
        return (net.params_tree, net.state, x, fm, jax.random.PRNGKey(0))
    if kind == "score":
        return (net.params_tree, net.state, x, y, fm, lm)
    raise ValueError(f"unsupported warmup kind {kind!r}")


def _graph_args(net, mds, kind: str):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.graph import _as_mask_list

    xs = [jnp.asarray(f) for f in mds.features]
    ys = None if mds.labels is None else [jnp.asarray(l) for l in mds.labels]
    fms = _as_mask_list(mds.features_masks)
    lms = _as_mask_list(mds.labels_masks)
    if kind in ("train_step", "train_step_stats"):
        return (net.params_tree, net.state, net.opt_state, xs, ys, fms, lms,
                _clock_like(net))
    if kind == "output":
        return (net.params_tree, net.state, xs, None, jax.random.PRNGKey(0))
    if kind == "score":
        return (net.params_tree, net.state, xs, ys, fms, lms)
    raise ValueError(f"unsupported warmup kind {kind!r}")


def _superstep_args(net, item, is_graph: bool):
    """[K, B, ...] superstep arguments: from a prepared Superbatch /
    MultiSuperbatch (ParallelWrapper path) or by stacking a plain batch K
    times (local path)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.graph import _as_mask_list

    if is_graph:
        return (net.params_tree, net.state, net.opt_state,
                [jnp.asarray(f) for f in item.features],
                [jnp.asarray(l) for l in item.labels],
                _as_mask_list(item.features_masks),
                _as_mask_list(item.labels_masks),
                _clock_like(net))
    return (net.params_tree, net.state, net.opt_state,
            jnp.asarray(item.features), jnp.asarray(item.labels),
            None if item.features_mask is None
            else jnp.asarray(item.features_mask),
            None if item.labels_mask is None
            else jnp.asarray(item.labels_mask),
            _clock_like(net))


def _stack_superbatch(ds, k: int, is_graph: bool):
    from deeplearning4j_tpu.datasets.iterators import (
        MultiSuperbatch, Superbatch)

    def stack(a):
        return None if a is None else np.stack([np.asarray(a)] * k)

    if is_graph:
        return MultiSuperbatch(
            [stack(f) for f in ds.features],
            [stack(l) for l in ds.labels],
            None if ds.features_masks is None
            else [stack(m) for m in ds.features_masks],
            None if ds.labels_masks is None
            else [stack(m) for m in ds.labels_masks],
            k=k)
    return Superbatch(stack(ds.features), stack(ds.labels),
                      stack(ds.features_mask), stack(ds.labels_mask), k=k)


# ---------------------------------------------------------------- warmup


def warmup_net(net, data=None, kinds: Optional[Sequence[str]] = None,
               background: bool = False, batch_size: int = 32,
               context=None, param_variants: Optional[Sequence[Any]] = None):
    """Pre-compile `net`'s programs for the given example batch(es).

    `data`: a DataSet / MultiDataSet / `(features, labels)` tuple, a list
    of them (one per expected batch signature), or None to synthesize a
    batch from the model's declared input type. `kinds` defaults to
    train_step + output + score (+ train_superstep when the superstep knob
    is active); labels-free items warm only `output`.

    `param_variants`: extra params trees to warm the inference program
    with (args[0] substituted) — adapter-merged serving trees have their
    own jit signature, and the synthetic-dataset path would otherwise
    only ever warm the net's bare base tree.

    Returns a summary dict ``{"programs", "aot", "compiled", "ready",
    "jit", "seconds"}`` — or, with `background=True`, the started daemon
    thread (its ``.warmup_result`` attribute carries the summary when
    done; compile errors land in ``.warmup_error`` instead of raising on
    the caller's thread).
    """
    from deeplearning4j_tpu.parallel.context import (
        current_context, parallel_context)

    ctx = context if context is not None else current_context()
    items = _normalize_items(net, data, batch_size)

    if background:
        thread = threading.Thread(
            target=_warmup_worker,
            args=(net, items, kinds, ctx, param_variants),
            name="dl4j-warmup", daemon=True)
        thread.warmup_result = None
        thread.warmup_error = None
        thread.start()
        return thread
    with parallel_context(ctx):
        return _warmup_items(net, items, kinds, param_variants)


def _warmup_worker(net, items, kinds, ctx, param_variants=None):
    from deeplearning4j_tpu.parallel.context import parallel_context

    thread = threading.current_thread()
    try:
        with parallel_context(ctx):
            thread.warmup_result = _warmup_items(net, items, kinds,
                                                 param_variants)
    except Exception as e:  # surfaced via the thread object, not the log
        thread.warmup_error = e


def _normalize_items(net, data, batch_size: int) -> List[Any]:
    from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
    from deeplearning4j_tpu.datasets.iterators import (
        MultiSuperbatch, Superbatch)

    if data is None:
        return [synthetic_dataset(net, batch_size)]
    if isinstance(data, (DataSet, MultiDataSet, Superbatch,
                         MultiSuperbatch)):
        return [data]
    if isinstance(data, tuple) and len(data) == 2:
        return [DataSet(np.asarray(data[0]),
                        None if data[1] is None else np.asarray(data[1]))]
    if isinstance(data, np.ndarray):
        return [DataSet(data, None)]
    return [_normalize_items(net, item, batch_size)[0] for item in data]


def _warmup_items(net, items, kinds, param_variants=None) -> Dict[str, Any]:
    from deeplearning4j_tpu.datasets.iterators import (
        MultiSuperbatch, Superbatch)
    from deeplearning4j_tpu.nn import superstep as _superstep

    if not getattr(net, "_initialized", False):
        net.init()
    is_graph = type(net).__name__ == "ComputationGraph"
    k = net._superstep_k() if hasattr(net, "_superstep_k") else 0
    t0 = time.perf_counter()
    counts = {"programs": 0, "aot": 0, "compiled": 0, "ready": 0, "jit": 0}

    def warm(kind, static, args):
        prog = net._get_jit(kind, **static)
        if hasattr(prog, "warm"):
            status = prog.warm(*args)
        else:
            # Store disabled: lower+compile anyway so the backend compile
            # lands in the persistent XLA cache (the first real call still
            # re-traces, but its backend compile becomes a disk read).
            prog.lower(*args).compile()
            status = "jit"
        counts["programs"] += 1
        counts[status] = counts.get(status, 0) + 1

    from deeplearning4j_tpu.datasets.staging import transfer_cast

    tdt = getattr(getattr(net, "dtype_policy", None), "transfer_dtype", None)
    for item in items:
        if isinstance(item, (Superbatch, MultiSuperbatch)):
            warm("train_superstep",
                 {"k": int(item.k), "scan": _superstep.use_scan()},
                 _superstep_args(net, item, is_graph))
            continue
        # Live batches reach dispatch through the staging tier, which
        # ships them in the policy's transfer dtype — warm the program
        # for THAT signature or the warmup compiles the wrong one.
        item = transfer_cast(item, tdt)
        has_labels = (item.labels is not None)
        item_kinds = list(kinds) if kinds is not None else [
            kd for kd in DEFAULT_KINDS if has_labels or kd == "output"]
        make = _graph_args if is_graph else _mln_args
        for kind in item_kinds:
            # Match the live dispatch's static args exactly — `output` is
            # always requested with train=False (`net.output` passes it),
            # and a static mismatch is a different cached program.
            static = {"train": False} if kind == "output" else {}
            args = make(net, item, kind)
            warm(kind, static, args)
            if kind == "output":
                for variant in (param_variants or ()):
                    warm(kind, static, (variant,) + args[1:])
        if k > 1 and kinds is None and has_labels:
            sb = _stack_superbatch(item, k, is_graph)
            warm("train_superstep", {"k": k, "scan": _superstep.use_scan()},
                 _superstep_args(net, sb, is_graph))
    counts["seconds"] = round(time.perf_counter() - t0, 3)
    return counts


# ------------------------------------------------------------------- CLI


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import json
    import os

    from deeplearning4j_tpu.compilation import cache as _cache

    parser = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.compilation.warmup",
        description=("Pre-populate the compile cache for a checkpointed "
                     "model (see module docstring)."))
    parser.add_argument("checkpoint",
                        help="sharded checkpoint dir / manager root / "
                             "legacy model ZIP")
    parser.add_argument("--batch-size", type=int, default=32,
                        help="synthetic batch size (match serving "
                             "max_batch_size for zero-compile serving)")
    parser.add_argument("--shape", default=None,
                        help="per-example feature shape, comma-separated "
                             "(default: inferred from the model config)")
    parser.add_argument("--kinds", default=None,
                        help="comma list of program kinds (default: "
                             "train_step,output,score)")
    parser.add_argument("--cache-dir", default=None,
                        help=f"cache directory (default: ${_cache.ENV_KNOB} "
                             "or the per-user dir)")
    args = parser.parse_args(argv)

    if args.cache_dir:
        os.environ[_cache.ENV_KNOB] = args.cache_dir
        # The package import already latched a root (possibly the per-user
        # default); drop it so the flag actually takes effect.
        from deeplearning4j_tpu import compilation as _compilation

        _compilation.reset()
    root = _cache.configure_persistent_cache()
    if root is None:
        parser.error(f"the compile cache is disabled (${_cache.ENV_KNOB}"
                     f"={os.environ.get(_cache.ENV_KNOB)!r}); warmup "
                     "would have nowhere to write")

    from deeplearning4j_tpu.checkpoint import load_any

    net = load_any(args.checkpoint)
    shape = (tuple(int(s) for s in args.shape.split(","))
             if args.shape else None)
    ds = synthetic_dataset(net, args.batch_size, shape=shape)
    kinds = args.kinds.split(",") if args.kinds else None
    summary = warmup_net(net, ds, kinds=kinds)
    summary["cache_dir"] = root
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
