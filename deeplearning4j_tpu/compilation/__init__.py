"""Persistent compile cache + AOT warmup: kill cold-start XLA compilation.

Three layers (PERF.md §14):

1. `cache.py`    — wires jax's persistent compilation cache to a per-user
                   directory (``DL4J_TPU_COMPILE_CACHE``, opt-out): the
                   backend compile of a seen program becomes a disk read.
2. `store.py` /
   `program.py`  — the framework-level AOT executable store: whole
                   compiled executables serialized under a fingerprint of
                   (model config, batch signature, jit kind/static, mesh
                   context, versions, backend); a hit skips tracing and
                   lowering entirely. Hooks into both engines through
                   `nn/jit_cache.py`.
3. `warmup.py`   — `net.warmup()` / `ParallelWrapper.warmup()` /
                   `InferenceServer(warmup=True)` / the
                   ``python -m deeplearning4j_tpu.compilation.warmup`` CLI:
                   pre-compile expected programs before traffic.

Observability: `dl4j_compile_cache_hits_total` /
`dl4j_compile_cache_misses_total` and the `dl4j_compile_seconds`
histogram, all labeled ``source=trace|persistent|aot``.
"""

from deeplearning4j_tpu.compilation.cache import (
    ENV_KNOB, cache_root, configure_persistent_cache, default_cache_dir)
from deeplearning4j_tpu.compilation.program import (
    CachedProgram, get_store, wrap_program)
from deeplearning4j_tpu.compilation.store import (
    AOTStore, build_fingerprint_doc, fingerprint, tree_signature)
from deeplearning4j_tpu.compilation.warmup import (
    infer_feature_shape, synthetic_dataset, warmup_net)

__all__ = [
    "ENV_KNOB", "cache_root", "configure_persistent_cache",
    "default_cache_dir", "CachedProgram", "get_store", "wrap_program",
    "AOTStore", "build_fingerprint_doc", "fingerprint", "tree_signature",
    "infer_feature_shape", "synthetic_dataset", "warmup_net", "reset",
]


def reset() -> None:
    """Test hook: drop the latched cache configuration, the store
    singleton, and jax's in-memory persistent-cache handle so the next use
    re-reads ``DL4J_TPU_COMPILE_CACHE``."""
    from deeplearning4j_tpu.compilation import program as _program

    _program.reset_for_tests()
