"""Batched GloVe AdaGrad update kernel.

TPU-native equivalent of the reference's per-pair GloVe learning step
(reference: `models/embeddings/learning/impl/elements/GloVe.java:180-220`
`iterateSample` — prediction = w_i.w_j + b_i + b_j - log X_ij, weighted by
f(X) = (X/xMax)^alpha capped at 1, per-element AdaGrad). The reference
iterates cooccurrence pairs one at a time under Hogwild threads; here a
BATCH of (row, col, count) triples becomes one jitted program —
gather -> weighted-residual -> segment-sum scatter-add -> AdaGrad — with
donated tables, exactly the redesign SURVEY.md §7 hard-part (c) prescribes
for Hogwild embedding updates.

Duplicate indices inside a batch are aggregated before the AdaGrad state
update (the standard sparse-AdaGrad formulation): H += (sum g)^2, then
w -= lr * (sum g) / sqrt(H + eps).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

ADAGRAD_EPS = 1e-6


@partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def glove_step(syn0, bias, hist_w, hist_b, rows, cols, counts, mask,
               lr, x_max, alpha):
    """One batched GloVe update.

    syn0: [V, D] vectors (shared between the two roles — the reference's
    single-table formulation, `GloVe.java:216` updates syn0 for both
    elements); bias: [V]; hist_w/hist_b: AdaGrad accumulators shaped like
    syn0/bias. rows/cols: [B] pair indices; counts: [B] cooccurrence
    weights; mask: [B] marks real (non-padding) pairs.

    Returns (syn0, bias, hist_w, hist_b, batch_loss) where batch_loss is
    the summed weighted squared error 0.5 * f(X) * pred^2 over real pairs
    (reference tracks the same per-sample error via `errorCounter`).
    """
    V, D = syn0.shape
    wi = syn0[rows]                                    # [B, D]
    wj = syn0[cols]
    pred = (jnp.sum(wi * wj, axis=-1) + bias[rows] + bias[cols]
            - jnp.log(jnp.maximum(counts, 1e-12)))     # [B]
    f = jnp.where(counts > x_max, 1.0, (counts / x_max) ** alpha)
    fdiff = f * pred * mask                            # [B] gradient factor

    # d pred/d wi = wj (and vice versa); biases get fdiff directly.
    g_vec = jnp.concatenate([fdiff[:, None] * wj, fdiff[:, None] * wi])  # [2B, D]
    g_b = jnp.concatenate([fdiff, fdiff])              # [2B]
    idx = jnp.concatenate([rows, cols])                # [2B]

    agg = jax.ops.segment_sum(g_vec, idx, num_segments=V)   # [V, D]
    agg_b = jax.ops.segment_sum(g_b, idx, num_segments=V)   # [V]

    hist_w = hist_w + agg * agg
    hist_b = hist_b + agg_b * agg_b
    syn0 = syn0 - lr * agg / jnp.sqrt(hist_w + ADAGRAD_EPS)
    bias = bias - lr * agg_b / jnp.sqrt(hist_b + ADAGRAD_EPS)

    loss = 0.5 * jnp.sum(f * pred * pred * mask)
    return syn0, bias, hist_w, hist_b, loss
