"""Gradient normalization and clipping.

Equivalent of the reference's `GradientNormalization` modes applied in
`nn/updater/LayerUpdater.java:181-221` before the updater. Operates on a
per-layer params pytree: "per layer" reduces over every leaf in the layer's
subtree; "per param type" treats each leaf independently.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.enums import GradientNormalization

_EPS = 1e-8


def _layer_l2(tree) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.sqrt(sum(jnp.sum(l.astype(jnp.float32) ** 2) for l in leaves) + 0.0)


def normalize_layer_gradients(grads, mode, threshold: float = 1.0):
    """Apply one layer's gradient normalization. `grads` is that layer's subtree."""
    mode = GradientNormalization.of(mode) or GradientNormalization.NONE
    if mode == GradientNormalization.NONE:
        return grads
    if mode == GradientNormalization.RENORMALIZE_L2_PER_LAYER:
        norm = _layer_l2(grads)
        return jax.tree_util.tree_map(lambda g: g / (norm + _EPS), grads)
    if mode == GradientNormalization.RENORMALIZE_L2_PER_PARAM_TYPE:
        return jax.tree_util.tree_map(
            lambda g: g / (jnp.linalg.norm(g.reshape(-1)) + _EPS), grads
        )
    if mode == GradientNormalization.CLIP_ELEMENT_WISE_ABSOLUTE_VALUE:
        return jax.tree_util.tree_map(lambda g: jnp.clip(g, -threshold, threshold), grads)
    if mode == GradientNormalization.CLIP_L2_PER_LAYER:
        norm = _layer_l2(grads)
        scale = jnp.where(norm > threshold, threshold / (norm + _EPS), 1.0)
        return jax.tree_util.tree_map(lambda g: g * scale, grads)
    if mode == GradientNormalization.CLIP_L2_PER_PARAM_TYPE:
        def clip_leaf(g):
            norm = jnp.linalg.norm(g.reshape(-1))
            return g * jnp.where(norm > threshold, threshold / (norm + _EPS), 1.0)

        return jax.tree_util.tree_map(clip_leaf, grads)
    raise ValueError(f"Unknown gradient normalization: {mode!r}")
