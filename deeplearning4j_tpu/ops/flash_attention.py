"""Pallas flash-attention forward kernel (TPU).

The reference predates attention entirely; this backs the framework's
long-context extension (`parallel/sequence.py`). Online-softmax
accumulation in fp32 — no [T, T] score matrix ever exists — with a hybrid
of two layouts chosen by K/V footprint: a K/V-resident kernel (K/V
fetched once per batch-head, reused across q-block programs, causal loop
stops at the diagonal) while they fit VMEM, and a streaming kernel
(k-blocks as the innermost grid dim, VMEM scratch accumulators, O(block)
memory at any T) beyond it.

Measured on the driver's v5e chip (bf16, BH=8, D=64, blocks 256):
1.2x XLA dense at T=2k, 1.6x at 8k, 3.1x at 16k, and still running at
T=65k where dense attention no longer fits at all (PERF.md §6). Reached
via `parallel.sequence.attention(..., impl="auto")`, the framework's
default attention entry.

Known headroom: the streaming layout's causal path gates only the COMPUTE
of above-diagonal k-blocks (`pl.when`); their DMAs still run, wasting up
to half the bandwidth at long causal T. Trimming them needs a triangular
grid (linear-index -> (i, j) via scalar prefetch) — future work.

Differentiation: `flash_attention` carries a custom_vjp whose BACKWARD
recomputes attention with the XLA dense path and uses its VJP — gradients
are exact, but training at dense-prohibitive T should use ring attention
(`parallel/sequence.py`), whose per-device blocks stay small by
construction. A Pallas backward kernel is the natural next step.

On non-TPU backends the kernel runs in Pallas interpret mode (numerics
identical, speed irrelevant) so the CPU test mesh exercises the same code.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30


def _flash_kernel_resident(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                           causal: bool, scale: float):
    """Fast path while K/V fit in VMEM: one program per (bh, q-block),
    K/V BlockSpec'd whole — their index map doesn't change across the
    q-block grid steps of one bh, so Pallas fetches them ONCE per
    batch-head and every q-block reuses the resident copy (measured ~1.5x
    the streaming kernel at T<=16k). The fori_loop bound stops at the
    causal diagonal, skipping both compute and reads of future blocks."""
    BQ, D = q_ref.shape[1], q_ref.shape[2]
    T = k_ref.shape[1]
    i = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    q_off = i * BQ

    nk = T // block_k
    if causal:
        nk = jnp.minimum(nk, (q_off + BQ - 1) // block_k + 1)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qpos = q_off + jax.lax.broadcasted_iota(
                jnp.int32, (BQ, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (BQ, block_k), 1)
            s = jnp.where(kpos > qpos, _NEG, s)
        blk_max = jnp.max(s, axis=1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(s - new_m)
        corr = jnp.exp(m - new_m)
        l = l * corr + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, new_m, l

    acc = jnp.zeros((BQ, D), jnp.float32)
    m = jnp.full((BQ, 1), _NEG, jnp.float32)
    l = jnp.zeros((BQ, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, nk, body, (acc, m, l))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  causal: bool, scale: float):
    """One grid step: fold k/v block j into query block i's accumulator.

    The k-block dimension is the INNERMOST grid axis — TPU grids run
    sequentially, so the VMEM scratch (acc/m/l) persists across the j
    steps of one (bh, i) pair, and Pallas double-buffers the next k/v
    block's DMA against this block's compute."""
    BQ, D = q_ref.shape[1], q_ref.shape[2]
    BK = k_ref.shape[1]
    i, j = pl.program_id(1), pl.program_id(2)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_off, k_off = i * BQ, j * BK
    live = True if not causal else k_off <= q_off + BQ - 1

    @pl.when(live)
    def _():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qpos = q_off + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 0)
            kpos = k_off + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 1)
            s = jnp.where(kpos > qpos, _NEG, s)
        m = m_ref[:]
        blk_max = jnp.max(s, axis=1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        p = jnp.exp(s - new_m)
        corr = jnp.exp(m - new_m)
        l_ref[:] = l_ref[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = new_m

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        o_ref[0] = (acc_ref[:] /
                    jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def _on_tpu() -> bool:
    return jax.devices()[0].platform == "tpu"


# Above this K/V footprint the resident kernel would oversubscribe VMEM
# (~16 MB/core, shared with q/out blocks and double buffering).
_RESIDENT_KV_LIMIT = 6 * 1024 * 1024


@functools.partial(jax.jit,
                   static_argnames=("causal", "scale", "block_q", "block_k"))
def _flash_fwd_bhtd(q, k, v, causal, scale, block_q, block_k):
    """q/k/v: [BH, T, D] -> [BH, T, D]."""
    from jax.experimental.pallas import tpu as pltpu

    BH, T, D = q.shape
    kv_bytes = 2 * T * D * q.dtype.itemsize
    if kv_bytes <= _RESIDENT_KV_LIMIT:
        return pl.pallas_call(
            functools.partial(_flash_kernel_resident, block_k=block_k,
                              causal=causal, scale=scale),
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            grid=(BH, T // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
                pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
                pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            interpret=not _on_tpu(),
        )(q, k, v)
    return pl.pallas_call(
        functools.partial(_flash_kernel, causal=causal, scale=scale),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=(BH, T // block_q, T // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=not _on_tpu(),
    )(q, k, v)


def _dense_ref(q, k, v, causal, scale):
    """XLA dense attention on [B, T, H, D] — the single shared dense
    implementation (`parallel/sequence.py`), also the VJP donor."""
    from deeplearning4j_tpu.parallel.sequence import dense_attention

    return dense_attention(q, k, v, causal=causal, scale=scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = 256, block_k: int = 256):
    """Flash multi-head attention. q/k/v: [B, T, H, Dh] -> [B, T, H, Dh].

    Falls back to the XLA dense path when T is not a block multiple (the
    kernel requires T % block == 0)."""
    scale = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    B, T, H, D = q.shape
    if T % block_q or T % block_k:
        return _dense_ref(q, k, v, causal, scale)
    to_bhtd = lambda a: jnp.swapaxes(a, 1, 2).reshape(B * H, T, D)
    o = _flash_fwd_bhtd(to_bhtd(q), to_bhtd(k), to_bhtd(v), causal, scale,
                        block_q, block_k)
    return jnp.swapaxes(o.reshape(B, H, T, D), 1, 2)


def _fwd(q, k, v, causal, scale, block_q, block_k):
    return flash_attention(q, k, v, causal, scale, block_q, block_k), (q, k, v)


def _bwd(causal, scale, block_q, block_k, res, g):
    q, k, v = res
    scale_v = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    _, vjp = jax.vjp(lambda q, k, v: _dense_ref(q, k, v, causal, scale_v),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
