"""Import shim: flash attention lives in `deeplearning4j_tpu.kernels`.

The kernel moved behind the kernel registry
(`deeplearning4j_tpu/kernels/flash_attention.py`, ISSUE 10) — this module
keeps the historical import surface (`from deeplearning4j_tpu.ops.
flash_attention import flash_attention`, `bench.py`, `parallel/
sequence.py`) alive by forwarding EVERY attribute read AND write to the
real module: the class swap below routes `getattr`/`setattr` through the
kernels module, so test monkeypatching of internals (e.g.
`_RESIDENT_KV_LIMIT`) still hits the code that runs.
"""

from __future__ import annotations

import sys
from types import ModuleType

from deeplearning4j_tpu.kernels import flash_attention as _impl


class _ForwardingModule(ModuleType):
    def __getattr__(self, name):
        return getattr(_impl, name)

    def __setattr__(self, name, value):
        setattr(_impl, name, value)

    def __dir__(self):
        return sorted(set(super().__dir__()) | set(dir(_impl)))


sys.modules[__name__].__class__ = _ForwardingModule
