"""Batched skip-gram / CBOW update kernels.

TPU-native equivalent of ND4J's fused `AggregateSkipGram`/`AggregateCBOW`
native ops (reference: `learning/impl/elements/SkipGram.java:17,258-264` —
the op boundary of Word2Vec training, SURVEY.md §3.5). The reference trains
with lock-free Hogwild threads mutating shared syn0/syn1; that doesn't map to
functional TPU updates (SURVEY.md §7 hard part (c)), so here a BATCH of
(center, target) pairs becomes one jitted program: gather -> fused sigmoid
cross-entropy -> segment-sum scatter-add updates, with donated tables.

All batches are padded to fixed sizes (pair_mask marks real pairs) so each
batch shape compiles exactly once.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

MAX_EXP = 6.0  # word2vec exp-table range; gradients are cut off beyond it
MAX_ROW_UPDATE_NORM = 1.0  # L2 cap on a row's AGGREGATED per-batch update


def _clip_rows(update):
    """Cap each row's aggregated update norm. Sequential Hogwild (the
    reference) self-stabilizes because each pair sees the previous pair's
    write; a batched scatter-add applies all collided updates against the
    same stale state, which oscillates/diverges when one row collects many
    contributions (small vocabs, very frequent words). Normal aggregates sit
    far below this cap, so typical training is unaffected."""
    norm = jnp.linalg.norm(update, axis=-1, keepdims=True)
    return update * jnp.minimum(1.0, MAX_ROW_UPDATE_NORM / jnp.maximum(norm, 1e-12))


@partial(jax.jit, donate_argnums=(0, 1))
def hs_skipgram_step(syn0, syn1, centers, codes, points, code_mask, pair_mask, lr):
    """Hierarchical-softmax skip-gram update.

    syn0: [V, D] word vectors; syn1: [I, D] inner-node vectors.
    centers: [B] word whose vector is updated (the context word in w2v
    convention); codes/points/code_mask: [B, L] Huffman paths of the predicted
    word; pair_mask: [B] marks real (non-padding) pairs.
    """
    V, D = syn0.shape
    B, L = codes.shape
    m = code_mask * pair_mask[:, None]  # [B, L]

    h = syn0[centers]  # [B, D]
    nodes = syn1[points]  # [B, L, D]
    logits = jnp.einsum("bd,bld->bl", h, nodes)
    f = jax.nn.sigmoid(logits)
    g = (1.0 - codes.astype(syn0.dtype) - f) * lr * m  # [B, L]
    # word2vec MAX_EXP semantics: saturated nodes contribute no update (the
    # C reference `continue`s outside +-6) — also the stabilizer that bounds
    # batched scatter-add aggregation over repeated indices.
    g = jnp.where(jnp.abs(logits) < MAX_EXP, g, 0.0)

    # dL/dh accumulated from the old syn1 (word2vec update order).
    h_grad = jnp.einsum("bl,bld->bd", g, nodes)  # [B, D]

    # syn1[points] += g * h  (scatter-add over flattened B*L)
    contrib1 = (g[:, :, None] * h[:, None, :]).reshape(B * L, D)
    syn1 = syn1 + _clip_rows(jax.ops.segment_sum(
        contrib1, points.reshape(-1), num_segments=syn1.shape[0]))

    # syn0[centers] += h_grad
    syn0 = syn0 + _clip_rows(jax.ops.segment_sum(h_grad, centers, num_segments=V))
    return syn0, syn1


@partial(jax.jit, donate_argnums=(0, 1))
def ns_skipgram_step(syn0, syn1neg, centers, targets, labels, pair_mask, lr):
    """Negative-sampling skip-gram update.

    targets: [B, 1+K] (positive word first, then K sampled negatives);
    labels: [B, 1+K] 1/0.
    """
    V, D = syn0.shape
    B, K1 = targets.shape
    h = syn0[centers]
    tv = syn1neg[targets]  # [B, K1, D]
    logits = jnp.einsum("bd,bkd->bk", h, tv)
    f = jax.nn.sigmoid(logits)
    lab = labels.astype(syn0.dtype)
    g = (lab - f) * lr * pair_mask[:, None]
    # word2vec MAX_EXP saturation (C reference): g = (label-1)*alpha above
    # +6, label*alpha below -6.
    g = jnp.where(logits > MAX_EXP, (lab - 1.0) * lr * pair_mask[:, None], g)
    g = jnp.where(logits < -MAX_EXP, lab * lr * pair_mask[:, None], g)

    h_grad = jnp.einsum("bk,bkd->bd", g, tv)
    contrib = (g[:, :, None] * h[:, None, :]).reshape(B * K1, D)
    syn1neg = syn1neg + _clip_rows(jax.ops.segment_sum(
        contrib, targets.reshape(-1), num_segments=syn1neg.shape[0]))
    syn0 = syn0 + _clip_rows(jax.ops.segment_sum(h_grad, centers, num_segments=V))
    return syn0, syn1neg


@partial(jax.jit, donate_argnums=(0, 1))
def ns_cbow_step(syn0, syn1neg, context, context_mask, targets, labels,
                 pair_mask, lr):
    """Negative-sampling CBOW update (reference: `AggregateCBOW` native op
    invoked from `learning/impl/elements/CBOW.java:160` with negative > 0 —
    word2vec.c semantics: h = mean of context vectors trained against the
    positive word + K sampled negatives on syn1neg, with the accumulated
    input gradient distributed to every context word).

    context: [B, W] padded context indices; context_mask: [B, W];
    targets: [B, 1+K] (positive first); labels: [B, 1+K] 1/0.
    """
    V, D = syn0.shape
    B, W = context.shape
    cm = context_mask * pair_mask[:, None]
    counts = jnp.maximum(jnp.sum(cm, axis=1, keepdims=True), 1.0)
    ctx = syn0[context] * cm[:, :, None]
    h = jnp.sum(ctx, axis=1) / counts                 # [B, D]

    tv = syn1neg[targets]                             # [B, 1+K, D]
    logits = jnp.einsum("bd,bkd->bk", h, tv)
    f = jax.nn.sigmoid(logits)
    lab = labels.astype(syn0.dtype)
    g = (lab - f) * lr * pair_mask[:, None]
    g = jnp.where(logits > MAX_EXP, (lab - 1.0) * lr * pair_mask[:, None], g)
    g = jnp.where(logits < -MAX_EXP, lab * lr * pair_mask[:, None], g)

    h_grad = jnp.einsum("bk,bkd->bd", g, tv)          # [B, D]
    K1 = targets.shape[1]
    contrib = (g[:, :, None] * h[:, None, :]).reshape(B * K1, D)
    syn1neg = syn1neg + _clip_rows(jax.ops.segment_sum(
        contrib, targets.reshape(-1), num_segments=syn1neg.shape[0]))

    per_word = jnp.broadcast_to(h_grad[:, None, :], (B, W, D)) * cm[:, :, None]
    syn0 = syn0 + _clip_rows(jax.ops.segment_sum(
        per_word.reshape(B * W, D), context.reshape(-1), num_segments=V))
    return syn0, syn1neg


@partial(jax.jit, donate_argnums=(0, 1))
def hs_cbow_step(syn0, syn1, context, context_mask, codes, points, code_mask,
                 pair_mask, lr):
    """Hierarchical-softmax CBOW update: h = mean of context vectors; the
    input-gradient is distributed back to every context word.

    context: [B, W] context word indices (padded); context_mask: [B, W].
    """
    V, D = syn0.shape
    B, W = context.shape
    cm = context_mask * pair_mask[:, None]
    counts = jnp.maximum(jnp.sum(cm, axis=1, keepdims=True), 1.0)  # [B,1]
    ctx = syn0[context] * cm[:, :, None]  # [B, W, D]
    h = jnp.sum(ctx, axis=1) / counts  # [B, D]

    nodes = syn1[points]
    logits = jnp.einsum("bd,bld->bl", h, nodes)
    f = jax.nn.sigmoid(logits)
    m = code_mask * pair_mask[:, None]
    g = (1.0 - codes.astype(syn0.dtype) - f) * lr * m
    g = jnp.where(jnp.abs(logits) < MAX_EXP, g, 0.0)

    h_grad = jnp.einsum("bl,bld->bd", g, nodes)  # [B, D]
    L = codes.shape[1]
    contrib1 = (g[:, :, None] * h[:, None, :]).reshape(B * L, D)
    syn1 = syn1 + _clip_rows(jax.ops.segment_sum(
        contrib1, points.reshape(-1), num_segments=syn1.shape[0]))

    # Each context word gets the full h_grad (word2vec reference behavior).
    per_word = jnp.broadcast_to(h_grad[:, None, :], (B, W, D)) * cm[:, :, None]
    syn0 = syn0 + _clip_rows(jax.ops.segment_sum(
        per_word.reshape(B * W, D), context.reshape(-1), num_segments=V))
    return syn0, syn1


@partial(jax.jit, donate_argnums=(0, 1))
def hs_skipgram_step_tbl(syn0, syn1, centers, words, codes_tbl, points_tbl,
                         cmask_tbl, pair_mask, lr):
    """HS skip-gram with device-resident Huffman tables: gathers the [B, L]
    paths from the [V, L] tables ON DEVICE, so each flush ships only [B]
    int32 indices over the host link. (The host-side `codes_tbl[words]`
    gather + its [B, L] transfer per flush dominated training time over a
    high-latency transport — PERF.md §5.)"""
    return hs_skipgram_step.__wrapped__(
        syn0, syn1, centers, codes_tbl[words], points_tbl[words],
        cmask_tbl[words], pair_mask, lr)


@partial(jax.jit, donate_argnums=(0, 1))
def hs_cbow_step_tbl(syn0, syn1, context, context_mask, words, codes_tbl,
                     points_tbl, cmask_tbl, pair_mask, lr):
    """HS CBOW with device-resident Huffman tables (see hs_skipgram_step_tbl)."""
    return hs_cbow_step.__wrapped__(
        syn0, syn1, context, context_mask, codes_tbl[words],
        points_tbl[words], cmask_tbl[words], pair_mask, lr)


@partial(jax.jit, donate_argnums=(0, 1))
def hs_skipgram_scan_tbl(syn0, syn1, centers, words, codes_tbl, points_tbl,
                         cmask_tbl, pair_mask, lrs):
    """K stacked HS skip-gram batches in ONE dispatch: `lax.scan` of
    `hs_skipgram_step_tbl` over the leading K axis. Each host dispatch
    costs milliseconds over a tunneled transport (PERF.md §4), so the
    word2vec flush loop batches K flushes per dispatch.

    centers/words/pair_mask: [K, B]; lrs: [K]."""
    def body(carry, inp):
        syn0, syn1 = carry
        c, w, pm, lr = inp
        syn0, syn1 = hs_skipgram_step_tbl.__wrapped__(
            syn0, syn1, c, w, codes_tbl, points_tbl, cmask_tbl, pm, lr)
        return (syn0, syn1), None

    (syn0, syn1), _ = jax.lax.scan(
        body, (syn0, syn1), (centers, words, pair_mask, lrs))
    return syn0, syn1


@partial(jax.jit, donate_argnums=(0, 1))
def hs_cbow_scan_tbl(syn0, syn1, context, context_mask, words, codes_tbl,
                     points_tbl, cmask_tbl, pair_mask, lrs):
    """K stacked HS CBOW batches in one dispatch (see hs_skipgram_scan_tbl).
    context/context_mask: [K, B, W]; words/pair_mask: [K, B]; lrs: [K]."""
    def body(carry, inp):
        syn0, syn1 = carry
        ctx, cm, w, pm, lr = inp
        syn0, syn1 = hs_cbow_step_tbl.__wrapped__(
            syn0, syn1, ctx, cm, w, codes_tbl, points_tbl, cmask_tbl, pm, lr)
        return (syn0, syn1), None

    (syn0, syn1), _ = jax.lax.scan(
        body, (syn0, syn1), (context, context_mask, words, pair_mask, lrs))
    return syn0, syn1


@partial(jax.jit, donate_argnums=(0, 1))
def ns_skipgram_scan(syn0, syn1neg, centers, targets, labels, pair_mask, lrs):
    """K stacked NS skip-gram batches in one dispatch (see
    hs_skipgram_scan_tbl). centers/pair_mask: [K, B]; targets:
    [K, B, 1+neg]; labels: [B, 1+neg] SHARED across the K batches (it is a
    constant — positive first, zeros after — so it uploads once, not per
    dispatch); lrs: [K]."""
    def body(carry, inp):
        syn0, syn1neg = carry
        c, t, pm, lr = inp
        syn0, syn1neg = ns_skipgram_step.__wrapped__(
            syn0, syn1neg, c, t, labels, pm, lr)
        return (syn0, syn1neg), None

    (syn0, syn1neg), _ = jax.lax.scan(
        body, (syn0, syn1neg), (centers, targets, pair_mask, lrs))
    return syn0, syn1neg


@partial(jax.jit, donate_argnums=(0, 1))
def ns_cbow_scan(syn0, syn1neg, context, context_mask, targets, labels,
                 pair_mask, lrs):
    """K stacked NS CBOW batches in one dispatch; labels [B, 1+neg] shared
    (see ns_skipgram_scan)."""
    def body(carry, inp):
        syn0, syn1neg = carry
        ctx, cm, t, pm, lr = inp
        syn0, syn1neg = ns_cbow_step.__wrapped__(
            syn0, syn1neg, ctx, cm, t, labels, pm, lr)
        return (syn0, syn1neg), None

    (syn0, syn1neg), _ = jax.lax.scan(
        body, (syn0, syn1neg),
        (context, context_mask, targets, pair_mask, lrs))
    return syn0, syn1neg


class ScanDispatchQueue:
    """The K-flush dispatch protocol shared by Word2Vec and
    ParagraphVectors (PERF.md §5): enqueue flush batches; at `k` of them,
    hand the whole list to `dispatch_many` (one scanned program); any
    leftover short of `k` goes through `dispatch_one` per batch so only
    two program shapes ever compile."""

    def __init__(self, k: int, dispatch_many, dispatch_one):
        self.k = int(k)
        self._many = dispatch_many
        self._one = dispatch_one
        self._q = []

    def add(self, item) -> None:
        self._q.append(item)
        if len(self._q) == self.k:
            self._many(self._q)
            self._q.clear()

    def drain(self) -> None:
        """Dispatch whatever is queued (call once at end of training)."""
        if not self._q:
            return
        if len(self._q) == self.k:
            self._many(self._q)
        else:
            for item in self._q:
                self._one(item)
        self._q.clear()
