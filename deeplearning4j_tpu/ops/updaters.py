"""Gradient updaters as composable functional transformations.

TPU-native equivalent of ND4J's `GradientUpdater` family (Adam/Nesterov/AdaGrad/
AdaDelta/RMSProp/SGD), selected by the reference's `nn/updater/LayerUpdater.java:240-272`.
Instead of mutable per-variable updater objects, each updater is an
(init, update) pair over pytrees — the whole optimizer step fuses into the
jitted train step, so there is no per-parameter op dispatch.

`update(state, grads, lr, step)` returns `(new_state, deltas)`; the caller
applies `params = params - deltas` (matching the reference's
`stepFunction.step(params, grad)` subtract semantics,
`optimize/solvers/StochasticGradientDescent.java:58`).

State layout mirrors the param pytree, so updater-state checkpointing and
averaging (reference `updaterState.bin`, `ParallelWrapper.java:198-225`)
serialize the same way params do.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.kernels import fused_update as _fused
from deeplearning4j_tpu.nn.conf.enums import Updater


class GradientUpdater(NamedTuple):
    name: str
    init: Callable[[Any], Any]  # params pytree -> state pytree
    update: Callable[[Any, Any, Any, Any], tuple]  # (state, grads, lr, step) -> (state, deltas)


def _zeros_like_tree(params):
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def sgd() -> GradientUpdater:
    def init(params):
        return ()

    def update(state, grads, lr, step):
        return state, jax.tree_util.tree_map(lambda g: lr * g, grads)

    return GradientUpdater("sgd", init, update)


def none_updater() -> GradientUpdater:
    def init(params):
        return ()

    def update(state, grads, lr, step):
        return state, jax.tree_util.tree_map(jnp.zeros_like, grads)

    return GradientUpdater("none", init, update)


def nesterovs(momentum: float = 0.9) -> GradientUpdater:
    """Nesterov momentum (reference: ND4J Nesterovs, default momentum 0.9).

    The update body lives behind the fused-update dispatch seam
    (`kernels/fused_update.py`): the XLA fallback there is this updater's
    pre-registry tree_map code verbatim (ND4J semantics: applied update =
    -(mu*vPrev) + (1+mu)*v, negated because the caller subtracts deltas);
    on TPU the registry may fuse all leaves into one elementwise kernel."""

    def init(params):
        return {"v": _zeros_like_tree(params)}

    def update(state, grads, lr, step):
        return _fused.dispatch("nesterovs", state, grads, lr, step,
                               (momentum,))

    return GradientUpdater("nesterovs", init, update)


def adam(beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8) -> GradientUpdater:
    def init(params):
        return {"m": _zeros_like_tree(params), "v": _zeros_like_tree(params)}

    def update(state, grads, lr, step):
        # Fused-update dispatch seam (kernels/fused_update.py); the XLA
        # fallback is the pre-registry per-leaf code verbatim.
        return _fused.dispatch("adam", state, grads, lr, step,
                               (beta1, beta2, eps))

    return GradientUpdater("adam", init, update)


def adamax(beta1: float = 0.9, beta2: float = 0.999, eps: float = 1e-8) -> GradientUpdater:
    def init(params):
        return {"m": _zeros_like_tree(params), "u": _zeros_like_tree(params)}

    def update(state, grads, lr, step):
        t = step + 1
        m = jax.tree_util.tree_map(lambda m0, g: beta1 * m0 + (1 - beta1) * g, state["m"], grads)
        u = jax.tree_util.tree_map(lambda u0, g: jnp.maximum(beta2 * u0, jnp.abs(g)), state["u"], grads)
        bc1 = 1.0 - beta1 ** t.astype(jnp.float32) if hasattr(t, "astype") else 1.0 - beta1 ** t
        deltas = jax.tree_util.tree_map(lambda m1, u1: lr * (m1 / bc1) / (u1 + eps), m, u)
        return {"m": m, "u": u}, deltas

    return GradientUpdater("adamax", init, update)


def adagrad(eps: float = 1e-6) -> GradientUpdater:
    def init(params):
        return {"h": _zeros_like_tree(params)}

    def update(state, grads, lr, step):
        h = jax.tree_util.tree_map(lambda h0, g: h0 + g * g, state["h"], grads)
        deltas = jax.tree_util.tree_map(lambda h1, g: lr * g / (jnp.sqrt(h1) + eps), h, grads)
        return {"h": h}, deltas

    return GradientUpdater("adagrad", init, update)


def adadelta(rho: float = 0.95, eps: float = 1e-6) -> GradientUpdater:
    """AdaDelta — note: learning rate is NOT used (reference AdaDelta ignores lr)."""

    def init(params):
        return {"msg": _zeros_like_tree(params), "msdx": _zeros_like_tree(params)}

    def update(state, grads, lr, step):
        msg = jax.tree_util.tree_map(lambda a, g: rho * a + (1 - rho) * g * g, state["msg"], grads)
        deltas = jax.tree_util.tree_map(
            lambda a, d, g: g * jnp.sqrt(d + eps) / jnp.sqrt(a + eps), msg, state["msdx"], grads
        )
        msdx = jax.tree_util.tree_map(lambda d, dl: rho * d + (1 - rho) * dl * dl, state["msdx"], deltas)
        return {"msg": msg, "msdx": msdx}, deltas

    return GradientUpdater("adadelta", init, update)


def rmsprop(decay: float = 0.95, eps: float = 1e-8) -> GradientUpdater:
    def init(params):
        return {"g2": _zeros_like_tree(params)}

    def update(state, grads, lr, step):
        # Fused-update dispatch seam (kernels/fused_update.py); the XLA
        # fallback is the pre-registry per-leaf code verbatim.
        return _fused.dispatch("rmsprop", state, grads, lr, step,
                               (decay, eps))

    return GradientUpdater("rmsprop", init, update)


def create(updater, *, momentum=0.9, adam_mean_decay=0.9, adam_var_decay=0.999,
           rho=0.95, rms_decay=0.95, epsilon=None) -> GradientUpdater:
    """Build a GradientUpdater from an `Updater` enum + hyperparams.

    Mirrors the reference's `UpdaterCreator`/`LayerUpdater.init()` switch
    (`nn/updater/LayerUpdater.java:240-272`) including its per-updater default
    epsilons.
    """
    u = Updater.of(updater) or Updater.SGD
    if u == Updater.SGD:
        return sgd()
    if u == Updater.NONE:
        return none_updater()
    if u == Updater.NESTEROVS:
        return nesterovs(momentum)
    if u == Updater.ADAM:
        return adam(adam_mean_decay, adam_var_decay, 1e-8 if epsilon is None else epsilon)
    if u == Updater.ADAMAX:
        return adamax(adam_mean_decay, adam_var_decay, 1e-8 if epsilon is None else epsilon)
    if u == Updater.ADAGRAD:
        return adagrad(1e-6 if epsilon is None else epsilon)
    if u == Updater.ADADELTA:
        return adadelta(rho, 1e-6 if epsilon is None else epsilon)
    if u == Updater.RMSPROP:
        return rmsprop(rms_decay, 1e-8 if epsilon is None else epsilon)
    raise ValueError(f"Unknown updater: {updater!r}")
