"""Learning-rate decay schedules.

Equivalent of the reference's LR policies (`nn/updater/LayerUpdater.java:134-158`,
`LearningRatePolicy` enum). A schedule is a pure fn(iteration) -> lr multiplier
applied inside the jitted step, so `iteration` may be a traced scalar.
"""

from __future__ import annotations

from typing import Callable, Mapping, Optional, Union

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.enums import LearningRatePolicy


def make_schedule(
    base_lr: float,
    policy: Union[str, LearningRatePolicy, None] = None,
    decay_rate: float = 0.0,
    power: float = 0.0,
    steps: float = 1.0,
    max_iterations: int = 1,
    schedule_map: Optional[Mapping[int, float]] = None,
) -> Callable:
    """Return fn(iteration) -> learning rate (jit-safe)."""
    p = LearningRatePolicy.of(policy) or LearningRatePolicy.NONE

    if p == LearningRatePolicy.NONE:
        return lambda it: jnp.asarray(base_lr, jnp.float32)
    if p == LearningRatePolicy.EXPONENTIAL:
        return lambda it: base_lr * jnp.power(decay_rate, it.astype(jnp.float32) if hasattr(it, "astype") else float(it))
    if p == LearningRatePolicy.INVERSE:
        return lambda it: base_lr / jnp.power(1.0 + decay_rate * it, power)
    if p == LearningRatePolicy.POLY:
        return lambda it: base_lr * jnp.power(1.0 - jnp.minimum(it / max_iterations, 1.0), power)
    if p == LearningRatePolicy.SIGMOID:
        return lambda it: base_lr / (1.0 + jnp.exp(-decay_rate * (it - steps)))
    if p == LearningRatePolicy.STEP:
        return lambda it: base_lr * jnp.power(decay_rate, jnp.floor(it / steps))
    if p == LearningRatePolicy.TORCH_STEP:
        return lambda it: base_lr * jnp.power(decay_rate, jnp.floor(it / steps))
    if p == LearningRatePolicy.SCHEDULE:
        if not schedule_map:
            return lambda it: jnp.asarray(base_lr, jnp.float32)
        # Piecewise-constant: lr = value of the largest key <= iteration.
        ks = sorted(int(k) for k in schedule_map)
        boundaries = jnp.asarray(ks, jnp.float32)
        values = jnp.asarray([base_lr] + [float(schedule_map[k]) for k in ks], jnp.float32)

        def fn(it):
            idx = jnp.sum(boundaries <= it).astype(jnp.int32)
            return values[idx]

        return fn
    if p == LearningRatePolicy.SCORE:
        # Score-based decay is driven host-side (needs the score); jit side is constant.
        return lambda it: jnp.asarray(base_lr, jnp.float32)
    raise ValueError(f"Unknown LR policy: {policy!r}")
