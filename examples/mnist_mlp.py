"""Minimal end-to-end: builder DSL -> MultiLayerNetwork -> fit -> evaluate
(reference analog: dl4j-examples MLPMnistSingleLayerExample)."""
from deeplearning4j_tpu.datasets.builtin import MnistDataSetIterator
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import ScoreIterationListener

conf = (NeuralNetConfiguration.builder()
        .seed(123).learning_rate(0.006).updater("nesterovs").momentum(0.9)
        .l2(1e-4)
        .list()
        .layer(DenseLayer(n_out=500, activation="relu"))
        .layer(OutputLayer(n_out=10, activation="softmax",
                           loss_function="negativeloglikelihood"))
        .set_input_type(InputType.feed_forward(784))
        .build())

net = MultiLayerNetwork(conf).init()
net.set_listeners(ScoreIterationListener(50))

train = MnistDataSetIterator(batch_size=128, train=True, flat=True)
test = MnistDataSetIterator(batch_size=128, train=False, flat=True)
for epoch in range(2):
    net.fit(train)
ev = net.evaluate(test)
print(ev.stats())
