"""Elastic fault-tolerant training (reference analog: Spark training
master + preemption-aware checkpointing). One process hosts the
coordinator; any number of peers join with the same address. Each
worker survives SIGTERM preemption (commit + flight bundle + clean
leave), and the cluster survives a lost host (survivors re-form,
restore the newest committed checkpoint, fast-forward data, continue).

Single-process this degenerates to supervised local training with
periodic committed checkpoints — run it, Ctrl-C-free kill it with
`kill -TERM <pid>`, run it again: it resumes from the last commit.

Multi-worker on one machine:

    python examples/elastic_training.py host 127.0.0.1:7070 &
    python examples/elastic_training.py peer 127.0.0.1:7070

Deterministic chaos (kill the peer at step 5, watch the host recover):

    DL4J_TPU_FAULT_PLAN='[{"kind": "kill", "step": 5, "worker": 1}]' \
        python examples/elastic_training.py peer 127.0.0.1:7070
"""
import sys

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.elastic import ElasticTrainer
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

role = sys.argv[1] if len(sys.argv) > 1 else "host"  # "host" | "peer"
address = sys.argv[2] if len(sys.argv) > 2 else "127.0.0.1:7070"

conf = (NeuralNetConfiguration.builder()
        .seed(7).learning_rate(0.05).updater("sgd")
        .list()
        .layer(DenseLayer(n_out=64, activation="tanh"))
        .layer(OutputLayer(n_out=3, activation="softmax",
                           loss_function="mcxent"))
        .set_input_type(InputType.feed_forward(10))
        .build())
net = MultiLayerNetwork(conf).init()

trainer = ElasticTrainer(
    ParallelWrapper(net),
    coordinator_address=address,
    worker_id=role,
    expected_world=2,
    host_coordinator=(role == "host"),
    checkpoint_root="/tmp/elastic-example",  # committed sharded ckpts
    save_every=2,                            # commit every 2 steps
    sync="auto",  # spmd on a real pod, coordinator averaging otherwise
)


def shard_fn(step, rank, world):
    """Random-access data: a shrunken cluster re-partitions by the NEW
    rank/world, so recovery never replays or skips another worker's
    share."""
    rng = np.random.RandomState(1000 + step * world + rank)
    X = rng.randn(64, 10).astype("float32")
    Y = np.eye(3)[rng.randint(0, 3, size=64)].astype("float32")
    return DataSet(X, Y)


result = trainer.run(shard_fn, steps=20)
print(f"[{role}] status={result.status} step={result.step} "
      f"restarts={result.restarts}")
