"""Decoder-only transformer LM through the config DSL: causal
SelfAttentionLayer + MoE FFN blocks, trained on cyclic toy sequences,
then sampled autoregressively. Swap in
`ParallelWrapper(cg, mesh, seq_axis=...)` to train sequence-sharded with
zero model changes."""
import numpy as np

from deeplearning4j_tpu.datasets.dataset import MultiDataSet
from deeplearning4j_tpu.models.zoo import generate_lm, transformer_lm
from deeplearning4j_tpu.nn.graph import ComputationGraph

V, T = 8, 16
conf = transformer_lm(vocab_size=V, t=T, d_model=32, n_heads=4,
                      n_blocks=2, moe=True, n_experts=4,
                      decode_cache_length=32)
cg = ComputationGraph(conf).init()

rng = np.random.RandomState(0)
starts = rng.randint(0, V, 32)
idx = (starts[:, None] + np.arange(T)[None]) % V
mds = MultiDataSet(features=[idx.astype("float32")],
                   labels=[np.eye(V, dtype="float32")[(idx + 1) % V]])
for step in range(200):
    cg.fit(mds)
    if step % 50 == 0:
        print(f"step {step}: loss {cg.score_value:.4f}")

print("greedy continuation of [3, 4]:",
      generate_lm(cg, [3, 4], 8, window=T, temperature=0))
print("same, KV-cached (O(1)/token):",
      generate_lm(cg, [3, 4], 8, window=T, temperature=0, use_cache=True))
