"""GravesLSTM character model with tBPTT + stateful sampling (reference
analog: GravesLSTMCharModellingExample)."""
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models.zoo import char_rnn
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

text = ("the quick brown fox jumps over the lazy dog " * 40)
chars = sorted(set(text))
V = len(chars)
c2i = {c: i for i, c in enumerate(chars)}
ids = np.asarray([c2i[c] for c in text])

net = MultiLayerNetwork(char_rnn(vocab_size=V, hidden=64, layers=1,
                                 tbptt_length=25)).init()
B, T = 16, 100
for step in range(30):
    starts = np.random.RandomState(step).randint(0, len(ids) - T - 1, B)
    x = np.eye(V, dtype="float32")[np.stack([ids[s:s + T] for s in starts])]
    y = np.eye(V, dtype="float32")[np.stack([ids[s + 1:s + T + 1]
                                             for s in starts])]
    net.fit(DataSet(x, y))
print("loss:", net.score_value)

# Stateful greedy sampling via rnn_time_step.
net.rnn_clear_previous_state()
cur = c2i["t"]
out = ["t"]
for _ in range(40):
    p = net.rnn_time_step(np.eye(V, dtype="float32")[[cur]])
    cur = int(np.asarray(p)[0].argmax())
    out.append(chars[cur])
print("sample:", "".join(out))
