"""Data-parallel training over every visible device (reference analog:
ParallelWrapper examples). On one device this degenerates gracefully; on
a pod slice the same code shards the batch over the mesh and GSPMD emits
the per-step gradient all-reduce."""
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

conf = (NeuralNetConfiguration.builder()
        .seed(7).learning_rate(0.05).updater("adam")
        .list()
        .layer(DenseLayer(n_out=64, activation="relu"))
        .layer(OutputLayer(n_out=3, activation="softmax",
                           loss_function="mcxent"))
        .set_input_type(InputType.feed_forward(10))
        .build())
net = MultiLayerNetwork(conf).init()
wrapper = ParallelWrapper(net)  # all local devices, data axis

rng = np.random.RandomState(0)
X = rng.randn(512, 10).astype("float32")
Y = np.eye(3)[(X.sum(1) > 0).astype(int) + (X[:, 0] > 1)].astype("float32")
for _ in range(30):
    wrapper.fit(DataSet(X, Y))
print("final score:", net.score_value)
print("accuracy:", (net.predict(X) == Y.argmax(-1)).mean())
