"""Fault-tolerant serving fleet (reference analog: a model-server
cluster behind a load balancer). One process hosts the coordinator and
the front-end router; `FleetManager` spawns N replica processes that
register with heartbeat leases and serve the same checkpoint. The
router sends every request to the least-loaded live replica and fails
over inside the request's deadline budget when one dies.

The demo script below, in order:

1. saves two checkpoints (old and new weights) of a small MLP;
2. spawns a 3-replica fleet on the old checkpoint;
3. runs client traffic through the router;
4. SIGKILLs a replica mid-traffic — requests fail over, nothing is
   lost, and the lease reaper reports the replica dead;
5. performs a rolling update to the new checkpoint: each replica
   drains, AOT-warms the new weights while out of rotation, and
   rejoins — zero client-visible errors, zero serving-path compiles;
6. drains the fleet gracefully.

Run it:

    JAX_PLATFORMS=cpu python examples/serving_fleet.py

Deterministic chaos is also available via the shared fault plan:

    DL4J_TPU_FAULT_PLAN='[{"kind": "kill_replica", "step": 10,
        "worker": 0}]' JAX_PLATFORMS=cpu python examples/serving_fleet.py
"""
import os
import tempfile
import time

from deeplearning4j_tpu.checkpoint.manager import CheckpointManager
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.coordinator import Coordinator
from deeplearning4j_tpu.serving import FleetManager, FleetRouter


def mlp(seed):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(seed).learning_rate(0.1).weight_init("xavier")
         .list()
         .layer(DenseLayer(n_out=16, activation="tanh"))
         .layer(OutputLayer(n_out=3, activation="softmax",
                            loss_function="mcxent"))
         .set_input_type(InputType.feed_forward(4))
         .build())).init()


tmp = tempfile.mkdtemp(prefix="fleet-example-")
old_ckpt = os.path.join(tmp, "ckpt-old")
new_ckpt = os.path.join(tmp, "ckpt-new")
CheckpointManager(old_ckpt, async_save=False).save(mlp(seed=1))
CheckpointManager(new_ckpt, async_save=False).save(mlp(seed=7))

# The coordinator is the same one elastic training uses; replicas are
# just members with a `replica` role and a heartbeat lease.
coord = Coordinator(lost_after_s=2.0).start()
print(f"coordinator at {coord.address}")

env = dict(os.environ, JAX_PLATFORMS="cpu")
env.pop("XLA_FLAGS", None)
manager = FleetManager(coord.address, old_ckpt, heartbeat_s=0.5,
                       env=env, log_dir=os.path.join(tmp, "logs"))
router = FleetRouter(coord.address, poll_interval_s=0.25,
                     request_timeout_s=10.0, attempt_timeout_s=1.0).start()

try:
    for _ in range(3):
        manager.spawn()
    while sum(1 for r in router.table() if r["state"] == "live") < 3:
        time.sleep(0.25)
    print("3 replicas live; router at", router.url)

    x = [[0.1, -0.2, 0.3, 0.4]]
    for _ in range(20):
        router.predict(x)
    print("20 requests ok:", router.counts())

    # Hard failure: SIGKILL one replica, keep sending. The router fails
    # over inside the deadline budget; the lease reaper reports it dead.
    manager.kill("replica-0")
    for _ in range(20):
        router.predict(x)
    while router.load_stats()["dead"] == 0:
        time.sleep(0.25)  # lease reaper evicts the killed replica
    stats = router.load_stats()
    print(f"after SIGKILL: {stats['live']} live, {stats['dead']} dead, "
          f"outcomes {router.counts()}")

    # Rolling update: drain -> AOT-warm new weights -> rejoin, one
    # replica at a time. Clients never see an error or a compile.
    summaries = manager.rolling_update(new_ckpt, router)
    for name, s in summaries.items():
        print(f"rolled {name}: ok={s.get('ok')} "
              f"compiled_during_warm={s.get('compiled_during_warm')} "
              f"({s.get('seconds', 0):.2f}s)")
    for _ in range(20):
        router.predict(x)
    print("post-update traffic ok:", router.counts())
finally:
    router.stop()
    codes = manager.stop_all()   # SIGTERM = graceful drain, exit 0
    coord.close()
    print("drained fleet, exit codes:", codes)
