"""LeNet on MNIST with listeners and model-zip round trip (reference
analog: dl4j-examples LenetMnistExample)."""
import tempfile

from deeplearning4j_tpu.datasets.builtin import MnistDataSetIterator
from deeplearning4j_tpu.models.zoo import lenet_mnist
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import (
    PerformanceListener, ScoreIterationListener,
)
from deeplearning4j_tpu.util.model_serializer import load_model, save_model

net = MultiLayerNetwork(lenet_mnist()).init()
net.set_listeners(ScoreIterationListener(25), PerformanceListener(25))

train = MnistDataSetIterator(batch_size=128, train=True)
test = MnistDataSetIterator(batch_size=128, train=False)
net.fit(train)
print(net.evaluate(test).stats())

path = tempfile.mktemp(suffix=".zip")
save_model(net, path)
restored = load_model(path)
print("restored model params:", restored.num_params())
