"""Word2Vec on a toy corpus: fit, query nearest words, export in the
Google text format (reference analog: dl4j-examples Word2VecRawTextExample)."""
import numpy as np

from deeplearning4j_tpu.nlp.serializer import write_word_vectors
from deeplearning4j_tpu.nlp.word2vec import Word2Vec

rng = np.random.RandomState(0)
topics = [["cat", "dog", "pet", "fur", "paw"],
          ["car", "road", "wheel", "drive", "engine"],
          ["sun", "moon", "star", "sky", "orbit"]]
sentences = [[t[i] for i in rng.randint(0, 5, 12)]
             for t in (topics[rng.randint(3)] for _ in range(600))]

w2v = Word2Vec(layer_size=32, window_size=3, min_word_frequency=5,
               negative=5, seed=1).fit(sentences)
print("nearest to 'cat':", w2v.words_nearest("cat", top=4))
print("similarity cat~dog:", round(w2v.similarity("cat", "dog"), 3),
      " cat~engine:", round(w2v.similarity("cat", "engine"), 3))
write_word_vectors(w2v, "/tmp/vectors.txt")
print("exported to /tmp/vectors.txt")
