"""Transformer encoder text classification with ragged sequences: feature
masks hide the padding from attention (key masking) and from the mean
pooling, and sparse integer class labels feed the loss directly."""
import numpy as np

from deeplearning4j_tpu.datasets.dataset import MultiDataSet
from deeplearning4j_tpu.models.zoo import transformer_classifier
from deeplearning4j_tpu.nn.graph import ComputationGraph

V, T, C = 40, 24, 3
cg = ComputationGraph(transformer_classifier(
    vocab_size=V, n_classes=C, t=T, d_model=32, n_heads=4,
    n_blocks=2, lr=5e-3)).init()

rng = np.random.RandomState(0)
n = 96
cls = rng.randint(0, C, n)
lens = rng.randint(8, T + 1, n)
idx = rng.randint(0, V, (n, T))
mask = np.zeros((n, T), np.float32)
for i in range(n):
    mask[i, :lens[i]] = 1.0
    sel = rng.rand(lens[i]) < 0.5
    idx[i, :lens[i]][sel] = cls[i]  # class-marker tokens
    idx[i, lens[i]:] = 0

mds = MultiDataSet(features=[idx.astype("float32")],
                   labels=[cls.astype(np.int32)],       # sparse ids
                   features_masks=[mask])
for step in range(80):
    cg.fit(mds)
    if step % 20 == 0:
        print(f"step {step}: loss {cg.score_value:.4f}")
out = cg.output_single(idx.astype("float32"), features_masks=[mask])
print("train accuracy:", (out.argmax(-1) == cls).mean())
