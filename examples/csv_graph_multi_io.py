"""Two CSV readers feeding a 2-input/2-output ComputationGraph through
RecordReaderMultiDataSetIterator (reference analog:
dl4j-examples MultipleRegressionOutputExample + RRMDSI docs)."""
import os
import tempfile

import numpy as np

from deeplearning4j_tpu.datasets.records import (
    CSVRecordReader, RecordReaderMultiDataSetIterator,
)
from deeplearning4j_tpu.nn.conf.graph import MergeVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraph

tmp = tempfile.mkdtemp()
rng = np.random.RandomState(0)
pa, pb = os.path.join(tmp, "a.csv"), os.path.join(tmp, "b.csv")
with open(pa, "w") as f:   # 4 features + class id
    for i in range(64):
        row = rng.rand(4).round(3)
        f.write(",".join(map(str, row)) + f",{rng.randint(3)}\n")
with open(pb, "w") as f:   # 3 features + 2 regression targets
    for i in range(64):
        row = rng.rand(5).round(3)
        f.write(",".join(map(str, row)) + "\n")

def make_iter():
    return (RecordReaderMultiDataSetIterator.builder(batch_size=16)
            .add_reader("a", CSVRecordReader().initialize(pa))
            .add_reader("b", CSVRecordReader().initialize(pb))
            .add_input("a", 0, 3)
            .add_input("b", 0, 2)
            .add_output_one_hot("a", 4, num_classes=3)
            .add_output("b", 3, 4)
            .build())

gb = (NeuralNetConfiguration.builder()
      .seed(7).learning_rate(0.05).updater("adam")
      .graph_builder()
      .add_inputs("ina", "inb")
      .add_layer("da", DenseLayer(n_out=16, activation="relu"), "ina")
      .add_layer("db", DenseLayer(n_out=16, activation="relu"), "inb")
      .add_vertex("m", MergeVertex(), "da", "db")
      .add_layer("cls", OutputLayer(n_out=3, activation="softmax",
                                    loss_function="mcxent"), "m")
      .add_layer("reg", OutputLayer(n_out=2, activation="identity",
                                    loss_function="mse"), "m")
      .set_outputs("cls", "reg"))
gb.set_input_types(InputType.feed_forward(4), InputType.feed_forward(3))
cg = ComputationGraph(gb.build()).init()
for _ in range(20):
    cg.fit(make_iter())
print("final score:", cg.score_value)
