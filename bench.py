#!/usr/bin/env python
"""Benchmark entry point (driver-run, real TPU).

Measures `MultiLayerNetwork.fit()` samples/sec on the LeNet-MNIST config — the
reference's first BASELINE.md config — using the reference's
PerformanceListener counting semantics (samples/sec averaged over the timed
interval, `optimize/listeners/PerformanceListener.java:86-102`).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
`vs_baseline` compares against the earliest recorded BENCH_r*.json (the first
measurement establishes the baseline — the reference publishes no numbers,
BASELINE.md).
"""

import glob
import json
import os
import re
import sys
import time

import numpy as np


def _baseline_value(metric: str):
    """Earliest prior BENCH_r{N}.json with the same metric, if any."""
    best = None
    for path in sorted(glob.glob(os.path.join(os.path.dirname(__file__) or ".", "BENCH_r*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
            if isinstance(rec, dict) and rec.get("metric") == metric and rec.get("value"):
                n = int(re.search(r"BENCH_r(\d+)", path).group(1))
                if best is None or n < best[0]:
                    best = (n, float(rec["value"]))
        except Exception:
            continue
    return best[1] if best else None


def main():
    batch = int(os.environ.get("BENCH_BATCH", "512"))
    steps = int(os.environ.get("BENCH_STEPS", "60"))
    warmup = int(os.environ.get("BENCH_WARMUP", "10"))

    import jax
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    net = MultiLayerNetwork(zoo.lenet_mnist()).init()

    rng = np.random.RandomState(0)
    # Pre-stage the batch on device: the framework's async prefetch pipeline
    # overlaps host->device transfer with compute in real training, so the
    # benchmark measures fit() step throughput (PerformanceListener semantics),
    # not the tunnel's transfer latency.
    x = jax.device_put(rng.rand(batch, 28, 28, 1).astype("float32"))
    y = jax.device_put(np.eye(10, dtype="float32")[rng.randint(0, 10, batch)])

    # Warmup (includes compile).
    for _ in range(warmup):
        net._fit_one(_ds(x, y))
    jax.block_until_ready(net.params_tree)

    t0 = time.perf_counter()
    for _ in range(steps):
        net._fit_one(_ds(x, y))
    jax.block_until_ready(net.params_tree)
    dt = time.perf_counter() - t0

    sps = batch * steps / dt
    metric = "lenet_mnist_fit_samples_per_sec"
    base = _baseline_value(metric)
    print(json.dumps({
        "metric": metric,
        "value": round(sps, 1),
        "unit": "samples/sec",
        "vs_baseline": round(sps / base, 3) if base else 1.0,
    }))


def _ds(x, y):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    return DataSet(x, y)


if __name__ == "__main__":
    sys.exit(main())
