#!/usr/bin/env python
"""Benchmark entry point (driver-run, real TPU).

Measures BASELINE.md configs through the PUBLIC training path —
`net.fit(AsyncDataSetIterator(...))`, i.e. host batches flowing through the
prefetch pipeline into the jitted train step — using the reference's
PerformanceListener counting semantics (samples/sec averaged over the timed
interval, `optimize/listeners/PerformanceListener.java:86-102`).

Configs (BASELINE.md):
  1. ResNet-50 ImageNet (ComputationGraph)  — the headline samples/sec/chip
  2. LeNet MNIST (MultiLayerNetwork)        — + legacy step-throughput metric
  3. GravesLSTM char-RNN (tBPTT)
plus an MFU estimate for ResNet-50 (XLA cost-analysis FLOPs / step time /
chip peak).

Prints ONE JSON line: the headline metric, with the remaining metrics nested
under "extra". `vs_baseline` compares each metric against the earliest
recorded BENCH_r*.json that carries it (the first measurement establishes
the number to beat — the reference publishes none, BASELINE.md).

Env knobs: BENCH_CONFIGS (comma list), BENCH_STEPS, BENCH_WARMUP,
BENCH_BATCH_<CONFIG>, BENCH_PEAK_FLOPS, BENCH_SUPERSTEP_K,
BENCH_OBS_STEPS/BENCH_OBS_WARMUP (obs_overhead arms).
"""

import glob
import json
import os
import re
import sys
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))


def _iter_bench_records():
    for path in sorted(glob.glob(os.path.join(_HERE, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                rec = json.load(f)
        except Exception:
            continue
        n = int(re.search(r"BENCH_r(\d+)", path).group(1))
        parsed = rec.get("parsed", rec) if isinstance(rec, dict) else None
        if isinstance(parsed, dict):
            yield n, parsed


# Metrics whose round-1/2 records were sync artifacts: the old timing method
# didn't actually wait for device execution over the tunneled transport, so
# those numbers were up to ~4x optimistic (PERF.md §1.4). Their baseline
# anchors at round 3, the first honest measurement.
_REANCHORED_AT_R3 = {
    "lenet_mnist_fit_samples_per_sec",
    "lenet_mnist_pipeline_samples_per_sec",
}


def _baseline_value(metric: str):
    """Earliest prior BENCH_r{N}.json value for `metric` (headline or extra)."""
    best = None
    for n, parsed in _iter_bench_records():
        if metric in _REANCHORED_AT_R3 and n < 3:
            continue
        value = None
        if parsed.get("metric") == metric and parsed.get("value"):
            value = float(parsed["value"])
        else:
            extra = parsed.get("extra") or {}
            ent = extra.get(metric)
            if isinstance(ent, dict) and ent.get("value"):
                value = float(ent["value"])
        if value is not None and (best is None or n < best[0]):
            best = (n, value)
    return best[1] if best else None


def _entry(metric, value, unit, note=None):
    base = _baseline_value(metric)
    out = {
        "metric": metric,
        "value": round(value, 3 if value < 100 else 1),
        "unit": unit,
        "vs_baseline": round(value / base, 3) if base else 1.0,
    }
    if note:
        out["note"] = note
    return out


# Streaming configs time the host->device link of a SHARED tunneled chip;
# the link's throughput swings ~4x between runs with other tenants' load
# (PERF.md §1.4), so their vs_baseline tracks congestion, not the framework.
# Round 5: every streaming entry also carries an IN-RUN link probe
# (tunnel_rtt_ms + link_mibps measured around the config) and a
# link-normalized companion metric, so a congestion-independent comparison
# exists in the JSON itself, not just in prose.
_LINK_NOTE = ("streams every batch over the shared tunnel; value tracks link "
              "congestion at run time, not framework speed (PERF.md); see "
              "tunnel_rtt_ms/link_mibps measured in-run and the "
              "*_per_link_mibps companion metric")


def _link_probe(n: int = 5, mib: int = 8):
    """In-run tunnel probe: (median scalar round-trip ms, median host->
    device transfer MiB/s for an `mib` MiB buffer). Run around each
    streaming config so its entry records the link conditions it saw."""
    import jax

    rtts, bws = [], []
    buf = np.zeros((mib * 1024 * 1024 // 4,), np.float32)
    for _ in range(n):
        t0 = time.perf_counter()
        _ = float(np.asarray(jax.device_put(np.float32(1.0)) + 0))
        rtts.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        d = jax.device_put(buf)
        _ = float(np.asarray(d[-1] + 0))  # settles the transfer
        bws.append(mib / (time.perf_counter() - t0))
        del d
    return float(np.median(rtts) * 1e3), float(np.median(bws))


# ------------------------------------------------------------------ timing


def _timed_fit(net, make_batch, batch, steps, warmup, distinct=4, cached=False):
    """Time `net.fit` over the public iterator pipeline.

    cached=False: AsyncDataSetIterator — streams every batch host->device
    (the link cost is part of the number). cached=True:
    DeviceCacheDataSetIterator — batches staged to HBM once, fit() replays
    them (device-resident datasets; the train step is the number).

    Sync discipline: `jax.block_until_ready` does not reliably wait for
    execution over the tunneled-TPU transport, so completion is forced by
    fetching the final loss scalar (depends on the last step).
    """
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import (
        AsyncDataSetIterator,
        DeviceCacheDataSetIterator,
    )

    rng = np.random.RandomState(0)
    pool = [make_batch(rng, batch) for _ in range(distinct)]
    # DtypePolicy transfer knob: when the net's policy names a transfer
    # dtype, the staging iterators cast floating features/labels host-side
    # before the put — the link carries bf16, not f32 (PERF.md §17; this
    # replaces the r05-era ad-hoc ml_dtypes cast inside make_batch).
    tdt = getattr(getattr(net, "dtype_policy", None), "transfer_dtype", None)

    def batches(n):
        return [DataSet(*pool[i % distinct]) for i in range(n)]

    if cached:
        it = DeviceCacheDataSetIterator(batches(distinct),
                                        transfer_dtype=tdt)
        epochs = max(1, steps // distinct)
        net.fit(it)  # stages the cache + compiles
        _ = net.score_value
        t0 = time.perf_counter()
        for _ in range(epochs):
            net.fit(it)
        _ = net.score_value
        dt = time.perf_counter() - t0
        n_steps = epochs * distinct
        return batch * n_steps / dt, dt / n_steps

    net.fit(AsyncDataSetIterator(batches(max(warmup, 2)), queue_size=4,
                                 transfer_dtype=tdt))
    _ = net.score_value
    t0 = time.perf_counter()
    net.fit(AsyncDataSetIterator(batches(steps), queue_size=4,
                                 transfer_dtype=tdt))
    _ = net.score_value
    dt = time.perf_counter() - t0
    return batch * steps / dt, dt / steps


def _step_cost(net, x, y):
    """XLA cost analysis of the engine's actual jitted train step:
    {"flops": ..., "bytes": ...} (delegates to the observability profiler —
    same code path StepProfiler uses, so BENCH and live MFU agree by
    construction). "bytes" is the backend's bytes-accessed estimate, the
    HBM traffic one step moves."""
    from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
    from deeplearning4j_tpu.observability import estimate_step_cost

    if type(net).__name__ == "ComputationGraph":
        ds = MultiDataSet(features=[np.asarray(x)], labels=[np.asarray(y)])
    else:
        ds = DataSet(np.asarray(x), np.asarray(y))
    return estimate_step_cost(net, ds)


def _step_flops(net, x, y):
    return _step_cost(net, x, y).get("flops")


def _chip_peak_flops():
    """Peak bf16 FLOPs/sec for the local chip (override: BENCH_PEAK_FLOPS)."""
    from deeplearning4j_tpu.observability import chip_peak_flops

    return chip_peak_flops()


def _chip_peak_hbm_bw():
    """Peak HBM bytes/sec for the local chip (override: BENCH_PEAK_HBM_BW)."""
    from deeplearning4j_tpu.observability import chip_peak_hbm_bw

    return chip_peak_hbm_bw()


def _roofline_entries(prefix, cost, step_time, extra_metrics):
    """Shared bytes-moved + roofline reporting: emit
    `<prefix>_bytes_per_step` and, when the chip's HBM bandwidth is known,
    an `hbm_bound` flag on the MFU-companion entry — True when the
    memory time (bytes / peak BW) exceeds the compute time
    (flops / peak FLOPs), i.e. the step sits on the memory roofline and
    more MFU needs less traffic, not more compute."""
    nbytes = cost.get("bytes")
    if not nbytes:
        return
    e = _entry(f"{prefix}_bytes_per_step", nbytes, "bytes")
    peak_bw, peak_fl = _chip_peak_hbm_bw(), _chip_peak_flops()
    flops = cost.get("flops")
    if peak_bw:
        mem_s = nbytes / peak_bw
        e["hbm_time_frac_of_step"] = round(mem_s / max(step_time, 1e-12), 4)
        if flops and peak_fl:
            e["hbm_bound"] = bool(mem_s > flops / peak_fl)
    extra_metrics[e["metric"]] = e


# ----------------------------------------------------------------- configs


def bench_lenet(steps, warmup):
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch = int(os.environ.get("BENCH_BATCH_LENET", "512"))

    def mk(rng, b):
        return (rng.rand(b, 28, 28, 1).astype("float32"),
                np.eye(10, dtype="float32")[rng.randint(0, 10, b)])

    net = MultiLayerNetwork(zoo.lenet_mnist()).init()
    cached_sps, _ = _timed_fit(net, mk, batch, steps, warmup, cached=True)
    net2 = MultiLayerNetwork(zoo.lenet_mnist()).init()
    rtt_ms, mibps = _link_probe()
    stream_sps, _ = _timed_fit(net2, mk, batch, steps, warmup)
    stream = _entry("lenet_mnist_pipeline_samples_per_sec", stream_sps,
                    "samples/sec", note=_LINK_NOTE)
    stream["tunnel_rtt_ms"] = round(rtt_ms, 2)
    stream["link_mibps"] = round(mibps, 1)
    norm = _entry("lenet_pipeline_samples_per_link_mibps",
                  stream_sps / max(mibps, 1e-9), "samples/sec per MiB/s")
    return (
        _entry("lenet_mnist_cached_samples_per_sec", cached_sps, "samples/sec"),
        stream, norm,
    )


def bench_lenet_pipeline_overlap(steps, warmup):
    """Staging-tier proof (PERF.md §20): the SAME run times a synchronous
    arm (DL4J_TPU_STAGING=0 — each fresh batch is produced and put on the
    consumer thread, inside the step cadence) against the overlapped arm
    (AsyncDataSetIterator -> DeviceStager: production, cast, and the put
    ride the worker thread while the jitted step computes). Batches are
    produced FRESH each step in both arms — a streaming workload, not a
    replayed pool — so the synchronous arm pays host production plus the
    wire inline and the overlapped arm hides both behind compute. The
    input_wait fraction is the engine's own
    dl4j_input_wait_seconds{source="mln"} delta over the overlapped arm's
    wall: with full overlap it collapses toward zero (the workload is
    compute-bound again)."""
    from deeplearning4j_tpu import observability as obs
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch = int(os.environ.get("BENCH_BATCH_LENET", "512"))

    def fresh(n, seed):
        rng = np.random.RandomState(seed)
        for _ in range(n):
            yield DataSet(
                rng.rand(batch, 28, 28, 1).astype("float32"),
                np.eye(10, dtype="float32")[rng.randint(0, 10, batch)])

    wait_child = obs.metrics.histogram(
        "dl4j_input_wait_seconds", label_names=("source",)
    ).labels(source="mln")

    def wait_seconds():
        _, _, s, _ = wait_child.histogram_state()
        return s

    net = MultiLayerNetwork(zoo.lenet_mnist()).init()
    # Synchronous arm first: it also warms the (shared) compiled program,
    # so the overlapped arm carries zero trace+compile. Same shapes/dtypes
    # in both arms -> one program.
    prior = os.environ.get("DL4J_TPU_STAGING")
    os.environ["DL4J_TPU_STAGING"] = "0"
    try:
        net.fit(fresh(max(warmup, 2), seed=99))
        _ = net.score_value
        t0 = time.perf_counter()
        net.fit(fresh(steps, seed=0))
        _ = net.score_value
        sync_dt = time.perf_counter() - t0
    finally:
        if prior is None:
            os.environ.pop("DL4J_TPU_STAGING", None)
        else:
            os.environ["DL4J_TPU_STAGING"] = prior

    rtt_ms, mibps = _link_probe()

    w0 = wait_seconds()
    t0 = time.perf_counter()
    net.fit(AsyncDataSetIterator(fresh(steps, seed=0), queue_size=4))
    _ = net.score_value
    ov_dt = time.perf_counter() - t0
    wait_frac = max(0.0, wait_seconds() - w0) / ov_dt

    ov_sps = batch * steps / ov_dt
    sync_sps = batch * steps / sync_dt
    head = _entry("lenet_pipeline_overlap_samples_per_sec", ov_sps,
                  "samples/sec", note=_LINK_NOTE)
    head["tunnel_rtt_ms"] = round(rtt_ms, 2)
    head["link_mibps"] = round(mibps, 1)
    head["input_wait_fraction"] = round(wait_frac, 4)
    head["overlap_speedup"] = round(ov_sps / max(sync_sps, 1e-9), 3)
    return (
        head,
        _entry("lenet_pipeline_sync_samples_per_sec", sync_sps,
               "samples/sec", note=_LINK_NOTE),
    )


def bench_lenet_step(steps, warmup):
    """Legacy r01 metric: pre-staged device batch, step throughput only."""
    import jax

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch = int(os.environ.get("BENCH_BATCH_LENET", "512"))
    net = MultiLayerNetwork(zoo.lenet_mnist()).init()
    rng = np.random.RandomState(0)
    x = jax.device_put(rng.rand(batch, 28, 28, 1).astype("float32"))
    y = jax.device_put(np.eye(10, dtype="float32")[rng.randint(0, 10, batch)])
    for _ in range(warmup):
        net._fit_one(DataSet(x, y))
    _ = net.score_value
    t0 = time.perf_counter()
    for _ in range(steps):
        net._fit_one(DataSet(x, y))
    _ = net.score_value  # forces completion of the last step
    sps = batch * steps / (time.perf_counter() - t0)
    return _entry("lenet_mnist_fit_samples_per_sec", sps, "samples/sec")


def bench_lenet_superstep(steps, warmup):
    """Superstep dispatch fusion (PERF.md §13): K train iterations per
    device dispatch over device-cached LeNet, against the per-batch loop on
    the SAME cached data in the SAME run — the ratio is the dispatch
    amortization, uncontaminated by run-to-run transport variance."""
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch = int(os.environ.get("BENCH_BATCH_LENET", "512"))
    k = int(os.environ.get("BENCH_SUPERSTEP_K", "8"))
    # Each cached epoch must form >= 2 full K-blocks so the timed loop is
    # superstep dispatches, not tail programs.
    distinct = 2 * k

    def mk(rng, b):
        return (rng.rand(b, 28, 28, 1).astype("float32"),
                np.eye(10, dtype="float32")[rng.randint(0, 10, b)])

    per_net = MultiLayerNetwork(zoo.lenet_mnist()).init()
    per_sps, _ = _timed_fit(per_net, mk, batch, steps, warmup,
                            distinct=distinct, cached=True)

    conf = zoo.lenet_mnist()
    conf.global_conf.superstep_k = k
    sup_net = MultiLayerNetwork(conf).init()
    sup_sps, _ = _timed_fit(sup_net, mk, batch, steps, warmup,
                            distinct=distinct, cached=True)

    head = _entry(f"lenet_superstep_k{k}_samples_per_sec", sup_sps,
                  "samples/sec",
                  note=f"{k} iterations fused per dispatch, device-cached")
    head["per_batch_same_run"] = round(per_sps, 1)
    ratio = _entry("lenet_superstep_vs_per_batch_ratio",
                   sup_sps / max(per_sps, 1e-9), "x (same-run)")
    return head, ratio


# Runs in a FRESH interpreter so every run pays (or skips) the real
# cold-start path: jax import, first trace, first backend compile.
_COLD_WARM_CHILD = r"""
import json, os, time
import numpy as np
from deeplearning4j_tpu import observability as obs
obs.install_jax_compile_hook()
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models import zoo
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

batch = int(os.environ.get("BENCH_BATCH_LENET_COLDWARM", "64"))
net = MultiLayerNetwork(zoo.lenet_mnist()).init()
rng = np.random.RandomState(0)
x = rng.rand(batch, 28, 28, 1).astype("float32")
y = np.eye(10, dtype="float32")[rng.randint(0, 10, batch)]

def totals():
    out = {}
    for name in ("dl4j_xla_compiles_total", "dl4j_compile_cache_hits_total"):
        fam = obs.metrics.get_family(name)
        out[name] = 0.0 if fam is None else sum(
            c.get() for c in fam.children())
    fam = obs.metrics.get_family("dl4j_xla_compile_seconds_total")
    out["compile_seconds"] = 0.0 if fam is None else sum(
        c.get() for c in fam.children())
    return out

t0 = time.perf_counter()
net.fit(DataSet(x, y))
_ = float(net.score_value)
first_fit = time.perf_counter() - t0
t = totals()
print(json.dumps({
    "first_fit_seconds": first_fit,
    "compile_seconds": t["compile_seconds"],
    "xla_compiles": t["dl4j_xla_compiles_total"],
    "cache_hits": t["dl4j_compile_cache_hits_total"],
}))
"""


def bench_lenet_cold_vs_warm(steps, warmup):
    """Cold-start kill (compilation/): the SAME first-fit, in a fresh
    process, with an empty vs a pre-populated compile cache. The cold child
    traces + backend-compiles LeNet from nothing; the warm child replays
    the executable store + persistent XLA cache. `warm_start_speedup` is
    the whole-first-fit wall ratio — the user-visible cold-start cut."""
    import shutil
    import subprocess
    import tempfile

    cache = tempfile.mkdtemp(prefix="bench-compile-cache-")

    def run_child():
        env = dict(os.environ, DL4J_TPU_COMPILE_CACHE=cache)
        proc = subprocess.run([sys.executable, "-c", _COLD_WARM_CHILD],
                              capture_output=True, text=True, env=env,
                              timeout=1800)
        if proc.returncode != 0:
            raise RuntimeError(f"cold/warm child failed: "
                               f"{proc.stderr[-2000:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    try:
        cold = run_child()   # empty cache: pays the full trace + compile
        warm = run_child()   # populated: AOT store + persistent cache
    finally:
        shutil.rmtree(cache, ignore_errors=True)

    speedup = cold["first_fit_seconds"] / max(warm["first_fit_seconds"],
                                              1e-9)
    head = _entry("lenet_warm_start_speedup", speedup, "x (fresh process)",
                  note="first fit() wall seconds, empty vs populated "
                       "compile cache; includes trace + backend compile "
                       "cold, executable-store replay warm")
    head["compile_seconds_cold"] = round(cold["compile_seconds"], 3)
    head["compile_seconds_warm"] = round(warm["compile_seconds"], 3)
    head["first_fit_seconds_cold"] = round(cold["first_fit_seconds"], 3)
    head["first_fit_seconds_warm"] = round(warm["first_fit_seconds"], 3)
    head["xla_compiles_cold"] = cold["xla_compiles"]
    head["xla_compiles_warm"] = warm["xla_compiles"]
    head["cache_hits_warm"] = warm["cache_hits"]
    return head


# Fresh interpreter per arm: DL4J_TPU_OBS / DL4J_TPU_FLIGHT are read at
# import, so toggling them honestly needs a new process.
_OBS_OVERHEAD_CHILD = r"""
import json, os, time
import numpy as np
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models import zoo
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

steps = int(os.environ.get("BENCH_OBS_STEPS", "150"))
warmup = int(os.environ.get("BENCH_OBS_WARMUP", "20"))
batch = int(os.environ.get("BENCH_BATCH_LENET", "64"))
net = MultiLayerNetwork(zoo.lenet_mnist()).init()
rng = np.random.RandomState(0)
x = rng.rand(batch, 28, 28, 1).astype("float32")
y = np.eye(10, dtype="float32")[rng.randint(0, 10, batch)]
ds = DataSet(x, y)
for _ in range(warmup):
    net.fit(ds)
_ = float(net.score_value)
t0 = time.perf_counter()
for _ in range(steps):
    net.fit(ds)
_ = float(net.score_value)
dt = time.perf_counter() - t0
print(json.dumps({"steps": steps, "seconds": dt,
                  "step_seconds": dt / steps}))
"""


def bench_obs_overhead(steps, warmup):
    """Recorder-budget proof (observability tier): the SAME steady-state
    lenet loop in three fresh interpreters — all observability disabled,
    metrics registry on, registry + flight recorder on. The ratios are the
    always-on cost; the flight-recorder budget is <2% (PERF.md §16)."""
    import subprocess

    arms = (
        ("disabled", {"DL4J_TPU_OBS": "0", "DL4J_TPU_FLIGHT": "0"}),
        ("metrics", {"DL4J_TPU_OBS": "1", "DL4J_TPU_FLIGHT": "0"}),
        ("metrics_flight", {"DL4J_TPU_OBS": "1", "DL4J_TPU_FLIGHT": "1"}),
    )
    res = {}
    for name, env_over in arms:
        env = dict(os.environ, **env_over)
        env.setdefault("BENCH_OBS_STEPS", str(max(150, steps)))
        proc = subprocess.run([sys.executable, "-c", _OBS_OVERHEAD_CHILD],
                              capture_output=True, text=True, env=env,
                              timeout=1800)
        if proc.returncode != 0:
            raise RuntimeError(f"obs_overhead child {name!r} failed: "
                               f"{proc.stderr[-2000:]}")
        res[name] = json.loads(proc.stdout.strip().splitlines()[-1])
    base = res["disabled"]["step_seconds"]
    ratio_m = res["metrics"]["step_seconds"] / max(base, 1e-12)
    ratio_f = res["metrics_flight"]["step_seconds"] / max(base, 1e-12)
    head = _entry("obs_overhead_flight_ratio", ratio_f,
                  "x vs disabled (fresh process)",
                  note="steady-state lenet step seconds with metrics + "
                       "flight recorder on, vs all observability off; "
                       "recorder budget is <1.02x (PERF.md §16)")
    head["metrics_only_ratio"] = round(ratio_m, 4)
    for name, r in res.items():
        head[f"step_seconds_{name}"] = round(r["step_seconds"], 6)
    return head


_SLO_LEDGER_CHILD = r"""
import json, os, threading, time
import numpy as np
from deeplearning4j_tpu.models.zoo import transformer_lm
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.serving import InferenceServer

V = 256
n_gen = int(os.environ.get("BENCH_LEDGER_GENS", "16"))
n_pred = int(os.environ.get("BENCH_LEDGER_PREDICTS", "48"))
cg = ComputationGraph(transformer_lm(
    vocab_size=V, t=64, d_model=64, n_heads=4, n_blocks=2,
    decode_cache_length=128)).init()
server = InferenceServer(cg, default_model="ledger_arm", decode_slots=4,
                         max_batch_size=8, max_delay_ms=1.0,
                         generate_queue_depth=max(64, n_gen))
m = server.models.get("ledger_arm")
m.batcher.warm()
m.scheduler.warmup()
rng = np.random.RandomState(0)
prompts = [list(rng.randint(1, V, 8)) for _ in range(n_gen)]
rows = rng.randint(1, V, (n_pred, 8)).astype(np.int32)
# warmup pass outside the timed window
server.predict(rows[:1])
server.generate(prompts[0], 4, temperature=0.0)
errors = []

def gen(i):
    try:
        server.generate(prompts[i], 4 + i % 13, temperature=1.0, seed=i)
    except Exception as e:
        errors.append(f"{type(e).__name__}: {e}")

def pred(i):
    try:
        server.predict(rows[i:i + 1])
    except Exception as e:
        errors.append(f"{type(e).__name__}: {e}")

threads = ([threading.Thread(target=gen, args=(i,)) for i in range(n_gen)]
           + [threading.Thread(target=pred, args=(i,))
              for i in range(n_pred)])
t0 = time.perf_counter()
for th in threads:
    th.start()
for th in threads:
    th.join()
dt = time.perf_counter() - t0
server.stop()
if errors:
    raise SystemExit("slo_ledger child errors: " + "; ".join(errors[:3]))
n = n_gen + n_pred
print(json.dumps({"requests": n, "seconds": dt,
                  "request_seconds": dt / n}))
"""


def bench_slo_ledger(steps, warmup):
    """Ledger-budget proof (ISSUE 17 acceptance): the SAME mixed
    predict+generate serving trace in two fresh interpreters — request
    ledger off (`DL4J_TPU_LEDGER=0`) and on (default). The always-on
    per-request lifecycle records + device-second attribution must cost
    <=2% of per-request wall time (PERF.md §25)."""
    import subprocess

    arms = (("off", {"DL4J_TPU_LEDGER": "0"}),
            ("on", {"DL4J_TPU_LEDGER": "1"}))

    def run_arm(name, env_over):
        env = dict(os.environ, **env_over)
        env.setdefault("BENCH_LEDGER_GENS", str(max(16, steps // 2)))
        env.setdefault("BENCH_LEDGER_PREDICTS", str(max(48, steps)))
        proc = subprocess.run([sys.executable, "-c", _SLO_LEDGER_CHILD],
                              capture_output=True, text=True, env=env,
                              timeout=1800)
        if proc.returncode != 0:
            raise RuntimeError(f"slo_ledger child {name!r} failed: "
                               f"{proc.stderr[-2000:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    # Interleaved repeats, median per arm: one 64-thread burst's wall
    # time swings with OS scheduling far more than the ledger's cost, so
    # a single off/on pair can land anywhere. Interleaving cancels slow
    # machine phases; the median throws away the outlier bursts.
    repeats = int(os.environ.get("BENCH_LEDGER_REPEATS", "3"))
    samples = {name: [] for name, _ in arms}
    requests = {}
    for _ in range(max(1, repeats)):
        for name, env_over in arms:
            r = run_arm(name, env_over)
            samples[name].append(float(r["request_seconds"]))
            requests[name] = int(r["requests"])
    med = {name: sorted(vals)[len(vals) // 2]
           for name, vals in samples.items()}
    ratio = med["on"] / max(med["off"], 1e-12)
    head = _entry("slo_ledger_overhead_ratio", ratio,
                  "x vs ledger off (fresh process)",
                  note="mixed predict+generate request seconds with the "
                       "request ledger + tenant attribution on vs off; "
                       f"median of {max(1, repeats)} interleaved pairs; "
                       "budget is <=1.02x (PERF.md §25)")
    for name in med:
        head[f"request_seconds_{name}"] = round(med[name], 6)
        head[f"request_seconds_{name}_range"] = [
            round(min(samples[name]), 6), round(max(samples[name]), 6)]
        head[f"requests_{name}"] = requests[name]
    return head


def bench_locktrace_overhead(steps, warmup):
    """Lock-tracer budget proof (ISSUE 18 acceptance): the SAME mixed
    predict+generate serving trace in two fresh interpreters — lock
    tracing off (`DL4J_TPU_LOCKTRACE=0`, the default: factories return
    plain threading primitives, so the cost is one env check at import)
    and on (`DL4J_TPU_LOCKTRACE=1`: every serving/observability lock is a
    TracedLock feeding held-sets + the order graph). Enabled overhead
    must stay <=2% of per-request wall time (PERF.md §26)."""
    import subprocess

    arms = (("off", {"DL4J_TPU_LOCKTRACE": "0"}),
            ("on", {"DL4J_TPU_LOCKTRACE": "1"}))

    def run_arm(name, env_over):
        env = dict(os.environ, **env_over)
        env.setdefault("BENCH_LEDGER_GENS", str(max(16, steps // 2)))
        env.setdefault("BENCH_LEDGER_PREDICTS", str(max(48, steps)))
        proc = subprocess.run([sys.executable, "-c", _SLO_LEDGER_CHILD],
                              capture_output=True, text=True, env=env,
                              timeout=1800)
        if proc.returncode != 0:
            raise RuntimeError(f"locktrace child {name!r} failed: "
                               f"{proc.stderr[-2000:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    # Same interleaved-median discipline as slo_ledger: one 64-thread
    # burst's wall time swings with OS scheduling far more than the
    # tracer's cost, so a single off/on pair can land anywhere.
    repeats = int(os.environ.get("BENCH_LOCKTRACE_REPEATS", "3"))
    samples = {name: [] for name, _ in arms}
    requests = {}
    for _ in range(max(1, repeats)):
        for name, env_over in arms:
            r = run_arm(name, env_over)
            samples[name].append(float(r["request_seconds"]))
            requests[name] = int(r["requests"])
    med = {name: sorted(vals)[len(vals) // 2]
           for name, vals in samples.items()}
    ratio = med["on"] / max(med["off"], 1e-12)
    head = _entry("locktrace_overhead_ratio", ratio,
                  "x vs locktrace off (fresh process)",
                  note="mixed predict+generate request seconds with the "
                       "traced-lock factory + order graph + stall "
                       "watchdog on vs off; median of "
                       f"{max(1, repeats)} interleaved pairs; "
                       "budget is <=1.02x (PERF.md §26)")
    for name in med:
        head[f"request_seconds_{name}"] = round(med[name], 6)
        head[f"request_seconds_{name}_range"] = [
            round(min(samples[name]), 6), round(max(samples[name]), 6)]
        head[f"requests_{name}"] = requests[name]
    return head


def bench_char_rnn(steps, warmup):
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch = int(os.environ.get("BENCH_BATCH_CHAR_RNN", "32"))
    vocab, t = 77, 100
    net = MultiLayerNetwork(zoo.char_rnn(vocab_size=vocab)).init()

    def mk(rng, b):
        idx = rng.randint(0, vocab, (b, t))
        x = np.eye(vocab, dtype="float32")[idx]
        y = np.eye(vocab, dtype="float32")[np.roll(idx, -1, axis=1)]
        return x, y

    # Median of k timed windows with the observed range in the entry: one
    # draw from this config spans 3.8k..19k samples/s across sessions
    # (PERF.md §4), so a point sample misleads; the median is the number,
    # the range is the honesty.
    k = int(os.environ.get("BENCH_CHAR_RNN_REPEATS", "5"))
    draws = [_timed_fit(net, mk, batch, steps, warmup if i == 0 else 0,
                        cached=True)[0] for i in range(k)]
    e = _entry("char_rnn_fit_samples_per_sec", float(np.median(draws)),
               "samples/sec")
    e["range_samples_per_sec"] = [round(min(draws), 1), round(max(draws), 1)]
    e["repeats"] = k
    return e


def _kernel_env(**vars):
    """Set kernel-registry env knobs for one bench leg and drop the
    resolution memo so the leg re-resolves under them; returns a restore
    callable. Value None deletes the var."""
    from deeplearning4j_tpu.kernels import registry

    saved = {k: os.environ.get(k) for k in vars}
    for k, v in vars.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    registry.clear_cache()

    def restore():
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        registry.clear_cache()

    return restore


def _dispatch_counts(kernel):
    """Current dl4j_kernel_dispatch_total values for one kernel, by impl."""
    from deeplearning4j_tpu import observability as obs

    fam = obs.metrics.to_json().get("dl4j_kernel_dispatch_total")
    out = {}
    for s in (fam or {"series": []})["series"]:
        if s["labels"]["kernel"] == kernel:
            out[s["labels"]["impl"]] = out.get(s["labels"]["impl"], 0) \
                + s["value"]
    return out


def _impl_delta(before, after):
    """The impl the bench leg actually dispatched (largest count delta)."""
    deltas = {k: after.get(k, 0) - before.get(k, 0)
              for k in set(after) | set(before)}
    return max(deltas, key=deltas.get) if deltas else "none"


def bench_char_rnn_fused_lstm(steps, warmup):
    """Kernel-registry tentpole (PERF.md §19): char-RNN with the fused
    Pallas LSTM cell (`auto`: picks Pallas on TPU, hidden=256 is
    lane-aligned) against `DL4J_TPU_KERNELS=xla` (the bit-stable pre-
    registry scan body) on the SAME device-cached data in the SAME run —
    the ratio is the cell fusion, not transport variance. Off-TPU both
    legs resolve the XLA fallback and the ratio reads ~1.0; the entry
    records which impl actually dispatched."""
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch = int(os.environ.get("BENCH_BATCH_CHAR_RNN", "32"))
    vocab, hidden, t = 77, 256, 100

    def mk(rng, b):
        idx = rng.randint(0, vocab, (b, t))
        x = np.eye(vocab, dtype="float32")[idx]
        y = np.eye(vocab, dtype="float32")[np.roll(idx, -1, axis=1)]
        return x, y

    restore = _kernel_env(DL4J_TPU_KERNELS="xla", DL4J_TPU_KERNEL_LSTM_CELL=None)
    try:
        xla_net = MultiLayerNetwork(zoo.char_rnn(vocab_size=vocab,
                                                 hidden=hidden)).init()
        xla_sps, _ = _timed_fit(xla_net, mk, batch, steps, warmup,
                                cached=True)
    finally:
        restore()

    restore = _kernel_env(DL4J_TPU_KERNELS=None, DL4J_TPU_KERNEL_LSTM_CELL=None)
    try:
        before = _dispatch_counts("lstm_cell")
        fused_net = MultiLayerNetwork(zoo.char_rnn(vocab_size=vocab,
                                                   hidden=hidden)).init()
        fused_sps, _ = _timed_fit(fused_net, mk, batch, steps, warmup,
                                  cached=True)
        impl = _impl_delta(before, _dispatch_counts("lstm_cell"))
    finally:
        restore()

    head = _entry("char_rnn_fused_lstm_samples_per_sec", fused_sps,
                  "samples/sec",
                  note=f"auto-resolved lstm_cell impl: {impl}; hidden=256")
    head["xla_fallback_same_run"] = round(xla_sps, 1)
    ratio = _entry("char_rnn_fused_lstm_vs_xla_ratio",
                   fused_sps / max(xla_sps, 1e-9), "x (same-run)")
    return head, ratio


def bench_fused_update_superstep(steps, warmup):
    """Fused optimizer update through the superstep carry (PERF.md §19):
    device-cached LeNet (nesterovs) at superstep k=8 with the fused
    flat-vector update kernel (`auto`) vs the per-leaf tree_map fallback
    (`DL4J_TPU_KERNEL_FUSED_UPDATE=xla`), same run, same cached data."""
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    batch = int(os.environ.get("BENCH_BATCH_LENET", "512"))
    k = int(os.environ.get("BENCH_SUPERSTEP_K", "8"))
    distinct = 2 * k  # >= 2 full K-blocks per epoch (see lenet_superstep)

    def mk(rng, b):
        return (rng.rand(b, 28, 28, 1).astype("float32"),
                np.eye(10, dtype="float32")[rng.randint(0, 10, b)])

    def run():
        conf = zoo.lenet_mnist()
        conf.global_conf.superstep_k = k
        net = MultiLayerNetwork(conf).init()
        return _timed_fit(net, mk, batch, steps, warmup,
                          distinct=distinct, cached=True)[0]

    restore = _kernel_env(DL4J_TPU_KERNEL_FUSED_UPDATE="xla")
    try:
        xla_sps = run()
    finally:
        restore()

    restore = _kernel_env(DL4J_TPU_KERNEL_FUSED_UPDATE=None)
    try:
        before = _dispatch_counts("fused_update")
        fused_sps = run()
        impl = _impl_delta(before, _dispatch_counts("fused_update"))
    finally:
        restore()

    head = _entry(f"fused_update_superstep_k{k}_samples_per_sec", fused_sps,
                  "samples/sec",
                  note=f"auto-resolved fused_update impl: {impl}; "
                       "nesterovs through the superstep carry")
    head["xla_fallback_same_run"] = round(xla_sps, 1)
    ratio = _entry("fused_update_superstep_vs_xla_ratio",
                   fused_sps / max(xla_sps, 1e-9), "x (same-run)")
    return head, ratio


def bench_word2vec(steps, warmup):
    """BASELINE.md config 4: Word2Vec skip-gram-HS on a synthetic
    text8-scale corpus (Zipf unigram distribution), words/sec through the
    public `Word2Vec.fit` — vocab build + Huffman coding + example assembly
    + jitted kernel flushes all included, matching how the reference's
    wall-clock on text8 is counted."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    n_words = int(os.environ.get("BENCH_W2V_WORDS", "2000000"))
    V, sent_len = 10000, 1000
    rng = np.random.RandomState(0)
    p = 1.0 / np.arange(1, V + 1)
    p /= p.sum()
    words = [f"w{i}" for i in range(V)]
    idx = rng.choice(V, size=n_words, p=p)
    sents = [[words[j] for j in idx[i:i + sent_len]]
             for i in range(0, n_words, sent_len)]
    kw = dict(layer_size=100, window_size=5, min_word_frequency=1,
              sample=1e-3, negative=0, seed=1, batch_size=16384)
    # Warm the compiled programs on the full corpus (kernel shapes depend
    # on vocab size + Huffman depth, so a prefix would leave the timed run
    # recompiling); the timed second fit is steady-state throughput, the
    # way the reference's PerformanceListener reports it.
    Word2Vec(**kw).fit(sents)
    w2v = Word2Vec(**kw)
    rtt_ms, mibps = _link_probe()
    t0 = time.perf_counter()
    w2v.fit(sents)
    dt = time.perf_counter() - t0
    e = _entry("word2vec_skipgram_words_per_sec", n_words / dt, "words/sec",
               note=("dispatch-paced over the shared tunnel: each K-flush "
                     "scan costs one RTT, so words/sec scales ~1/RTT "
                     "(460-490k at ~10 ms RTT, PERF.md §5); tunnel_rtt_ms "
                     "is the in-run measurement"))
    e["tunnel_rtt_ms"] = round(rtt_ms, 2)
    e["link_mibps"] = round(mibps, 1)
    return e


def bench_vgg16_dp(steps, warmup):
    """BASELINE.md config 5: VGG-16 (Keras-zoo topology) through
    ParallelWrapper over every visible device — samples/sec/chip. On the
    single tunneled chip this measures the wrapper's sharded path at mesh
    size 1; multi-chip scaling efficiency is exercised (not timed) by the
    driver's dryrun_multichip on the virtual CPU mesh."""
    import jax
    import ml_dtypes

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.keras.trained_models import vgg16_config
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    batch = int(os.environ.get("BENCH_BATCH_VGG16", "128"))
    n_dev = len(jax.devices())
    net = MultiLayerNetwork(vgg16_config(n_classes=1000, dtype="bfloat16"))
    pw = ParallelWrapper(net)
    rng = np.random.RandomState(0)

    def mk_ds():
        x = rng.rand(batch, 224, 224, 3).astype("float32")
        return DataSet(
            x.astype(ml_dtypes.bfloat16),
            np.eye(1000, dtype="float32")[rng.randint(0, 1000, batch)])

    pool = [mk_ds() for _ in range(2)]
    for _ in range(max(2, warmup // 2)):
        pw.fit(pool[0])
    _ = net.score_value
    rtt_ms, mibps = _link_probe()
    n = max(8, steps)
    t0 = time.perf_counter()
    for i in range(n):
        pw.fit(pool[i % 2])
    _ = net.score_value
    dt = time.perf_counter() - t0
    e = _entry("vgg16_dp_samples_per_sec_per_chip",
               batch * n / dt / max(n_dev, 1), "samples/sec/chip",
               note=_LINK_NOTE)
    e["tunnel_rtt_ms"] = round(rtt_ms, 2)
    e["link_mibps"] = round(mibps, 1)
    return e


def bench_flash_attention(steps, warmup):
    """Pallas flash-attention forward vs XLA dense attention (bf16,
    T=8192, BH=8, D=64 — PERF.md §6). Reports the speedup ratio; device
    memory is the bigger win (no [T, T] buffer)."""
    import jax
    import jax.numpy as jnp
    import ml_dtypes

    from deeplearning4j_tpu.ops.flash_attention import (
        _dense_ref, flash_attention,
    )

    B, T, H, D = 2, 8192, 4, 64
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(
        rng.randn(B, T, H, D).astype("float32").astype(ml_dtypes.bfloat16))
    q, k, v = mk(), mk(), mk()
    flash = jax.jit(lambda q, k, v: flash_attention(q, k, v, True, None,
                                                    256, 256))
    dense = jax.jit(lambda q, k, v: _dense_ref(q, k, v, True, D ** -0.5))

    def timed(f, n):
        for _i in range(max(1, warmup)):
            o = f(q, k, v)
        _ = float(o[0, 0, 0, 0].astype(jnp.float32))  # sync
        t0 = time.perf_counter()
        for _i in range(n):
            o = f(q, k, v)
        _ = float(o[0, 0, 0, 0].astype(jnp.float32))
        return (time.perf_counter() - t0) / n

    n = max(10, steps)
    tf, td = timed(flash, n), timed(dense, n)
    e = _entry("flash_attention_speedup_vs_xla", td / tf, "ratio")
    e["flash_ms"] = round(tf * 1e3, 2)
    e["xla_dense_ms"] = round(td * 1e3, 2)
    return e


def bench_flash_triangular(steps, warmup):
    """Round-5 metric: the causal streaming kernel's triangular DMA
    sequence vs the round-4 rectangular pattern (same kernel, full-grid
    pair list with compute masking) at T=32768 bf16. Timed as R kernel
    runs inside ONE jitted scan — the only discipline the tunnel respects
    (PERF.md §6)."""
    import functools as ft

    import jax
    import jax.numpy as jnp
    import ml_dtypes
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from deeplearning4j_tpu.ops import flash_attention as fa

    BH, T, D = 4, 32768, 64
    BQ = BK = 256
    R = 8
    nq, nk = T // BQ, T // BK

    def pairs(triangular):
        if triangular:
            return fa._pair_arrays(nq, nk, BQ, BK, True, "row")
        ii = np.repeat(np.arange(nq, dtype=np.int32), nk)
        jj = np.tile(np.arange(nk, dtype=np.int32), nq)
        return ii, jj

    def stream_sum(q, k, v, triangular):
        ii, jj = pairs(triangular)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2, grid=(BH, len(ii)),
            in_specs=[
                pl.BlockSpec((1, BQ, D), lambda b, t, a, c: (b, a[t], 0)),
                pl.BlockSpec((1, BK, D), lambda b, t, a, c: (b, c[t], 0)),
                pl.BlockSpec((1, BK, D), lambda b, t, a, c: (b, c[t], 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, BQ, D), lambda b, t, a, c: (b, a[t], 0)),
                pl.BlockSpec((1, BQ, 1), lambda b, t, a, c: (b, a[t], 0)),
            ],
            scratch_shapes=[pltpu.VMEM((BQ, D), jnp.float32),
                            pltpu.VMEM((BQ, 1), jnp.float32),
                            pltpu.VMEM((BQ, 1), jnp.float32)],
        )
        o, _lse = pl.pallas_call(
            ft.partial(fa._flash_stream_kernel, block_q=BQ, block_k=BK,
                       nk=nk, causal=True, scale=D ** -0.5),
            grid_spec=grid_spec,
            out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                       jax.ShapeDtypeStruct((BH, T, 1), jnp.float32)],
        )(jnp.asarray(ii), jnp.asarray(jj), q, k, v)
        return jnp.sum(o.astype(jnp.float32))

    def repeated(triangular):
        def fn(q, k, v):
            def body(acc, s):
                qs = (q.astype(jnp.float32) * (1.0 + 0.001 * s)).astype(q.dtype)
                return acc + stream_sum(qs, k, v, triangular), None
            acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                                  jnp.arange(R, dtype=jnp.float32))
            return acc
        return jax.jit(fn)

    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(
        (rng.randn(BH, T, D) * 0.5).astype("float32")
        .astype(ml_dtypes.bfloat16))
    q, k, v = mk(), mk(), mk()

    def timed(f, rounds=3):
        _ = float(np.asarray(f(q, k, v)))  # compile
        ts = []
        for _i in range(rounds):
            t0 = time.perf_counter()
            _ = float(np.asarray(f(q, k, v)))
            ts.append((time.perf_counter() - t0) / R)
        return min(ts)

    t_tri = timed(repeated(True))
    t_rect = timed(repeated(False))
    e = _entry("flash_tri_speedup_32k", t_rect / t_tri, "ratio")
    e["tri_ms"] = round(t_tri * 1e3, 2)
    e["rect_ms"] = round(t_rect * 1e3, 2)
    return e


def bench_transformer(steps, warmup):
    """Round-5 config: decoder-only transformer LM (DSL-built:
    SelfAttentionLayer w/ Pallas flash + pre-LN blocks) — training
    tokens/sec on device-resident batches. No BASELINE row (the reference
    predates attention); anchors at its first record."""
    import ml_dtypes

    from deeplearning4j_tpu.models.zoo import transformer_lm
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    V, T = 8192, 1024
    B = int(os.environ.get("BENCH_BATCH_TRANSFORMER", "16"))
    net = ComputationGraph(transformer_lm(
        vocab_size=V, t=T, d_model=512, n_heads=8, n_blocks=4,
        dtype="bfloat16")).init()

    import jax

    from deeplearning4j_tpu.datasets.dataset import MultiDataSet

    rng = np.random.RandomState(0)

    def mk():
        idx = rng.randint(0, V, (B, T))
        # Sparse class-id labels (round 5): [B, T] int32 instead of the
        # [B, T, V] one-hot (134 MB at these dims) — the format real LM
        # training uses. Device-resident batches either way.
        return MultiDataSet(
            features=[jax.device_put(idx.astype("float32"))],
            labels=[jax.device_put(
                np.roll(idx, -1, axis=1).astype(np.int32))])

    pool = [mk() for _ in range(2)]
    for _ in range(max(2, warmup)):
        net.fit(pool[0])
    _ = net.score_value
    n = max(8, steps)
    t0 = time.perf_counter()
    for i in range(n):
        net.fit(pool[i % 2])
    _ = net.score_value
    dt = time.perf_counter() - t0
    e = _entry("transformer_lm_train_tokens_per_sec", B * T * n / dt,
               "tokens/sec")
    e["ms_per_step"] = round(dt / n * 1e3, 1)
    return e


def bench_serving_slo(steps, warmup):
    """Serving-tier SLO config: continuous-batching generation throughput
    (tokens/sec) vs the drain-then-refill control arm on the SAME model
    and request trace, plus TTFT p50/p99 per arm and predict-path request
    latency p50/p99 through the shape-bucket batcher. No BASELINE row
    (the reference never had a serving tier); anchors at its first
    record."""
    import threading

    from deeplearning4j_tpu import observability as obs
    from deeplearning4j_tpu.models.zoo import transformer_lm
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.serving import InferenceServer

    V = 256
    cap = int(os.environ.get("BENCH_SERVING_CACHE", "128"))
    slots = int(os.environ.get("BENCH_SERVING_SLOTS", "4"))
    n_req = max(12, steps)
    gen_cap = int(os.environ.get("BENCH_SERVING_GEN_STEPS", "64"))
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, V, rng.randint(4, 17)))
               for _ in range(n_req)]
    # Varying generation lengths are what continuous batching exploits:
    # short sequences free their slot mid-flight; drain mode idles those
    # slots until the longest sequence in the batch finishes.
    lengths = [4 + (i * 13) % gen_cap for i in range(n_req)]

    def run_arm(mode, name):
        cg = ComputationGraph(transformer_lm(
            vocab_size=V, t=64, d_model=64, n_heads=4, n_blocks=2,
            decode_cache_length=cap)).init()
        server = InferenceServer(cg, default_model=name, decode_slots=slots,
                                 scheduler_mode=mode, max_batch_size=8,
                                 max_delay_ms=1.0,
                                 generate_queue_depth=max(64, n_req))
        # Compile every prefill bucket + the decode step outside the
        # timed window (production pays this once, at startup).
        server.models.get(name).scheduler.warmup()
        generated, errors = [], []

        def client(i):
            try:
                out = server.generate(prompts[i], lengths[i],
                                      temperature=1.0, seed=i)
                generated.append(len(out) - len(prompts[i]))
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")

        threads = []
        t0 = time.perf_counter()
        for i in range(n_req):
            th = threading.Thread(target=client, args=(i,))
            th.start()
            threads.append(th)
            time.sleep(0.002)  # staggered arrivals: mid-flight admission
        for th in threads:
            th.join()
        dt = time.perf_counter() - t0
        server.stop()
        if errors:
            raise RuntimeError(f"serving bench arm {mode}: {errors[:3]}")
        ttft = obs.metrics.get_family("dl4j_serving_ttft_seconds").labels(
            model=name).summarize(quantiles=(0.5, 0.99))
        return sum(generated) / dt, ttft

    cont_tps, cont_ttft = run_arm("continuous", "slo_cont")
    drain_tps, drain_ttft = run_arm("drain", "slo_drain")

    head = _entry("serving_continuous_tokens_per_sec", cont_tps,
                  "tokens/sec")
    head["continuous_vs_drain"] = round(cont_tps / max(drain_tps, 1e-9), 2)
    head["ttft_p50_ms"] = round(cont_ttft.get("p50", 0.0) * 1e3, 1)
    head["ttft_p99_ms"] = round(cont_ttft.get("p99", 0.0) * 1e3, 1)
    drain = _entry("serving_drain_tokens_per_sec", drain_tps, "tokens/sec")
    drain["ttft_p50_ms"] = round(drain_ttft.get("p50", 0.0) * 1e3, 1)
    drain["ttft_p99_ms"] = round(drain_ttft.get("p99", 0.0) * 1e3, 1)

    # Predict-path SLO through the shape-bucket batcher: concurrent
    # mixed-size requests, per-model latency histogram -> p50/p99.
    cg = ComputationGraph(transformer_lm(
        vocab_size=V, t=64, d_model=64, n_heads=4, n_blocks=2,
        decode_cache_length=cap)).init()
    server = InferenceServer(cg, default_model="slo_predict",
                             max_batch_size=8, max_delay_ms=1.0)
    server.models.get("slo_predict").batcher.warm()
    perr = []

    prng = np.random.RandomState(1)
    rows = prng.randint(1, V, (max(16, steps), 8)).astype(np.int32)

    def pclient(i):
        try:
            server.predict(np.tile(rows[i], (1 + i % 3, 1)))
        except Exception as e:
            perr.append(f"{type(e).__name__}: {e}")

    threads = [threading.Thread(target=pclient, args=(i,))
               for i in range(max(16, steps))]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    server.stop()
    if perr:
        raise RuntimeError(f"serving bench predict arm: {perr[:3]}")
    lat = obs.metrics.get_family("dl4j_serving_request_seconds").labels(
        model="slo_predict", route="predict").summarize(
            quantiles=(0.5, 0.99))
    pe = _entry("serving_predict_p99_ms", lat.get("p99", 0.0) * 1e3, "ms")
    pe["p50_ms"] = round(lat.get("p50", 0.0) * 1e3, 2)
    pe["requests"] = int(lat.get("count", 0))
    return [head, drain, pe]


def bench_decode_paged(steps, warmup):
    """Paged-KV generation fast path (ISSUE 15): slots-resident at EQUAL
    HBM vs the dense stepper, decode tokens/sec through the paged
    scheduler vs the equal-HBM dense arm on the same request trace, and
    prefix-cache TTFT (repeat prompt) vs a cold prefill. The pool is
    sized to exactly the dense arm's KV rows (slots x capacity =
    usable_pages x page_size), so the slot multiplier is pure
    padding/duplication reclaim — every request shares one long system
    prompt, resident once under the paged arm and N times under dense."""
    import threading

    from deeplearning4j_tpu.models.zoo import transformer_lm
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.serving.scheduler import GenerationScheduler

    V = 256
    cap = 256
    page = 32
    dense_slots = 4
    paged_slots = 16
    # Equal HBM: usable pages hold exactly the dense arm's KV rows.
    pool_pages = dense_slots * (cap // page) + 1  # +1 reserved zero page
    n_req = paged_slots
    gen = 30
    rng = np.random.RandomState(0)
    prompt = list(rng.randint(1, V, 6 * page))  # 6 full shared pages

    def run_arm(kv, slots, name, pages=None):
        cg = ComputationGraph(transformer_lm(
            vocab_size=V, t=64, d_model=64, n_heads=4, n_blocks=2,
            decode_cache_length=cap)).init()
        sched = GenerationScheduler(
            cg, model_name=name, slots=slots, prompt_buckets=[cap],
            queue_depth=max(64, n_req), kv=kv, page_size=page,
            kv_pages=pages).start()
        sched.warmup()
        # TTFT: cold prefill (also admits the prompt into the prefix
        # cache on the paged arm), then the repeat-prompt hit.
        t0 = time.perf_counter()
        sched.generate(prompt, 1, temperature=0.0, timeout_s=300)
        ttft_miss = time.perf_counter() - t0
        t0 = time.perf_counter()
        sched.generate(prompt, 1, temperature=0.0, timeout_s=300)
        ttft_hit = time.perf_counter() - t0
        errors, resident = [], [0]

        def client(i):
            try:
                sched.generate(prompt, gen, temperature=1.0, seed=i,
                               timeout_s=600)
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_req)]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        while any(th.is_alive() for th in threads):
            if kv == "paged":
                resident[0] = max(resident[0],
                                  len(sched.stepper.pool.tracked()))
            else:
                resident[0] = slots
            time.sleep(0.01)
        for th in threads:
            th.join()
        dt = time.perf_counter() - t0
        sched.stop()
        if errors:
            raise RuntimeError(f"decode_paged arm {kv}: {errors[:3]}")
        return n_req * gen / dt, ttft_miss, ttft_hit, resident[0]

    paged_tps, ttft_miss, ttft_hit, paged_res = run_arm(
        "paged", paged_slots, "decode_paged", pages=pool_pages)
    dense_tps, dense_miss, _, _ = run_arm("dense", dense_slots,
                                          "decode_dense")

    head = _entry("decode_paged_tokens_per_sec", paged_tps, "tokens/sec",
                  note=f"{paged_slots} slots on {pool_pages - 1} usable "
                       f"pages of {page} tokens (= dense {dense_slots} x "
                       f"{cap} KV rows), one {len(prompt)}-token shared "
                       "prompt resident once")
    head["paged_vs_dense_tokens_per_sec"] = round(
        paged_tps / max(dense_tps, 1e-9), 2)
    slots_e = _entry("decode_paged_slots_resident_at_equal_hbm",
                     paged_res / dense_slots, "x",
                     note=f"{paged_res} paged slots resident vs "
                          f"{dense_slots} dense at the same KV rows")
    ttft_e = _entry("decode_paged_prefix_hit_ttft_ms", ttft_hit * 1e3, "ms",
                    note="repeat prompt: shared pages installed by "
                         "reference + stored first-token distribution "
                         "replayed; no prefill dispatch")
    ttft_e["prefill_miss_ttft_ms"] = round(ttft_miss * 1e3, 2)
    ttft_e["dense_prefill_ttft_ms"] = round(dense_miss * 1e3, 2)
    ttft_e["hit_below_prefill"] = bool(ttft_hit < ttft_miss)
    return [head, slots_e, ttft_e]


# Runs in its own process: the host-device count must be forced into
# XLA_FLAGS before jax initializes its backends, and the parent bench
# process has usually initialized jax long before this config runs.
_SHARDED_DECODE_WORKER = """
import json, sys, time
import numpy as np


def main():
    out_path, steps, warmup = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    import jax
    from deeplearning4j_tpu.models.zoo import (PagedDecodeStepper,
                                               transformer_lm)
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.parallel import mesh as mesh_mod
    from deeplearning4j_tpu.parallel.context import ParallelContext
    from deeplearning4j_tpu.serving.host import per_chip_bytes

    V, T, D, HEADS, BLOCKS, CAP, PAGE, SLOTS = 512, 64, 256, 8, 4, 512, 64, 4
    prompt = list(np.random.RandomState(0).randint(1, V, 48))
    steps = max(10, min(steps, CAP - len(prompt) - warmup - 8))
    results = {}
    for ways in (1, 2, 4):
        cg = ComputationGraph(transformer_lm(
            vocab_size=V, t=T, d_model=D, n_heads=HEADS, n_blocks=BLOCKS,
            decode_cache_length=CAP, seed=11)).init()
        ctx = None
        if ways > 1:
            n = len(jax.devices())
            mesh = mesh_mod.create_mesh((n // ways, ways),
                                        ("data", "model"))
            ctx = ParallelContext(mesh=mesh, model_axis="model")
            mesh_mod.shard_params(cg, mesh, model_axis="model")
        stepper = PagedDecodeStepper(cg, SLOTS, page_size=PAGE,
                                     context=ctx)
        for slot in range(SLOTS):
            _, st, n_tok = stepper.prefill(prompt)
            stepper.install(slot, st, n_tok)
        toks = [1] * SLOTS
        for _ in range(warmup):
            np.asarray(stepper.step(toks))
        t0 = time.perf_counter()
        for _ in range(steps):
            np.asarray(stepper.step(toks))
        dt = time.perf_counter() - t0
        kv = {}
        for i in range(BLOCKS):
            st = stepper._state[f"attn{i}"]
            kv[f"attn{i}"] = {"k": st["k_pages"], "v": st["v_pages"]}
        kv_global = sum(l.nbytes
                        for l in jax.tree_util.tree_leaves(kv))
        param_global = sum(
            l.nbytes for l in jax.tree_util.tree_leaves(cg.params_tree))
        results[str(ways)] = {
            "tokens_per_sec": SLOTS * steps / dt,
            "param_per_chip_bytes": per_chip_bytes(cg.params_tree),
            "kv_per_chip_bytes": per_chip_bytes(kv),
            "param_global_bytes": param_global,
            "kv_global_bytes": kv_global,
        }
    with open(out_path, "w") as f:
        json.dump(results, f)


if __name__ == "__main__":
    main()
"""


def bench_lm_sharded_decode(steps, warmup):
    """Tensor-parallel sharded inference (ISSUE 20), two arms.

    Arm 1 (subprocess, 8 forced host devices): the SAME transformer LM
    decoded through `PagedDecodeStepper` unsharded and at 2-/4-way model
    parallelism — tokens/sec and per-chip param+KV bytes per arm. The
    acceptance gate is memory, not speed: per-chip bytes at 4-way must be
    <= 0.35x of 1-way (the whole point of sharding is serving a model
    bigger than one chip). On a CPU host-device mesh the collectives are
    emulated, so sharded tokens/sec measures program overhead, not real
    interconnect speedups.

    Arm 2 (fleet tier): two 2-way shard groups behind the router under
    continuous generate traffic; a rolling update walks each GROUP as one
    unit. Gates: zero client-visible errors (the other group carries
    traffic while one rolls) and zero serving-path compiles after rejoin
    (AOT fingerprints fold the mesh context)."""
    import subprocess
    import tempfile
    import threading
    import urllib.request

    from deeplearning4j_tpu.checkpoint.manager import CheckpointManager
    from deeplearning4j_tpu.models.zoo import transformer_lm
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.parallel.coordinator import Coordinator
    from deeplearning4j_tpu.serving import FleetManager, FleetRouter
    from deeplearning4j_tpu.serving.router import sum_metric_families

    tmp = tempfile.mkdtemp(prefix="bench-sharded-")

    # ---- arm 1: per-chip residency + tokens/sec at 1/2/4-way
    script = os.path.join(tmp, "sharded_worker.py")
    with open(script, "w") as f:
        f.write(_SHARDED_DECODE_WORKER)
    out_json = os.path.join(tmp, "sharded.json")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = _HERE + os.pathsep + env.get("PYTHONPATH", "")
    subprocess.run([sys.executable, script, out_json, str(steps),
                    str(warmup)], env=env, timeout=900, check=True)
    with open(out_json) as f:
        ways = json.load(f)
    one, four = ways["1"], ways["4"]
    chip = {w: r["param_per_chip_bytes"] + r["kv_per_chip_bytes"]
            for w, r in ways.items()}
    ratio4 = chip["4"] / chip["1"]

    head = _entry(
        "lm_sharded_decode_tokens_per_sec", four["tokens_per_sec"],
        "tokens/sec",
        note="4-way tensor-parallel paged decode on an emulated CPU "
             "host-device mesh; collectives are emulated, so this "
             "tracks per-step program overhead, not TPU speedup")
    head["tokens_per_sec_1way"] = round(one["tokens_per_sec"], 1)
    head["tokens_per_sec_2way"] = round(ways["2"]["tokens_per_sec"], 1)
    bytes_e = _entry(
        "lm_sharded_decode_per_chip_bytes_ratio", ratio4, "x",
        note="per-chip param+KV bytes at 4-way / 1-way; the acceptance "
             "gate is <= 0.35 (embeddings/norms replicate, attention/"
             "MLP weights and KV pages split 4 ways)")
    bytes_e["per_chip_bytes_ratio_2way"] = round(
        chip["2"] / chip["1"], 3)
    bytes_e["param_ratio_4way"] = round(
        four["param_per_chip_bytes"] / one["param_per_chip_bytes"], 3)
    bytes_e["kv_ratio_4way"] = round(
        four["kv_per_chip_bytes"] / one["kv_per_chip_bytes"], 3)
    bytes_e["per_chip_mib_4way"] = round(chip["4"] / 2 ** 20, 2)
    bytes_e["meets_0p35_gate"] = bool(ratio4 <= 0.35)

    # ---- arm 2: sharded-group rolling update under traffic
    def lm_ckpt(seed, name):
        cg = ComputationGraph(transformer_lm(
            vocab_size=32, t=16, d_model=32, n_heads=4, n_blocks=1,
            decode_cache_length=256, seed=seed)).init()
        path = os.path.join(tmp, name)
        CheckpointManager(path, async_save=False).save(cg)
        return path

    pa, pb = lm_ckpt(1, "ckpt_a"), lm_ckpt(7, "ckpt_b")
    coord = Coordinator(lost_after_s=5.0).start()
    manager = FleetManager(coord.address, pa, heartbeat_s=0.25, env=env,
                           log_dir=os.path.join(tmp, "logs"))
    router = FleetRouter(coord.address, poll_interval_s=0.1,
                         request_timeout_s=60.0, http=False).start()
    client_errors, stop = [], threading.Event()
    try:
        for group in ("ga", "gb"):
            manager.spawn_group(group, 2, extra_args=[
                "--decode-slots", "2", "--kv-cache", "paged"])
        deadline = time.monotonic() + 240.0
        while time.monotonic() < deadline:
            if sum(1 for r in router.table()
                   if r["state"] == "live" and r.get("group")) == 4:
                break
            time.sleep(0.2)
        else:
            raise RuntimeError("shard groups never became live: "
                               f"{router.table()}")

        def traffic():
            while not stop.is_set():
                try:
                    router.generate([1, 2, 3], 4, timeout_s=60.0,
                                    temperature=0.0)
                except Exception as e:
                    client_errors.append(f"{type(e).__name__}: {e}")

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        try:
            results = manager.rolling_update(pb, router, timeout_s=300.0)
        finally:
            stop.set()
            t.join(30.0)
        bad = {n: r for n, r in results.items() if not r.get("ok")}
        if bad:
            raise RuntimeError(f"sharded rolling update failed: {bad}")

        urls = [r["url"] for r in router.table() if r["state"] == "live"]

        def compiles():
            total = 0.0
            for u in urls:
                with urllib.request.urlopen(u + "/metrics",
                                            timeout=5.0) as resp:
                    total += sum_metric_families(
                        resp.read().decode(), ("dl4j_xla_compiles_total",))
            return total

        c0 = compiles()
        for _ in range(20):
            router.generate([1, 2, 3], 4, timeout_s=60.0, temperature=0.0)
        serving_compiles = compiles() - c0
    finally:
        router.stop()
        manager.stop_all()
        coord.close()

    roll_e = _entry(
        "lm_sharded_rolling_update_serving_compiles", serving_compiles,
        "compiles",
        note="serving-path XLA compiles across both shard groups AFTER a "
             "rolling update that walked each group as one unit; the AOT "
             "store folds the mesh context into program fingerprints, so "
             "the gate is exactly 0")
    roll_e["client_errors"] = len(client_errors)
    roll_e["zero_5xx"] = not client_errors
    roll_e["members_reloaded"] = len(results)
    if client_errors:
        roll_e["first_errors"] = client_errors[:3]
    return [head, bytes_e, roll_e]


def bench_resnet50(steps, warmup):
    from deeplearning4j_tpu.models.resnet import resnet50
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    batch = int(os.environ.get("BENCH_BATCH_RESNET50", "256"))
    image = int(os.environ.get("BENCH_IMAGE_RESNET50", "224"))
    conf = resnet50(n_classes=1000, image=image, dtype="bfloat16")
    # The r05 ad-hoc `x.astype(ml_dtypes.bfloat16)` in the batch maker is
    # now the policy's transfer_dtype knob: batches stay f32 host-side and
    # the staging iterators (_timed_fit reads net.dtype_policy) cast before
    # the put, so the link carries bf16 for every config that opts in.
    conf.global_conf.dtype_policy = {"name": "mixed_bfloat16",
                                     "transfer_dtype": "bfloat16"}
    net = ComputationGraph(conf).init()

    def mk(rng, b):
        x = rng.rand(b, image, image, 3).astype("float32")
        return (x, np.eye(1000, dtype="float32")[rng.randint(0, 1000, b)])

    # Headline: device-resident dataset through the public fit() path
    # (DeviceCacheDataSetIterator — see PERF.md: the tunneled transport
    # serializes host->device transfers against compute, so streaming
    # throughput measures the link, not the framework).
    sps, step_time = _timed_fit(net, mk, batch, steps, warmup, distinct=2,
                                cached=True)
    head = _entry("resnet50_imagenet_fit_samples_per_sec_per_chip", sps,
                  "samples/sec/chip")

    extra_metrics = {}
    rng = np.random.RandomState(0)
    x, y = mk(rng, batch)
    cost = _step_cost(net, x, y)
    flops = cost.get("flops")
    peak = _chip_peak_flops()
    if flops and peak:
        mfu = flops / step_time / peak
        extra_metrics["resnet50_train_mfu"] = _entry(
            "resnet50_train_mfu", mfu, "fraction_of_peak")
        from deeplearning4j_tpu import observability as obs

        obs.metrics.gauge(
            "dl4j_train_mfu",
            "Model FLOPs utilization: flops/step / step_time / chip peak"
        ).set(mfu)
    # Roofline companion to MFU: HBM bytes one step moves, and whether the
    # step is memory-bound at the chip's peak bandwidth (the fused
    # bottleneck kernel attacks exactly this term — PERF.md §27).
    _roofline_entries("resnet50_train", cost, step_time, extra_metrics)

    # Streaming variant: every batch crosses the host->device link. Few
    # steps on purpose — the shared tunnel's transfer latency varies by
    # orders of magnitude between runs (PERF.md), so this is a spot check.
    rtt_ms, mibps = _link_probe()
    stream_sps, _ = _timed_fit(net, mk, batch, 4, warmup=1, distinct=2)
    se = _entry("resnet50_stream_samples_per_sec", stream_sps,
                "samples/sec/chip", note=_LINK_NOTE)
    se["tunnel_rtt_ms"] = round(rtt_ms, 2)
    se["link_mibps"] = round(mibps, 1)
    extra_metrics["resnet50_stream_samples_per_sec"] = se
    extra_metrics["resnet50_stream_samples_per_link_mibps"] = _entry(
        "resnet50_stream_samples_per_link_mibps",
        stream_sps / max(mibps, 1e-9), "samples/sec per MiB/s")

    # uint8 shipping: bytes over the link, 0-255 -> 0-1 scaled ON DEVICE
    # inside the jitted step (PERF.md §3's halve-the-feature-bytes item;
    # 2x fewer bytes than bf16, 4x fewer than f32).
    def mk8(rng, b):
        x = (rng.rand(b, image, image, 3) * 255).astype("uint8")
        return x, np.eye(1000, dtype="float32")[rng.randint(0, 1000, b)]

    stream8_sps, _ = _timed_fit(net, mk8, batch, 4, warmup=1, distinct=2)
    e8 = _entry("resnet50_stream_uint8_samples_per_sec", stream8_sps,
                "samples/sec/chip", note=_LINK_NOTE)
    e8["vs_bf16_stream_same_run"] = round(stream8_sps / max(stream_sps,
                                                            1e-9), 2)
    extra_metrics["resnet50_stream_uint8_samples_per_sec"] = e8
    return head, extra_metrics


def bench_resnet50_bf16(steps, warmup):
    """A/B the DtypePolicy on the same model: full-f32 vs mixed_bfloat16
    with bf16 transfer staging. Reports the bf16 training throughput, the
    speedup over f32, and the measured h2d byte ratio (the transfer knob
    should halve the feature bytes on the link: f32 -> bf16)."""
    from deeplearning4j_tpu import observability as obs
    from deeplearning4j_tpu.models.resnet import resnet50
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    batch = int(os.environ.get("BENCH_BATCH_RESNET50_BF16", "64"))
    image = int(os.environ.get("BENCH_IMAGE_RESNET50_BF16", "96"))

    def mk(rng, b):
        x = rng.rand(b, image, image, 3).astype("float32")
        return (x, np.eye(1000, dtype="float32")[rng.randint(0, 1000, b)])

    def h2d_total():
        fam = obs.metrics.get_family("dl4j_host_to_device_bytes_total")
        if fam is None:
            return 0.0
        return float(sum(c.get() for c in fam.children()))

    def run_arm(policy):
        conf = resnet50(n_classes=1000, image=image, dtype="float32")
        if policy is not None:
            conf.global_conf.dtype_policy = policy
        net = ComputationGraph(conf).init()
        sps, _ = _timed_fit(net, mk, batch, steps, warmup, distinct=2,
                            cached=True)
        # Spot check for the link bytes: feed host batches straight to
        # fit() — the dispatch choke point applies the policy's transfer
        # cast, so the h2d counter sees the bytes actually shipped.
        from deeplearning4j_tpu.datasets.dataset import DataSet

        rng = np.random.RandomState(0)
        before = h2d_total()
        for _ in range(2):
            net.fit(DataSet(*mk(rng, batch)))
        per_batch = (h2d_total() - before) / 2
        return sps, per_batch

    f32_sps, f32_bytes = run_arm(None)
    bf16_sps, bf16_bytes = run_arm({"name": "mixed_bfloat16",
                                    "transfer_dtype": "bfloat16"})
    head = _entry("resnet50_bf16_fit_samples_per_sec_per_chip", bf16_sps,
                  "samples/sec/chip")
    head["vs_f32_same_run"] = round(bf16_sps / max(f32_sps, 1e-9), 2)
    head["h2d_bytes_ratio_vs_f32"] = round(
        bf16_bytes / max(f32_bytes, 1e-9), 3)
    return head


def bench_resnet50_fused_bottleneck(steps, warmup):
    """A/B the fused bottleneck-block kernel on the same fused-graph model:
    auto kernel resolution vs DL4J_TPU_KERNELS=xla forced fallback, same
    run, same data. Reports fused throughput, the fused-vs-fallback ratio,
    the impl auto-resolution actually picked (so a CPU run's ratio ~1.0 is
    self-explaining: both arms ran the XLA composite), and the roofline
    companion entries for the fused arm (PERF.md §27 — the kernel's whole
    point is the bytes term)."""
    from deeplearning4j_tpu import kernels as kern
    from deeplearning4j_tpu.models.resnet import resnet50
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    batch = int(os.environ.get("BENCH_BATCH_RESNET50_FUSED", "32"))
    image = int(os.environ.get("BENCH_IMAGE_RESNET50_FUSED", "64"))

    def mk(rng, b):
        x = rng.rand(b, image, image, 3).astype("float32")
        return (x, np.eye(1000, dtype="float32")[rng.randint(0, 1000, b)])

    def run_arm(forced_mode):
        prev = os.environ.get("DL4J_TPU_KERNELS")
        try:
            if forced_mode is None:
                os.environ.pop("DL4J_TPU_KERNELS", None)
            else:
                os.environ["DL4J_TPU_KERNELS"] = forced_mode
            kern.registry.clear_cache()
            conf = resnet50(n_classes=1000, image=image, dtype="bfloat16",
                            fused_blocks=True)
            conf.global_conf.dtype_policy = {"name": "mixed_bfloat16",
                                             "transfer_dtype": "bfloat16"}
            net = ComputationGraph(conf).init()
            sps, step_time = _timed_fit(net, mk, batch, steps, warmup,
                                        distinct=2, cached=True)
            res = kern.registry.resolve("bottleneck_block")
            return net, sps, step_time, res
        finally:
            if prev is None:
                os.environ.pop("DL4J_TPU_KERNELS", None)
            else:
                os.environ["DL4J_TPU_KERNELS"] = prev
            kern.registry.clear_cache()

    net, fused_sps, step_time, res = run_arm(None)
    _, fb_sps, _, _ = run_arm("xla")

    head = _entry("resnet50_fused_bottleneck_fit_samples_per_sec_per_chip",
                  fused_sps, "samples/sec/chip")
    head["vs_xla_fallback_same_run"] = round(fused_sps / max(fb_sps, 1e-9), 2)
    head["auto_resolved_impl"] = res.impl
    head["auto_resolved_reason"] = res.reason

    extra_metrics = {}
    rng = np.random.RandomState(0)
    x, y = mk(rng, batch)
    _roofline_entries("resnet50_fused_bottleneck", _step_cost(net, x, y),
                      step_time, extra_metrics)
    return head, extra_metrics


def bench_lm_int8_serving(steps, warmup):
    """Post-training int8 serving: quantize a checkpointed transformer LM
    (checkpoint/quantize.py), serve it through the batcher, and report
    predict p50/p99 plus the measured HBM ratio vs the f32 original and
    the output parity error."""
    import shutil
    import tempfile
    import threading

    from deeplearning4j_tpu import observability as obs
    from deeplearning4j_tpu.checkpoint import (
        quantize_checkpoint,
        restore_checkpoint,
        save_checkpoint,
    )
    from deeplearning4j_tpu.models.zoo import transformer_lm
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.serving import InferenceServer
    from deeplearning4j_tpu.serving.host import estimate_hbm_bytes

    V = 256
    net = ComputationGraph(transformer_lm(
        vocab_size=V, t=64, d_model=128, n_heads=4, n_blocks=2)).init()
    tmp = tempfile.mkdtemp(prefix="bench_int8_")
    try:
        src = os.path.join(tmp, "step_00000001")
        dst = os.path.join(tmp, "int8")
        save_checkpoint(net, src)
        quantize_checkpoint(src, dst)
        qnet = restore_checkpoint(dst)
        hbm_ratio = estimate_hbm_bytes(qnet) / max(estimate_hbm_bytes(net),
                                                   1)
        rng = np.random.RandomState(0)
        rows = rng.randint(1, V, (max(16, steps), 8)).astype(np.int32)
        ref = np.asarray(net.output(rows[:8]))
        got = np.asarray(qnet.output(rows[:8]))
        parity = float(np.max(np.abs(ref - got)))

        server = InferenceServer(qnet, default_model="lm_int8",
                                 max_batch_size=8, max_delay_ms=1.0)
        server.models.get("lm_int8").batcher.warm()
        errors = []

        def client(i):
            try:
                server.predict(rows[i:i + 1])
            except Exception as e:
                errors.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(max(16, steps))]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        server.stop()
        if errors:
            raise RuntimeError(f"lm_int8_serving bench: {errors[:3]}")
        lat = obs.metrics.get_family(
            "dl4j_serving_request_seconds").labels(
                model="lm_int8", route="predict").summarize(
                    quantiles=(0.5, 0.99))
        head = _entry("lm_int8_predict_p99_ms", lat.get("p99", 0.0) * 1e3,
                      "ms")
        head["p50_ms"] = round(lat.get("p50", 0.0) * 1e3, 2)
        head["hbm_ratio_vs_f32"] = round(hbm_ratio, 3)
        head["parity_max_abs_err"] = round(parity, 5)
        return head
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_lora_multitenant(steps, warmup):
    """Multi-tenant LoRA serving (nn/transfer.py + the serving adapter
    plumbing): ONE resident transformer-LM base + N rank-8 adapters
    served over HTTP. Reports per-adapter predict p50/p99 (client-side
    wall clock, worst tenant headline), the adapters-at-equal-HBM ratio
    (how many tenants fit in the HBM one more full base replica would
    cost — the number PERF.md §24 derives), and the compiles-after-warmup
    counter, which MUST be 0: adapter switches ride the same compiled
    programs."""
    import threading
    import urllib.request

    import jax.numpy as jnp

    from deeplearning4j_tpu.models.zoo import transformer_lm
    from deeplearning4j_tpu.nn import lora as lora_mod
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.transfer import TransferLearning
    from deeplearning4j_tpu.serving import InferenceServer
    from deeplearning4j_tpu.serving.fleet import compiles_total
    from deeplearning4j_tpu.serving.host import estimate_hbm_bytes

    V, T, N_TENANTS = 256, 64, 4
    base = ComputationGraph(transformer_lm(
        vocab_size=V, t=T, d_model=128, n_heads=4, n_blocks=2,
        decode_cache_length=128)).init()

    server = InferenceServer(base, default_model="lm_lora", warmup=True,
                             max_batch_size=8, max_delay_ms=1.0,
                             decode_slots=4, kv_cache="paged",
                             kv_page_size=16)
    rng = np.random.RandomState(0)
    tenants = [f"tenant_{i}" for i in range(N_TENANTS)]
    for name in tenants:
        tuned = TransferLearning(base).add_lora(rank=8, alpha=16).build()
        for lp in tuned.params_tree.values():
            for pname in list(lp if isinstance(lp, dict) else ()):
                if pname.endswith(lora_mod.LORA_B):
                    lp[pname] = jnp.asarray(rng.normal(
                        0.0, 0.02, lp[pname].shape).astype(np.float32))
        server.load_adapter(name, net=tuned)
    server.start()
    try:
        if not server.wait_ready(600):
            raise RuntimeError("lora_multitenant bench: warmup timed out")
        adapter_bytes = max(
            r["bytes"] for r in server.models.get("lm_lora").adapter_rows())
        base_hbm = estimate_hbm_bytes(base)

        c0 = compiles_total()
        rows = rng.randint(1, V, (8, 8)).tolist()
        per_tenant = max(16, steps)
        lats = {name: [] for name in tenants}
        errors = []

        def client(name, i):
            body = json.dumps({"data": [rows[i % len(rows)]],
                               "adapter": name}).encode()
            req = urllib.request.Request(
                server.url + "/predict", body,
                {"Content-Type": "application/json"})
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=120) as r:
                    r.read()
                lats[name].append(time.perf_counter() - t0)
            except Exception as e:
                errors.append(f"{name}: {type(e).__name__}: {e}")

        # Bounded client pool: the stdlib HTTP server's accept backlog
        # drops connections under a full thundering herd.
        work = [(name, i) for i in range(per_tenant) for name in tenants]
        lock = threading.Lock()

        def worker():
            while True:
                with lock:
                    if not work:
                        return
                    name, i = work.pop()
                client(name, i)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        # One paged generate per tenant: the decode path must also ride
        # the warmed programs (grouped multi-adapter decode rounds).
        for name in tenants:
            body = json.dumps({"prompt_ids": [1, 2, 3], "n_steps": 8,
                               "temperature": 0.0,
                               "adapter": name}).encode()
            req = urllib.request.Request(
                server.url + "/generate", body,
                {"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                r.read()
        compiles = compiles_total() - c0
        if errors:
            raise RuntimeError(f"lora_multitenant bench: {errors[:3]}")
        if compiles:
            raise RuntimeError(
                f"lora_multitenant bench: {compiles} serving-path compiles "
                "after warmup (must be 0 — adapter switches may not "
                "recompile)")

        p99s = {n: float(np.percentile(ls, 99) * 1e3)
                for n, ls in lats.items()}
        p50s = {n: float(np.percentile(ls, 50) * 1e3)
                for n, ls in lats.items()}
        head = _entry("lora_multitenant_predict_p99_ms",
                      max(p99s.values()), "ms",
                      note=f"{N_TENANTS} tenants x {per_tenant} reqs, "
                           "worst tenant")
        head["p50_ms"] = round(max(p50s.values()), 2)
        head["adapters_resident"] = N_TENANTS
        head["adapter_bytes"] = int(adapter_bytes)
        head["adapters_per_base_hbm"] = int(base_hbm // adapter_bytes)
        head["adapter_hbm_ratio"] = round(
            N_TENANTS * adapter_bytes / max(base_hbm, 1), 4)
        head["compiles_after_warmup"] = int(compiles)
        return head
    finally:
        server.stop()


_ELASTIC_WORKER = """
import json, os, sys
wid = sys.argv[1]; addr = sys.argv[2]; root = sys.argv[3]; out = sys.argv[4]
is_host = sys.argv[5] == "host"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.elastic import ElasticTrainer
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

conf = (NeuralNetConfiguration.builder()
        .seed(7).learning_rate(0.05).updater("sgd")
        .list()
        .layer(DenseLayer(n_out=64, activation="tanh"))
        .layer(OutputLayer(n_out=8, activation="softmax",
                           loss_function="mcxent"))
        .set_input_type(InputType.feed_forward(32))
        .build())

def shard_fn(step, rank, world):
    rng = np.random.RandomState(1000 + step)
    X = rng.randn(64, 32).astype(np.float32)
    Y = np.eye(8, dtype=np.float32)[rng.randint(0, 8, 64)]
    n = X.shape[0] // world
    return DataSet(X[rank*n:(rank+1)*n], Y[rank*n:(rank+1)*n])

net = MultiLayerNetwork(conf).init()
trainer = ElasticTrainer(
    ParallelWrapper(net, workers=1),
    coordinator_address=addr, worker_id=wid, expected_world=2,
    checkpoint_root=os.path.join(root, "ckpt"), save_every=2,
    host_coordinator=is_host, heartbeat_s=0.25, join_grace_s=60.0,
    collective_timeout_s=20.0, lost_after_s=1.0)
result = trainer.run(shard_fn, steps=int(sys.argv[6]))
with open(out, "w") as f:
    json.dump({"status": result.status, "step": result.step,
               "restarts": result.restarts,
               "recoveries_s": list(result.recoveries_s)}, f)
"""


def bench_elastic_recovery(steps, warmup):
    """Time-to-recover on a 2-process CPU cluster (parallel/elastic.py):
    worker b is killed mid-run by a deterministic fault plan; the metric
    is the survivor's fault-detected -> training-resumed latency (the
    same quantity `dl4j_elastic_recovery_seconds` observes). Includes
    heartbeat-lease expiry (lost_after_s=1.0 here), eviction, re-join,
    checkpoint restore and the first post-restart step."""
    import socket
    import subprocess
    import tempfile

    kill_at = max(3, min(6, steps // 2))
    total = kill_at + 4
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"127.0.0.1:{port}"
    tmp = tempfile.mkdtemp(prefix="bench-elastic-")
    script = os.path.join(tmp, "worker.py")
    with open(script, "w") as f:
        f.write(_ELASTIC_WORKER)
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    # `worker` is the coordinator RANK: the peer ("b", second joiner) is 1.
    env["DL4J_TPU_FAULT_PLAN"] = json.dumps(
        [{"kind": "kill", "step": kill_at, "worker": 1}])
    env["PYTHONPATH"] = _HERE + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, script, wid, addr, tmp,
         os.path.join(tmp, f"out-{wid}.json"), role, str(total)],
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT, env=env)
        for wid, role in (("a", "host"), ("b", "peer"))]
    try:
        for p in procs:
            p.wait(timeout=300)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    with open(os.path.join(tmp, "out-a.json")) as f:
        survivor = json.load(f)
    recoveries = survivor.get("recoveries_s") or []
    if survivor.get("status") != "finished" or not recoveries:
        return _entry("elastic_recovery_seconds", 0.0, "seconds",
                      note=f"recovery did not complete: {survivor}")
    return _entry(
        "elastic_recovery_seconds", float(recoveries[0]), "seconds",
        note=(f"2-process CPU cluster, worker killed at step {kill_at}; "
              "detection (1.0s heartbeat lease) + evict + re-join + "
              "restore + first step. Lower is better; vs_baseline < 1 "
              "is an improvement."))


def bench_fleet_slo(steps, warmup):
    """Serving-fleet SLO drill (serving/fleet.py + serving/router.py):
    a 3-replica CPU fleet behind the least-loaded failover router. A
    deterministic fault plan SIGKILLs replica 0 mid-run (1.0s lease) and
    a rolling update re-deploys a second checkpoint across the survivors
    while client traffic continues. Reports non-shed availability (the
    acceptance floor is 0.99), mean failover latency, and the compiles
    the rollout performed — all of which happen on the DRAINED replica
    (AOT warm before rejoin), never on the serving path."""
    import tempfile
    import threading

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration,
                                    observability as obs)
    from deeplearning4j_tpu.checkpoint.manager import CheckpointManager
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel.coordinator import Coordinator
    from deeplearning4j_tpu.serving import FleetManager, FleetRouter

    def mlp(seed):
        return MultiLayerNetwork(
            (NeuralNetConfiguration.builder()
             .seed(seed).learning_rate(0.1).weight_init("xavier")
             .list()
             .layer(DenseLayer(n_out=4, activation="tanh"))
             .layer(OutputLayer(n_out=2, activation="softmax",
                                loss_function="mcxent"))
             .set_input_type(InputType.feed_forward(3))
             .build())).init()

    tmp = tempfile.mkdtemp(prefix="bench-fleet-")
    path_a = os.path.join(tmp, "ckpt-a")
    path_b = os.path.join(tmp, "ckpt-b")
    CheckpointManager(path_a, async_save=False).save(mlp(1))
    CheckpointManager(path_b, async_save=False).save(mlp(7))

    n_req = max(120, steps * 4)
    kill_at = max(8, n_req // 12)
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _HERE + os.pathsep + env.get("PYTHONPATH", "")
    env["DL4J_TPU_FAULT_PLAN"] = json.dumps(
        [{"kind": "kill_replica", "step": kill_at, "worker": 0}])

    coord = Coordinator(lost_after_s=1.0).start()
    manager = FleetManager(coord.address, path_a, heartbeat_s=0.25,
                           env=env, log_dir=os.path.join(tmp, "logs"))
    router = FleetRouter(coord.address, poll_interval_s=0.1,
                         request_timeout_s=10.0, attempt_timeout_s=0.75,
                         quarantine_s=4.0, http=False).start()
    ok = failed = 0
    update = {}
    try:
        for _ in range(3):
            manager.spawn()
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if sum(1 for r in router.table()
                   if r["state"] == "live") == 3:
                break
            time.sleep(0.1)
        else:
            raise RuntimeError("fleet never reached 3 live replicas")

        rolled = [None]

        def roll():
            rolled[0] = manager.rolling_update(path_b, router,
                                               timeout_s=120.0)

        x = [[0.1, -0.2, 0.3]]
        roller = None
        for i in range(n_req):
            if i == n_req // 2:
                roller = threading.Thread(target=roll)
                roller.start()
            try:
                router.predict(x, timeout_s=10.0)
                ok += 1
            except Exception:
                failed += 1
        if roller is not None:
            roller.join(180.0)
        update = rolled[0] or {}
    finally:
        try:
            router.stop()
        finally:
            manager.stop_all()
            coord.close()

    counts = router.counts()
    shed = int(counts.get("shed", 0))
    availability = ok / max(1, n_req - shed)
    fam = obs.metrics.get_family("dl4j_router_failover_seconds")
    fo_mean, fo_count = 0.0, 0
    if fam is not None:
        for child in fam.children():
            _, _, fo_sum, fo_count = child.histogram_state()
            fo_mean = fo_sum / fo_count if fo_count else 0.0
    rollout_compiles = sum(int(r.get("compiled_during_warm", 0))
                           for r in update.values()
                           if isinstance(r, dict))
    head = _entry(
        "fleet_availability_nonshed", availability, "ratio",
        note=(f"3 CPU replicas, replica 0 SIGKILLed at its request "
              f"#{kill_at}, rolling update mid-run; {ok}/{n_req} ok, "
              f"{shed} shed, {failed - shed} failed. Floor is 0.99."))
    head["rolled_replicas"] = sum(
        1 for r in update.values() if isinstance(r, dict) and r.get("ok"))
    head["rollout_compiles_while_drained"] = rollout_compiles
    fo = _entry("fleet_failover_seconds", fo_mean, "seconds",
                note=(f"mean of {fo_count} failovers (lease 1.0s, "
                      "attempt timeout 0.75s); acceptance is < 1s."))
    return [head, fo]


def bench_obs_federation(steps, warmup):
    """Observability-plane overhead drill (observability/federation.py):
    a 2-replica CPU fleet behind the failover router, mean predict
    latency with NO federation traffic vs with a background aggregator
    federating every member's /metrics every ~2 seconds (7.5x the
    Prometheus default scrape cadence) and the merged /api/trace
    timeline every ~10 seconds (member rings hold ~30s+ of history, so
    nothing is lost at that cadence).

    Measurement design: single-core VM latency drifts a few percent
    between arms minutes apart, which would swamp a <= 2% effect — so
    requests run in PAIRED adjacent blocks (scraper-idle block, then a
    same-size block containing exactly one federation cycle, which at
    ~2s per block IS the target cadence; every 5th pair also federates
    traces). The headline is the median of the paired per-block p50
    differences; block pairs seconds apart share the same drift, so it
    cancels. The whole observability plane shares one <= 2% latency
    budget (PERF.md §15, §22); federation must fit inside it because
    scrapes are incremental (?since= trace cursors) over keep-alive
    connections and ride a separate HTTP thread on each replica, never
    the dispatch path."""
    import tempfile
    import threading

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.checkpoint.manager import CheckpointManager
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel.coordinator import Coordinator
    from deeplearning4j_tpu.serving import FleetManager, FleetRouter

    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(1).learning_rate(0.1).weight_init("xavier")
         .list()
         .layer(DenseLayer(n_out=4, activation="tanh"))
         .layer(OutputLayer(n_out=2, activation="softmax",
                            loss_function="mcxent"))
         .set_input_type(InputType.feed_forward(3))
         .build())).init()
    tmp = tempfile.mkdtemp(prefix="bench-obs-fed-")
    ckpt = os.path.join(tmp, "ckpt")
    CheckpointManager(ckpt, async_save=False).save(net)

    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _HERE + os.pathsep + env.get("PYTHONPATH", "")

    coord = Coordinator(lost_after_s=5.0).start()
    manager = FleetManager(coord.address, ckpt, heartbeat_s=0.25,
                           env=env, log_dir=os.path.join(tmp, "logs"))
    router = FleetRouter(coord.address, poll_interval_s=0.1,
                         request_timeout_s=10.0, attempt_timeout_s=2.0,
                         quarantine_s=4.0, http=False).start()
    x = [[0.1, -0.2, 0.3]]
    pairs = 8
    block = max(400, steps * 10)

    def timed(n):
        lat = []
        for _ in range(n):
            t0 = time.perf_counter()
            router.predict(x, timeout_s=10.0)
            lat.append(time.perf_counter() - t0)
        lat.sort()
        return {"mean": sum(lat) / n, "p50": lat[n // 2],
                "p99": lat[int(0.99 * (n - 1))]}

    def median(vals):
        vals = sorted(vals)
        mid = len(vals) // 2
        return (vals[mid] if len(vals) % 2
                else (vals[mid - 1] + vals[mid]) / 2.0)

    try:
        manager.spawn()
        manager.spawn()
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if sum(1 for r in router.table()
                   if r["state"] == "live") == 2:
                break
            time.sleep(0.1)
        else:
            raise RuntimeError("fleet never reached 2 live replicas")
        for _ in range(max(10, warmup)):
            router.predict(x, timeout_s=10.0)

        # Warm the aggregator BEFORE the baseline arm so one-time costs
        # (coordinator discovery, HTTP connection setup, import of the
        # merge path) don't land inside the federated measurement.
        agg = router.aggregator()
        agg.federate_metrics()
        agg.federate_trace()

        # One steady-state federation cycle, timed (cursors warm).
        t0 = time.perf_counter()
        agg.federate_metrics()
        agg.federate_trace()
        scrape_s = time.perf_counter() - t0

        diffs_p50, diffs_mean = [], []
        offs, ons = [], []
        for k in range(pairs):
            off = timed(block)

            def one_cycle(do_trace=(k % 5 == 0)):
                try:
                    agg.federate_metrics()
                    if do_trace:
                        agg.federate_trace()
                except Exception:
                    pass

            th = threading.Thread(target=one_cycle, daemon=True)
            th.start()
            on = timed(block)
            th.join(30.0)
            offs.append(off)
            ons.append(on)
            diffs_p50.append((on["p50"] - off["p50"]) / off["p50"] * 100)
            diffs_mean.append(
                (on["mean"] - off["mean"]) / off["mean"] * 100)
    finally:
        try:
            router.stop()
        finally:
            manager.stop_all()
            coord.close()

    overhead_pct = median(diffs_p50)
    mean_pct = median(diffs_mean)
    base_p50 = median([o["p50"] for o in offs]) * 1e3
    fed_p50 = median([o["p50"] for o in ons]) * 1e3
    base_p99 = median([o["p99"] for o in offs]) * 1e3
    fed_p99 = median([o["p99"] for o in ons]) * 1e3
    head = _entry(
        "obs_federation_overhead_pct", overhead_pct, "percent",
        note=(f"median paired per-block p50 overhead; 2 CPU replicas, "
              f"{pairs} pairs x {block} predicts/block, one federation "
              f"cycle per ON block (metrics every pair, traces every "
              f"5th); p50 {base_p50:.2f} -> {fed_p50:.2f} ms, mean "
              f"diff {mean_pct:+.1f}%, p99 {base_p99:.2f} -> "
              f"{fed_p99:.2f} ms; budget is <= 2%."))
    scr = _entry(
        "obs_federation_scrape_seconds", scrape_s, "seconds",
        note="one steady-state fleet-wide /metrics + /api/trace "
             "federation (incremental ?since= cursors over keep-alive "
             "connections; every member scraped + merged).")
    return [head, scr]


def main():
    # Compile-time accounting for the self-attribution snapshot in _emit():
    # every XLA compile during the run lands in dl4j_xla_compile_* counters.
    from deeplearning4j_tpu import observability as obs

    obs.install_jax_compile_hook()
    steps = int(os.environ.get("BENCH_STEPS", "30"))
    warmup = int(os.environ.get("BENCH_WARMUP", "5"))
    configs = os.environ.get(
        "BENCH_CONFIGS",
        "resnet50,resnet50_bf16,resnet50_fused_bottleneck,"
        "lenet,char_rnn,char_rnn_fused_lstm,"
        "lenet_step,lenet_superstep,fused_update_superstep,"
        "lenet_cold_warm,lenet_pipeline_overlap,word2vec,vgg16,"
        "flash_attn,flash_tri,transformer,"
        "serving_slo,lm_int8_serving,lora_multitenant,obs_overhead,"
        "slo_ledger,locktrace_overhead,elastic_recovery,"
        "fleet_slo,obs_federation,decode_paged,lm_sharded_decode"
    ).split(",")

    head, extra = None, {}
    if "resnet50" in configs:
        head, extra = bench_resnet50(max(10, steps // 3), warmup)
    if "lenet" in configs:
        # >= 200 cached batches: at ~0.15 ms/step a 30-step run is mostly
        # the tail sync RTT over the tunnel (same effect as char_rnn,
        # PERF.md §4) — r4 measured 103k..181k samples/s run-to-run until
        # the timed window dwarfed the RTT.
        for e in bench_lenet(max(200, steps), warmup):
            extra[e["metric"]] = e
    if "char_rnn" in configs:
        # >= 80 timed batches: at ~4.4 ms/batch a short run can't amortize
        # the tail sync RTT over the tunneled transport (PERF.md §4).
        e = bench_char_rnn(max(80, steps), warmup)
        extra[e["metric"]] = e
    if "lenet_step" in configs:
        e = bench_lenet_step(max(200, steps), warmup)
        extra[e["metric"]] = e
    if "lenet_superstep" in configs:
        # Same >=200-step floor as the other lenet configs: the compared
        # loops must both dwarf the tail sync RTT (PERF.md §4).
        for e in bench_lenet_superstep(max(200, steps), warmup):
            extra[e["metric"]] = e
    if "char_rnn_fused_lstm" in configs:
        # Same >=80-batch floor as char_rnn (tail sync RTT, PERF.md §4).
        for e in bench_char_rnn_fused_lstm(max(80, steps), warmup):
            extra[e["metric"]] = e
    if "fused_update_superstep" in configs:
        for e in bench_fused_update_superstep(max(200, steps), warmup):
            extra[e["metric"]] = e
    if "lenet_cold_warm" in configs:
        e = bench_lenet_cold_vs_warm(steps, warmup)
        extra[e["metric"]] = e
    if "lenet_pipeline_overlap" in configs:
        # Same >=200-step floor as the other lenet streaming configs: both
        # compared arms must dwarf the tail sync RTT (PERF.md §4).
        for e in bench_lenet_pipeline_overlap(max(200, steps), warmup):
            extra[e["metric"]] = e
    if "word2vec" in configs:
        e = bench_word2vec(steps, warmup)
        extra[e["metric"]] = e
    if "vgg16" in configs:
        e = bench_vgg16_dp(max(8, steps // 3), warmup)
        extra[e["metric"]] = e
    if "flash_attn" in configs:
        e = bench_flash_attention(steps, warmup)
        extra[e["metric"]] = e
    if "flash_tri" in configs:
        e = bench_flash_triangular(steps, warmup)
        extra[e["metric"]] = e
    if "transformer" in configs:
        e = bench_transformer(steps, warmup)
        extra[e["metric"]] = e
    if "resnet50_bf16" in configs:
        e = bench_resnet50_bf16(max(8, steps // 3), warmup)
        extra[e["metric"]] = e
    if "resnet50_fused_bottleneck" in configs:
        e, more = bench_resnet50_fused_bottleneck(max(8, steps // 3), warmup)
        extra[e["metric"]] = e
        extra.update(more)
    if "serving_slo" in configs:
        for e in bench_serving_slo(steps, warmup):
            extra[e["metric"]] = e
    if "lm_int8_serving" in configs:
        e = bench_lm_int8_serving(steps, warmup)
        extra[e["metric"]] = e
    if "obs_overhead" in configs:
        e = bench_obs_overhead(steps, warmup)
        extra[e["metric"]] = e
    if "slo_ledger" in configs:
        e = bench_slo_ledger(steps, warmup)
        extra[e["metric"]] = e
    if "locktrace_overhead" in configs:
        e = bench_locktrace_overhead(steps, warmup)
        extra[e["metric"]] = e
    if "elastic_recovery" in configs:
        e = bench_elastic_recovery(steps, warmup)
        extra[e["metric"]] = e
    if "fleet_slo" in configs:
        for e in bench_fleet_slo(steps, warmup):
            extra[e["metric"]] = e
    if "obs_federation" in configs:
        for e in bench_obs_federation(steps, warmup):
            extra[e["metric"]] = e
    if "decode_paged" in configs:
        for e in bench_decode_paged(steps, warmup):
            extra[e["metric"]] = e
    if "lm_sharded_decode" in configs:
        for e in bench_lm_sharded_decode(steps, warmup):
            extra[e["metric"]] = e
    if "lora_multitenant" in configs:
        e = bench_lora_multitenant(steps, warmup)
        extra[e["metric"]] = e
    if head is None:  # resnet50 excluded: promote the first extra metric
        if not extra:
            _emit({
                "metric": "bench_config_error", "value": 0, "unit": "none",
                "vs_baseline": 0,
                "error": f"no recognized config in BENCH_CONFIGS={configs}"})
            return 1
        first = next(iter(extra))
        head = extra.pop(first)
    out = dict(head)
    out["extra"] = {k: {kk: vv for kk, vv in v.items() if kk != "metric"}
                    for k, v in extra.items()}
    _emit(out)


def _emit(out: dict) -> None:
    # Self-attribution (ISSUE 2): step-latency/dispatch summaries, compile
    # totals, jit-cache hits, MFU — so a BENCH round explains its own time.
    try:
        from deeplearning4j_tpu import observability as obs

        out["observability"] = obs.bench_snapshot()
    except Exception:
        pass
    print(json.dumps(out))
    # The full record also lands in a file: stdout-tail capture has
    # truncated the JSON before (BENCH_r05.json came back `parsed: null`,
    # losing the headline ResNet-50 number), so the driver reads this.
    with open(os.path.join(_HERE, "BENCH_out.json"), "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")


if __name__ == "__main__":
    sys.exit(main())
