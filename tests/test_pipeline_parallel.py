"""Pipeline-parallel tests (8-device virtual CPU mesh).

The reference has no pipeline parallelism (SURVEY.md §2.3); these cover the
TPU-native extension: the GPipe microbatch schedule in
`parallel/pipeline.py` must be exactly a sequential composition of its
stages — values AND gradients — and must train.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
    unstack_stage_params,
)


def dense_stage(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def make_stages(rng, s, f):
    return [{"w": jnp.asarray(rng.randn(f, f) * 0.3),
             "b": jnp.asarray(rng.randn(f) * 0.1)} for _ in range(s)]


def sequential(stages, x):
    for p in stages:
        x = dense_stage(p, x)
    return x


@pytest.fixture(params=[(1, 8), (2, 4)], ids=["pipe8", "data2xpipe4"])
def mesh(request):
    dp, pp = request.param
    return mesh_mod.create_mesh((dp, pp), axis_names=("data", "pipe"))


class TestPipelineApply:
    @pytest.mark.parametrize("n_micro", [4, 8])
    def test_matches_sequential(self, rng, mesh, n_micro):
        s = mesh.shape["pipe"]
        f, b = 6, 16
        stages = make_stages(rng, s, f)
        x = jnp.asarray(rng.randn(b, f))
        got = pipeline_apply(dense_stage, stack_stage_params(stages), x,
                             mesh, n_microbatches=n_micro)
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(sequential(stages, x)),
                                   rtol=1e-10, atol=1e-12)

    def test_grads_match_sequential(self, rng, mesh):
        s = mesh.shape["pipe"]
        f, b = 5, 8
        stages = make_stages(rng, s, f)
        stacked = stack_stage_params(stages)
        x = jnp.asarray(rng.randn(b, f))
        tgt = jnp.asarray(rng.randn(b, f))

        def loss_pipe(p, x):
            return jnp.mean(
                (pipeline_apply(dense_stage, p, x, mesh,
                                n_microbatches=4) - tgt) ** 2)

        def loss_seq(stages, x):
            return jnp.mean((sequential(stages, x) - tgt) ** 2)

        gp, gx = jax.grad(loss_pipe, argnums=(0, 1))(stacked, x)
        gs, gx_ref = jax.grad(loss_seq, argnums=(0, 1))(stages, x)
        for i, ref in enumerate(gs):
            got = jax.tree.map(lambda a, i=i: a[i], gp)
            for k in ref:
                np.testing.assert_allclose(np.asarray(got[k]),
                                           np.asarray(ref[k]),
                                           rtol=1e-8, atol=1e-10)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_ref),
                                   rtol=1e-8, atol=1e-10)

    def test_trains(self, rng, mesh):
        # A pipelined 4-8 stage tanh MLP fits a random-projection target.
        s = mesh.shape["pipe"]
        f, b = 6, 32
        stacked = stack_stage_params(make_stages(rng, s, f))
        x = jnp.asarray(rng.randn(b, f))
        w_true = jnp.asarray(rng.randn(f, f) * 0.5)
        tgt = jnp.tanh(x @ w_true)

        @jax.jit
        def step(p):
            def loss(p):
                out = pipeline_apply(dense_stage, p, x, mesh,
                                     n_microbatches=4)
                return jnp.mean((out - tgt) ** 2)
            l, g = jax.value_and_grad(loss)(p)
            return jax.tree.map(lambda a, ga: a - 0.5 * ga, p, g), l

        p = stacked
        l0 = None
        for i in range(150):
            p, l = step(p)
            # Sync each iteration: unbounded queuing of collective programs
            # aborts the virtual-CPU backend.
            l = float(l)
            l0 = l if l0 is None else l0
        assert l < 0.5 * l0, (l0, l)

    def test_round_trip_stack(self, rng, mesh):
        s = mesh.shape["pipe"]
        stages = make_stages(rng, s, 4)
        back = unstack_stage_params(stack_stage_params(stages), s)
        for a, b_ in zip(stages, back):
            np.testing.assert_array_equal(np.asarray(a["w"]),
                                          np.asarray(b_["w"]))

    def test_rejects_indivisible_microbatch(self, rng, mesh):
        s = mesh.shape["pipe"]
        stages = make_stages(rng, s, 4)
        x = jnp.asarray(rng.randn(10, 4))
        with pytest.raises(ValueError, match="divisible"):
            pipeline_apply(dense_stage, stack_stage_params(stages), x, mesh,
                           n_microbatches=3)

    def test_rejects_stage_count_mesh_mismatch(self, rng, mesh):
        s = mesh.shape["pipe"]
        stages = make_stages(rng, 2 * s, 4)  # would silently drop stages
        x = jnp.asarray(rng.randn(8, 4))
        with pytest.raises(ValueError, match="one stage per device"):
            pipeline_apply(dense_stage, stack_stage_params(stages), x, mesh,
                           n_microbatches=4)

    def test_no_nan_grads_from_bubble(self, rng, mesh):
        # A stage_fn with a non-finite derivative at garbage inputs must not
        # poison gradients via the warm-up/drain bubble (where-grad trap).
        s = mesh.shape["pipe"]
        f, b = 4, 8
        stages = [{"w": jnp.asarray(rng.randn(f, f) * 0.3),
                   "b": jnp.zeros(f)} for _ in range(s)]

        def sqrt_stage(params, x):
            return jnp.sqrt(jnp.abs(x @ params["w"] + params["b"])) + 1e-3

        x = jnp.asarray(np.abs(rng.randn(b, f)) + 0.5)
        g = jax.grad(lambda p: jnp.sum(pipeline_apply(
            sqrt_stage, p, x, mesh, n_microbatches=4)))(
                stack_stage_params(stages))
        assert all(np.all(np.isfinite(np.asarray(v)))
                   for v in jax.tree.leaves(g))


def test_shard_inputs_matches_replicated(rng):
    """shard_inputs=True (microbatch stack sharded over the pipe axis,
    owner-psum feed) computes the identical pipeline output and
    gradients as the replicated-input default."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.parallel import mesh as mesh_mod
    from deeplearning4j_tpu.parallel.pipeline import (
        pipeline_apply, stack_stage_params,
    )

    S, M, f = 4, 8, 6
    mesh = mesh_mod.create_mesh((2, S), axis_names=("data", "pipe"))
    stages = stack_stage_params([
        {"w": jnp.asarray(rng.rand(f, f).astype("float32") * 0.3),
         "b": jnp.zeros((f,), "float32")} for _ in range(S)])
    x = jnp.asarray(rng.rand(16, f).astype("float32"))

    def stage_fn(p, h):
        return jnp.tanh(h @ p["w"] + p["b"])

    def loss(p, x, shard):
        out = pipeline_apply(stage_fn, p, x, mesh, n_microbatches=M,
                             shard_inputs=shard)
        return jnp.mean(out ** 2), out

    (l0, o0), g0 = jax.value_and_grad(
        lambda p, x: loss(p, x, False), argnums=(0, 1),
        has_aux=True)(stages, x)
    (l1, o1), g1 = jax.value_and_grad(
        lambda p, x: loss(p, x, True), argnums=(0, 1),
        has_aux=True)(stages, x)
    np.testing.assert_allclose(np.asarray(o0), np.asarray(o1),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    # B=12 IS divisible by M=6, so the error must come from the
    # shard_inputs M % S guard, not the batch check.
    with pytest.raises(ValueError, match="shard_inputs"):
        pipeline_apply(stage_fn, stages, x[:12], mesh, n_microbatches=6,
                       shard_inputs=True)
