"""Transfer learning, LoRA adapters, adapter checkpoints, and
multi-tenant serving (ISSUE 16).

Covers the freeze contract (frozen leaves bitwise-unchanged AND zero
updater state), the LoRA fine-tuning loss trend against a full
fine-tune, adapter checkpoint round-trip + base-fingerprint refusal,
and the serving acceptance: one resident base + two LoRA tenants served
over HTTP (predict AND paged generate) with distinct outputs and zero
serving-path XLA compiles after warmup.
"""

import json
import threading
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.nn import lora as lora_mod
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.transfer import TransferLearning, _layer_items
from deeplearning4j_tpu.checkpoint import adapters as adapters_mod


def _mln(seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(0.1).updater("adam")
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _batch(seed=0, b=32):
    rng = np.random.RandomState(seed)
    x = rng.randn(b, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, b)]
    return DataSet(x, y)


def _leaves(net):
    return {(lk, name): np.asarray(a)
            for lk, lp in net.params_tree.items()
            for name, a in (lp.items() if isinstance(lp, dict) else ())}


# ------------------------------------------------------------- freezing


class TestFreeze:
    def test_frozen_leaves_bitwise_unchanged_and_no_updater_state(self):
        base = _mln()
        tuned = TransferLearning(base).freeze_up_to(1).build()
        frozen_keys = tuned.layer_keys[:2]

        # Frozen layers carry NO updater state: their opt entry is ().
        for lk in frozen_keys:
            assert tuned.opt_state[lk] == ()
        assert tuned.opt_state[tuned.layer_keys[2]] != ()

        before = _leaves(tuned)
        ds = _batch()
        for _ in range(5):
            tuned.fit(ds)
        after = _leaves(tuned)

        for (lk, name), arr in before.items():
            if lk in frozen_keys:
                np.testing.assert_array_equal(
                    arr, after[(lk, name)],
                    err_msg=f"frozen leaf {lk}/{name} moved")
        # The head actually trained.
        head = tuned.layer_keys[2]
        assert any(not np.array_equal(before[(head, n)], after[(head, n)])
                   for (lk, n) in before if lk == head)

    def test_source_net_is_never_mutated(self):
        base = _mln()
        before = _leaves(base)
        tuned = TransferLearning(base).freeze_up_to(0).build()
        tuned.fit(_batch())
        for key, arr in _leaves(base).items():
            np.testing.assert_array_equal(before[key], arr)


# ----------------------------------------------------------------- lora


class TestLoRATraining:
    def test_lora_loss_trend_vs_full_finetune(self):
        base = _mln()
        ds = _batch()

        full = TransferLearning(base).build()
        lora = TransferLearning(base).add_lora(rank=2, alpha=4).build()

        s_full0, s_lora0 = full.score(ds), lora.score(ds)
        for _ in range(30):
            full.fit(ds)
            lora.fit(ds)
        # Both fine-tunes learn; the rank-2 adapter tracks the full
        # fine-tune's trend even though it trains a fraction of the params.
        assert full.score(ds) < s_full0
        assert lora.score(ds) < s_lora0

        # LoRA training moved ONLY the adapter factors: every base leaf
        # (of adapted layers) is bitwise the source net's.
        for (lk, name), arr in _leaves(lora).items():
            if name.endswith((lora_mod.LORA_A, lora_mod.LORA_B)):
                continue
            if name.endswith(lora_mod.LORA_SCALE):
                continue
            np.testing.assert_array_equal(
                arr, np.asarray(base.params_tree[lk][name]),
                err_msg=f"LoRA fine-tune moved base leaf {lk}/{name}")
        # ... and the B factors left their zero init (they did train).
        assert any(np.any(arr != 0) for (lk, name), arr in
                   _leaves(lora).items()
                   if name.endswith(lora_mod.LORA_B))

    def test_lora_layers_have_no_base_updater_state(self):
        lora = TransferLearning(_mln()).add_lora(rank=2).build()
        # Adapted layers keep updater state only for the a/b factors.
        import jax

        for lk in lora.layer_keys:
            flat = jax.tree_util.tree_leaves(lora.opt_state[lk])
            lp = lora.params_tree[lk]
            n_trainable = sum(a.size for name, a in lp.items()
                              if name.endswith((lora_mod.LORA_A,
                                                lora_mod.LORA_B)))
            moments = sum(a.size for a in flat
                          if hasattr(a, "size") and a.ndim > 0)
            assert moments <= 2 * n_trainable + 2


# ---------------------------------------------------- adapter checkpoint


class TestAdapterCheckpoint:
    def test_round_trip_is_bitwise(self, tmp_path):
        base = _mln()
        tuned = TransferLearning(base).add_lora(rank=2, alpha=8).build()
        tuned.fit(_batch())
        path = str(tmp_path / "tenant")
        adapters_mod.save_adapter(tuned, path, name="tenant-a")

        assert adapters_mod.is_adapter_checkpoint(path)
        meta = adapters_mod.adapter_meta(path)
        assert meta["name"] == "tenant-a"
        assert meta["rank"] == 2

        loaded = adapters_mod.load_adapter(path, base_net=base)
        want = lora_mod.extract_adapter(tuned.params_tree)
        assert set(loaded) == set(want)
        for lk in want:
            for name, arr in want[lk].items():
                np.testing.assert_array_equal(np.asarray(arr),
                                              np.asarray(loaded[lk][name]))

    def test_mismatched_base_is_refused(self, tmp_path):
        from deeplearning4j_tpu.checkpoint.array_store import CheckpointError

        tuned = TransferLearning(_mln(seed=1)).add_lora(rank=2).build()
        path = str(tmp_path / "tenant")
        adapters_mod.save_adapter(tuned, path, name="t")
        other = _mln(seed=2)
        with pytest.raises(CheckpointError, match="refusing"):
            adapters_mod.load_adapter(path, base_net=other)
        # Without a base to verify against, loading is allowed.
        assert adapters_mod.load_adapter(path)

    def test_fingerprint_ignores_adapter_leaves(self):
        base = _mln()
        tuned = TransferLearning(base).add_lora(rank=2).build()
        assert (adapters_mod.base_fingerprint(base)
                == adapters_mod.base_fingerprint(tuned))

    def test_save_without_lora_leaves_is_an_error(self, tmp_path):
        from deeplearning4j_tpu.checkpoint.array_store import CheckpointError

        with pytest.raises(CheckpointError, match="LoRA"):
            adapters_mod.save_adapter(_mln(), str(tmp_path / "x"))


# ------------------------------------------------- multi-tenant serving


def _post(url, route, payload, timeout=60):
    req = urllib.request.Request(url + route, json.dumps(payload).encode(),
                                 {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _tenant_net(base, seed):
    """A deterministic, strongly-distinct tenant: built via the public
    TransferLearning path, with the adapter factors overwritten by a
    seeded draw (training to divergence would dominate test runtime)."""
    tuned = TransferLearning(base).add_lora(rank=2, alpha=4).build()
    rng = np.random.RandomState(seed)
    for lk, lp in tuned.params_tree.items():
        for name in list(lp if isinstance(lp, dict) else ()):
            if name.endswith((lora_mod.LORA_A, lora_mod.LORA_B)):
                lp[name] = jnp.asarray(
                    rng.normal(0.0, 0.5, lp[name].shape).astype(np.float32))
    return tuned


class TestMultiTenantServing:
    def test_two_adapters_one_base_http_predict_and_generate(self):
        from deeplearning4j_tpu.models import zoo
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.serving import InferenceServer
        from deeplearning4j_tpu.serving.fleet import compiles_total

        conf = zoo.transformer_lm(vocab_size=17, t=16, d_model=32,
                                  n_heads=2, n_blocks=1,
                                  decode_cache_length=32)
        base = ComputationGraph(conf).init()

        server = InferenceServer(base, warmup=True, max_batch_size=4,
                                 decode_slots=2, kv_cache="paged",
                                 kv_page_size=8)
        server.load_adapter("tenant-a", net=_tenant_net(base, 1))
        server.load_adapter("tenant-b", net=_tenant_net(base, 2))
        server.start()
        try:
            assert server.wait_ready(600)
            url = server.url
            c0 = compiles_total()

            x = [[[t % 7] for t in range(16)]]
            p = {a: _post(url, "/predict", {"data": x, "adapter": a}
                          if a else {"data": x})["predictions"]
                 for a in (None, "tenant-a", "tenant-b")}
            assert not np.allclose(p["tenant-a"], p["tenant-b"])
            assert not np.allclose(p["tenant-a"], p[None])

            gen = {a: _post(url, "/generate",
                            dict({"prompt_ids": [1, 2, 3], "n_steps": 6,
                                  "temperature": 0.0},
                                 **({"adapter": a} if a else {})))["ids"]
                   for a in (None, "tenant-a", "tenant-b")}
            # Same prompt, per-tenant continuations: the prefix cache must
            # not leak KV across adapters and greedy outputs must differ.
            assert gen["tenant-a"] != gen["tenant-b"]
            assert gen["tenant-a"] != gen[None]

            # Adapter switches ride the SAME compiled programs: zero
            # serving-path XLA compiles after warmup.
            assert compiles_total() - c0 == 0

            # Concurrent mixed-tenant decode matches the sequential runs.
            res, errs = {}, []

            def run(name, adapter):
                try:
                    res[name] = _post(url, "/generate",
                                      {"prompt_ids": [1, 2, 3],
                                       "n_steps": 6, "temperature": 0.0,
                                       "adapter": adapter})["ids"]
                except Exception as e:  # pragma: no cover - diagnostic
                    errs.append(e)

            ts = [threading.Thread(target=run, args=(a, a))
                  for a in ("tenant-a", "tenant-b")]
            [t.start() for t in ts]
            [t.join() for t in ts]
            assert not errs
            assert res["tenant-a"] == gen["tenant-a"]
            assert res["tenant-b"] == gen["tenant-b"]
            assert compiles_total() - c0 == 0

            # /v1/models: adapter rows + the <=10% HBM acceptance ratio.
            with urllib.request.urlopen(url + "/v1/models",
                                        timeout=30) as r:
                row = json.loads(r.read())["models"][0]
            names = {a["name"] for a in row["adapters"]}
            assert names == {"tenant-a", "tenant-b"}
            for a in row["adapters"]:
                assert a["rank"] == 2 and a["bytes"] > 0 and a["pinned"]
            total = sum(a["bytes"] for a in row["adapters"])
            assert total <= 0.10 * row["hbm_bytes"]

            # Unknown adapter is a 400, on both routes.
            for route, payload in (("/predict", {"data": x}),
                                   ("/generate", {"prompt_ids": [1],
                                                  "n_steps": 1})):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _post(url, route, dict(payload, adapter="nope"))
                assert ei.value.code == 400
        finally:
            server.stop()

    def test_speculative_decoding_rejects_adapters(self):
        from deeplearning4j_tpu.models import zoo
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.serving import InferenceServer
        from deeplearning4j_tpu.serving.errors import InputValidationError

        conf = zoo.transformer_lm(vocab_size=17, t=16, d_model=16,
                                  n_heads=2, n_blocks=1,
                                  decode_cache_length=32)
        base = ComputationGraph(conf).init()
        draft = ComputationGraph(conf).init()
        server = InferenceServer(base, decode_slots=2, draft=draft,
                                 spec_k=2)
        server.load_adapter("t", net=_tenant_net(base, 3))
        try:
            with pytest.raises(InputValidationError):
                server.generate([1, 2], 2, adapter="t")
        finally:
            server.stop()
