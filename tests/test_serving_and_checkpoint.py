"""Remote stats transport, UI modules, inference serving, async checkpoint.

Reference analogs: `RemoteUIStatsStorageRouter.java` + `RemoteReceiverModule`
(train in one process, watch from another), `TrainModule.java:92-99` model
route + histogram module, `DL4jServeRouteBuilder.java` (serving), and the
SURVEY §5 exceed-goal: periodic async checkpoint with exact resume.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.api.storage import (
    InMemoryStatsStorage,
    RemoteStatsStorageRouter,
)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.serving import InferenceServer
from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.ui.stats import StatsListener
from deeplearning4j_tpu.util.checkpoint import (
    CheckpointListener,
    load_checkpoint,
    save_checkpoint,
)


def _net(seed=3, dropout=None):
    b = (NeuralNetConfiguration.builder()
         .seed(seed).learning_rate(0.1).updater("adam"))
    if dropout is not None:
        b = b.drop_out(dropout)
    conf = (b.list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _batch(step, n=16):
    r = np.random.RandomState(500 + step)
    X = r.randn(n, 4).astype("float32")
    Y = np.eye(3)[r.randint(0, 3, n)].astype("float32")
    return X, Y


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


class TestRemoteStats:
    def test_train_here_watch_there(self, rng):
        """The pod workflow: training process routes stats over HTTP to a
        UI server in 'another' process (real HTTP transport)."""
        storage = InMemoryStatsStorage()
        server = UIServer(port=0, enable_remote=True).attach(storage).start()
        try:
            router = RemoteStatsStorageRouter(server.url)
            net = _net()
            net.set_listeners(StatsListener(router, frequency=1,
                                            session_id="remote_sess"))
            X, Y = _batch(0)
            for _ in range(3):
                net.fit(X, Y)
            router.flush(timeout=30)
            assert router.dropped == 0
            # Server-side storage received everything over HTTP.
            assert "remote_sess" in storage.list_session_ids()
            assert storage.get_static_info("remote_sess")["num_params"] > 0
            updates = storage.get_updates("remote_sess")
            assert len(updates) == 3
            assert all(np.isfinite(u["score"]) for u in updates)
            # And the UI API serves them back.
            got = _get_json(server.url + "api/updates?sid=remote_sess")
            assert len(got) == 3
            router.close()
        finally:
            server.stop()

    def test_receiver_disabled_returns_403(self):
        server = UIServer(port=0).attach(InMemoryStatsStorage()).start()
        try:
            req = urllib.request.Request(
                server.url + "remote",
                data=json.dumps({"type": "update",
                                 "record": {"session_id": "s"}}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 403
        finally:
            server.stop()

    def test_histogram_and_model_pages(self, rng):
        storage = InMemoryStatsStorage()
        server = UIServer(port=0).attach(storage).start()
        try:
            net = _net()
            net.set_listeners(StatsListener(storage, frequency=1,
                                            session_id="s"))
            X, Y = _batch(0)
            net.fit(X, Y)
            for path in ("histogram", "model"):
                with urllib.request.urlopen(server.url + path, timeout=10) as r:
                    assert r.status == 200
                    assert b"<html" in r.read()[:200]
            # The data behind the pages: histograms present in updates,
            # config JSON in static info.
            u = storage.get_updates("s")[-1]
            assert "param_histograms" in u
            assert any(k.endswith("/W") for k in u["param_histograms"])
            assert "model_config_json" in storage.get_static_info("s")
        finally:
            server.stop()


class TestInferenceServer:
    def test_predict_matches_output_and_batches(self, rng):
        net = _net()
        X, Y = _batch(0)
        net.fit(X, Y)
        server = InferenceServer(net, port=0, max_batch_size=8,
                                 max_delay_ms=2).start()
        try:
            with urllib.request.urlopen(server.url + "/health", timeout=10) as r:
                assert json.loads(r.read())["status"] == "ok"
            data = X[:3].tolist()
            req = urllib.request.Request(
                server.url + "/predict",
                data=json.dumps({"data": data}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                preds = np.asarray(json.loads(r.read())["predictions"])
            np.testing.assert_allclose(preds, np.asarray(net.output(X[:3])),
                                       rtol=1e-5, atol=1e-6)

            # Concurrent requests are coalesced; all get correct slices.
            results = {}
            def call(i):
                results[i] = server.predict(X[i:i + 1])
            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            full = np.asarray(net.output(X))
            for i, p in results.items():
                np.testing.assert_allclose(p[0], full[i], rtol=1e-5,
                                           atol=1e-6)

            # Oversized request (> max_batch_size) is chunked server-side.
            big = server.predict(X)  # 16 rows > 8
            np.testing.assert_allclose(big, full, rtol=1e-5, atol=1e-6)
        finally:
            server.stop()

    def test_bad_request_400(self, rng):
        net = _net()
        server = InferenceServer(net, port=0).start()
        try:
            req = urllib.request.Request(
                server.url + "/predict", data=b"not json",
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 400
        finally:
            server.stop()


class TestCheckpointResume:
    def test_kill_and_resume_bit_for_bit(self, tmp_path, rng):
        """SURVEY §5 exceed-goal done-condition: resume reproduces the
        uninterrupted run exactly — params AND the rng stream (dropout on,
        so a wrong rng continuation would diverge)."""
        ckpt_dir = str(tmp_path / "ckpts")
        a = _net(dropout=0.7)
        listener = CheckpointListener(ckpt_dir, frequency=5, keep_last=2)
        a.set_listeners(listener)
        for step in range(10):
            X, Y = _batch(step)
            a.fit(X, Y)
        listener.flush()
        # keep_last pruning: only iters 10 and 5 -> keep_last=2 keeps both.
        assert len(listener.saved_paths) == 2
        ckpt5 = listener.saved_paths[0]
        assert ckpt5.endswith("iter5.zip")

        b = load_checkpoint(ckpt5)
        assert b.iteration == 5
        for step in range(5, 10):
            X, Y = _batch(step)
            b.fit(X, Y)
        pa = a.params()
        pb = b.params()
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))

    def test_save_checkpoint_sync_roundtrip(self, tmp_path, rng):
        net = _net()
        X, Y = _batch(0)
        net.fit(X, Y)
        path = str(tmp_path / "c.zip")
        save_checkpoint(net, path)
        back = load_checkpoint(path)
        np.testing.assert_array_equal(np.asarray(back.params()),
                                      np.asarray(net.params()))
        assert back.iteration == net.iteration
        # The restored net predicts identically.
        np.testing.assert_allclose(np.asarray(back.output(X)),
                                   np.asarray(net.output(X)), rtol=1e-6)


class TestFailureDetection:
    """SURVEY §5 exceed-goal (row: failure detection / elastic recovery):
    NaN/inf divergence detected mid-training and rolled back in place to
    the newest healthy checkpoint — the loop keeps running."""

    def _poison(self, net):
        import jax.numpy as jnp

        lk = net.layer_keys[0]
        pname = next(iter(net.params_tree[lk]))
        net.params_tree[lk][pname] = (
            net.params_tree[lk][pname] * jnp.asarray(np.nan))

    def test_detects_and_rolls_back(self, tmp_path):
        from deeplearning4j_tpu.util.failure import FailureDetectionListener

        net = _net()
        ckpts = CheckpointListener(str(tmp_path / "c"), frequency=2,
                                   keep_last=3)
        watchdog = FailureDetectionListener(ckpts, check_frequency=1)
        net.set_listeners(ckpts, watchdog)
        for step in range(6):
            net.fit(*_batch(step))
        good_iter = net.iteration
        assert good_iter == 6
        self._poison(net)
        # Detection lags one check interval (the watchdog inspects the
        # PREVIOUS interval's score so it never blocks the pipeline).
        net.fit(*_batch(98))
        net.fit(*_batch(99))
        assert watchdog.recoveries == 1
        assert net.iteration <= good_iter  # rolled back to a checkpoint
        assert np.all(np.isfinite(np.asarray(net.params())))
        # Training continues and reports finite scores again.
        for step in range(6, 10):
            net.fit(*_batch(step))
        assert np.isfinite(net.score_value)
        assert watchdog.recovery_log[0]["restored_iteration"] <= good_iter

    def test_skips_poisoned_checkpoint(self, tmp_path):
        from deeplearning4j_tpu.util.failure import (
            FailureDetectionListener, _checkpoint_healthy,
        )

        net = _net()
        ckpts = CheckpointListener(str(tmp_path / "c"), frequency=2,
                                   keep_last=4)
        net.set_listeners(ckpts)
        for step in range(4):
            net.fit(*_batch(step))
        ckpts.flush()
        healthy = list(ckpts.saved_paths)
        # A checkpoint written AFTER divergence began must be skipped.
        self._poison(net)
        net.fit(*_batch(98))
        net.fit(*_batch(99))
        ckpts.flush()
        assert len(ckpts.saved_paths) > len(healthy)
        bad = [p for p in ckpts.saved_paths if p not in healthy]
        assert any(not _checkpoint_healthy(p) for p in bad)
        watchdog = FailureDetectionListener(ckpts, check_frequency=1)
        watchdog._recover(net, net.iteration, float("nan"))
        assert watchdog.recovery_log[0]["restored_from"] in healthy
        assert np.all(np.isfinite(np.asarray(net.params())))

    def test_gives_up_after_max_recoveries(self, tmp_path):
        from deeplearning4j_tpu.util.failure import (
            FailureDetectionListener, TrainingDivergedError,
        )

        net = _net()
        ckpts = CheckpointListener(str(tmp_path / "c"), frequency=1)
        watchdog = FailureDetectionListener(ckpts, check_frequency=1,
                                            max_recoveries=0)
        net.set_listeners(ckpts, watchdog)
        net.fit(*_batch(0))
        self._poison(net)
        with pytest.raises(TrainingDivergedError):
            net.fit(*_batch(1))
            net.fit(*_batch(2))


def test_predict_timeout_configurable(rng):
    """ADVICE r4: predict()'s wait is a constructor knob (None = forever),
    and the timeout error names the knob."""
    from deeplearning4j_tpu.serving import InferenceServer

    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(1).learning_rate(0.1)
         .list()
         .layer(DenseLayer(n_out=4, activation="tanh"))
         .layer(OutputLayer(n_out=2, activation="softmax",
                            loss_function="mcxent"))
         .set_input_type(InputType.feed_forward(3))
         .build())).init()
    server = InferenceServer(net, port=0,
                             predict_timeout_s=120.0).start()
    try:
        assert server.predict_timeout_s == 120.0
        out = server.predict(rng.rand(2, 3).astype("float32"))
        assert out.shape == (2, 2)
    finally:
        server.stop()
