"""DataVec-bridge tests: record readers + record-reader iterators.

Reference analogs: `deeplearning4j-core/src/test/.../datasets/datavec/
RecordReaderDataSetiteratorTest.java` (CSV classification/regression),
`SequenceRecordReaderDataSetIteratorTest` (aligned sequence readers +
masking), ImageRecordReader directory-label tests, CIFAR iterator shape
tests. The two end-to-end cases the round-3 verdict asked for — a model
training from a directory of PNGs and a CSV regression model — live in
TestEndToEnd.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator
from deeplearning4j_tpu.datasets.records import (
    CSVRecordReader,
    CSVSequenceRecordReader,
    Cifar10DataSetIterator,
    ImageRecordReader,
    RecordReaderDataSetIterator,
    SequenceRecordReaderDataSetIterator,
    load_cifar10,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    RnnOutputLayer,
    SimpleRnn,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _write_csv(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(",".join(str(x) for x in r) + "\n")


class TestCSVRecordReader:
    def test_skip_lines_and_delimiter(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("header;x\n1;2\n3;4\n")
        rr = CSVRecordReader(skip_num_lines=1, delimiter=";").initialize(str(p))
        assert list(rr) == [["1", "2"], ["3", "4"]]

    def test_classification_one_hot_and_batching(self, tmp_path):
        p = tmp_path / "d.csv"
        rows = [[i * 0.1, i * 0.2, i % 3] for i in range(10)]
        _write_csv(p, rows)
        it = RecordReaderDataSetIterator(
            CSVRecordReader().initialize(str(p)), batch_size=4,
            label_index=2, num_classes=3)
        batches = list(it)
        assert [b.num_examples() for b in batches] == [4, 4, 2]
        assert batches[0].features.shape == (4, 2)
        assert batches[0].labels.shape == (4, 3)
        np.testing.assert_array_equal(batches[0].labels.sum(axis=1), 1.0)
        # Label column excluded from features.
        np.testing.assert_allclose(batches[0].features[1], [0.1, 0.2],
                                   atol=1e-6)

    def test_regression_label_range(self, tmp_path):
        p = tmp_path / "d.csv"
        _write_csv(p, [[1, 2, 3, 4], [5, 6, 7, 8]])
        it = RecordReaderDataSetIterator(
            CSVRecordReader().initialize(str(p)), batch_size=2,
            label_index=2, label_index_to=3, regression=True)
        (b,) = list(it)
        np.testing.assert_allclose(b.features, [[1, 2], [5, 6]])
        np.testing.assert_allclose(b.labels, [[3, 4], [7, 8]])

    def test_padded_batches_are_static_shape(self, tmp_path):
        p = tmp_path / "d.csv"
        _write_csv(p, [[i, i % 2] for i in range(5)])
        it = RecordReaderDataSetIterator(
            CSVRecordReader().initialize(str(p)), batch_size=4,
            label_index=1, num_classes=2, pad_batches=True)
        batches = list(it)
        assert all(b.features.shape[0] == 4 for b in batches)
        # Last batch: 1 real row, 3 padding rows masked out via the
        # per-example [B] mask the losses/eval stack consumes.
        assert batches[-1].labels_mask.shape == (4,)
        assert batches[-1].labels_mask.sum() == 1


class TestImageRecordReader:
    @pytest.fixture
    def image_dir(self, tmp_path):
        """Two classes: 'bright' disks vs 'dark' images, 12x12 PNGs."""
        from PIL import Image
        rng = np.random.RandomState(0)
        for label, base in (("bright", 200), ("dark", 40)):
            d = tmp_path / "imgs" / label
            d.mkdir(parents=True)
            for i in range(12):
                arr = np.clip(base + rng.randn(12, 12) * 15, 0, 255)
                Image.fromarray(arr.astype(np.uint8), "L").save(
                    str(d / f"{i}.png"))
        return str(tmp_path / "imgs")

    def test_parent_dir_labels_and_shapes(self, image_dir):
        rr = ImageRecordReader(12, 12, channels=1).initialize(image_dir)
        assert rr.labels == ["bright", "dark"]
        img, label = next(rr.records())
        assert img.shape == (12, 12, 1)
        assert 0.0 <= img.min() and img.max() <= 1.0
        it = RecordReaderDataSetIterator(rr, batch_size=8)
        b = next(iter(it))
        assert b.features.shape == (8, 12, 12, 1)  # NHWC
        assert b.labels.shape == (8, 2)

    def test_resize(self, image_dir):
        rr = ImageRecordReader(6, 6, channels=1).initialize(image_dir)
        img, _ = next(rr.records())
        assert img.shape == (6, 6, 1)


class TestSequenceReaders:
    def _seq_files(self, tmp_path, lengths, cols=3):
        rng = np.random.RandomState(1)
        paths = []
        for i, t in enumerate(lengths):
            p = tmp_path / f"seq_{i}.csv"
            _write_csv(p, rng.rand(t, cols).round(4).tolist())
            paths.append(str(p))
        return paths

    def test_two_reader_alignment_and_masks(self, tmp_path):
        fpaths = self._seq_files(tmp_path / "f1" if False else tmp_path, [4, 2])
        lab0 = tmp_path / "lab_0.csv"
        lab1 = tmp_path / "lab_1.csv"
        _write_csv(lab0, [[0], [1], [0], [1]])
        _write_csv(lab1, [[1], [0]])
        it = SequenceRecordReaderDataSetIterator(
            CSVSequenceRecordReader().initialize(fpaths),
            CSVSequenceRecordReader().initialize([str(lab0), str(lab1)]),
            batch_size=2, num_classes=2)
        (b,) = list(it)
        assert b.features.shape == (2, 4, 3)
        assert b.labels.shape == (2, 4, 2)
        np.testing.assert_array_equal(b.features_mask,
                                      [[1, 1, 1, 1], [1, 1, 0, 0]])
        # Padding timesteps carry zero labels.
        np.testing.assert_array_equal(b.labels[1, 2:], 0.0)

    def test_single_reader_label_column_split(self, tmp_path):
        p = tmp_path / "s.csv"
        _write_csv(p, [[0.1, 7, 0.2], [0.3, 8, 0.4]])
        it = SequenceRecordReaderDataSetIterator(
            CSVSequenceRecordReader().initialize(str(p)),
            batch_size=1, regression=True, label_index=1)
        (b,) = list(it)
        np.testing.assert_allclose(b.features[0], [[0.1, 0.2], [0.3, 0.4]])
        np.testing.assert_allclose(b.labels[0], [[7], [8]])


class TestCifar:
    def test_shapes_and_onehot(self):
        ds = load_cifar10(train=True, num_examples=64)
        assert ds.features.shape == (64, 32, 32, 3)
        assert ds.labels.shape == (64, 10)
        np.testing.assert_array_equal(ds.labels.sum(axis=1), 1.0)
        assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0

    def test_binary_format_parser(self, tmp_path, monkeypatch):
        """Hand-written data_batch files in the CIFAR binary layout."""
        rng = np.random.RandomState(0)
        d = tmp_path / "cifar"
        d.mkdir()
        for name in [f"data_batch_{i}.bin" for i in range(1, 6)]:
            rec = np.zeros((2, 3073), np.uint8)
            rec[:, 0] = [3, 7]
            rec[:, 1:] = rng.randint(0, 255, (2, 3072))
            rec.tofile(str(d / name))
        monkeypatch.setenv("CIFAR_DIR", str(d))  # read at call time
        ds = load_cifar10(train=True)
        assert ds.features.shape == (10, 32, 32, 3)
        np.testing.assert_array_equal(ds.labels.argmax(1),
                                      [3, 7] * 5)

    def test_iterator(self):
        it = Cifar10DataSetIterator(batch_size=16, num_examples=48)
        batches = list(it)
        assert len(batches) == 3
        assert batches[0].features.shape == (16, 32, 32, 3)


class TestEndToEnd:
    """The verdict's two done-conditions: LeNet-style training from a
    directory of PNGs, and a CSV regression model end-to-end."""

    def test_conv_net_trains_from_png_directory(self, tmp_path, rng):
        from PIL import Image
        r = np.random.RandomState(0)
        for label, base in (("bright", 210), ("dark", 45)):
            d = tmp_path / "imgs" / label
            d.mkdir(parents=True)
            for i in range(16):
                arr = np.clip(base + r.randn(10, 10) * 20, 0, 255)
                Image.fromarray(arr.astype(np.uint8), "L").save(
                    str(d / f"{i}.png"))
        reader = ImageRecordReader(10, 10, channels=1).initialize(
            str(tmp_path / "imgs"))
        conf = (NeuralNetConfiguration.builder()
                .seed(3).learning_rate(0.05).updater("adam")
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=3, stride=1,
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel_size=2, stride=2))
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss_function="mcxent"))
                .set_input_type(InputType.convolutional(10, 10, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        for _ in range(8):
            # Compose with the async staging wrapper, as users would.
            net.fit(AsyncDataSetIterator(
                RecordReaderDataSetIterator(reader, batch_size=8)))
        ev = net.evaluate(RecordReaderDataSetIterator(reader, batch_size=8))
        assert ev.accuracy() > 0.9

    def test_csv_regression_end_to_end(self, tmp_path, rng):
        r = np.random.RandomState(0)
        X = r.rand(128, 3)
        y = (2.0 * X[:, 0] - X[:, 1] + 0.5 * X[:, 2])[:, None]
        rows = np.concatenate([X, y], axis=1)
        p = tmp_path / "reg.csv"
        _write_csv(p, rows.round(6).tolist())
        reader = CSVRecordReader().initialize(str(p))
        it = RecordReaderDataSetIterator(reader, batch_size=32,
                                         label_index=3, regression=True)
        conf = (NeuralNetConfiguration.builder()
                .seed(3).learning_rate(0.05).updater("adam")
                .list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=1, activation="identity",
                                   loss_function="mse"))
                .set_input_type(InputType.feed_forward(3))
                .build())
        net = MultiLayerNetwork(conf).init()
        first = net.score(DataSet(X.astype("float32"),
                                  y.astype("float32")))
        for _ in range(60):
            net.fit(it)
        final = net.score(DataSet(X.astype("float32"), y.astype("float32")))
        assert final < first * 0.1, (first, final)

    def test_padded_batch_trains_and_evaluates(self, tmp_path):
        """The padded labels_mask must flow through fit() AND evaluate()
        (regression test: a [B, C]-shaped mask crashed both)."""
        p = tmp_path / "d.csv"
        _write_csv(p, [[i * 0.3, (3 - i) * 0.2, i % 2] for i in range(5)])
        it = RecordReaderDataSetIterator(
            CSVRecordReader().initialize(str(p)), batch_size=4,
            label_index=2, num_classes=2, pad_batches=True)
        conf = (NeuralNetConfiguration.builder()
                .seed(3).learning_rate(0.1)
                .list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(2))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(it)
        ev = net.evaluate(it)
        # Only the 5 real rows are counted, not the 3 padding rows.
        assert int(ev.confusion.matrix.sum()) == 5


class TestNativeCsv:
    """The C++ fastcsv parser (deeplearning4j_tpu/native) must agree with
    the Python reader exactly, and fall back gracefully."""

    def test_native_matches_python(self, tmp_path, rng):
        from deeplearning4j_tpu import native as native_mod
        from deeplearning4j_tpu.datasets.records import CSVRecordReader

        data = rng.randn(50, 7).astype("float32")
        path = str(tmp_path / "m.csv")
        np.savetxt(path, data, delimiter=",", fmt="%.6g",
                   header="a,b,c,d,e,f,g", comments="")
        rr = CSVRecordReader(skip_num_lines=1).initialize(path)
        m = rr.numeric_matrix()
        py = np.asarray([[float(v) for v in row] for row in rr.records()],
                        np.float32)
        np.testing.assert_allclose(m, py, rtol=1e-6)
        assert m.dtype == np.float32 and m.shape == (50, 7)
        # When the toolchain exists, the native path must actually be used.
        if native_mod.native_available():
            nat = native_mod.parse_numeric_csv(path, ",", 1)
            np.testing.assert_array_equal(nat, m)

    def test_non_numeric_falls_back(self, tmp_path):
        from deeplearning4j_tpu import native as native_mod
        from deeplearning4j_tpu.datasets.records import CSVRecordReader

        path = str(tmp_path / "s.csv")
        with open(path, "w") as f:
            f.write("1.0,2.0\n3.0,oops\n")
        # Native parser refuses (returns None)…
        if native_mod.native_available():
            assert native_mod.parse_numeric_csv(path, ",", 0) is None
        # …and numeric_matrix surfaces the Python error for bad floats.
        rr = CSVRecordReader().initialize(path)
        with pytest.raises(ValueError):
            rr.numeric_matrix()

    def test_ragged_rejected_by_native(self, tmp_path):
        from deeplearning4j_tpu import native as native_mod

        if not native_mod.native_available():
            pytest.skip("no toolchain")
        path = str(tmp_path / "r.csv")
        with open(path, "w") as f:
            f.write("1,2,3\n4,5\n")
        assert native_mod.parse_numeric_csv(path, ",", 0) is None

    def test_blank_line_skip_parity_and_hex_rejection(self, tmp_path):
        from deeplearning4j_tpu import native as native_mod

        if not native_mod.native_available():
            pytest.skip("no toolchain")
        # Blank lines count toward skip in BOTH paths (csv.reader parity).
        path = str(tmp_path / "b.csv")
        with open(path, "w") as f:
            f.write("\nheader,h2\n1,2\n3,4\n")
        from deeplearning4j_tpu.datasets.records import CSVRecordReader
        rr = CSVRecordReader(skip_num_lines=2).initialize(path)
        m = rr.numeric_matrix()
        np.testing.assert_array_equal(m, [[1, 2], [3, 4]])
        assert native_mod.parse_numeric_csv(path, ",", 2) is not None
        # Hex floats: Python float() rejects them; native must too.
        path2 = str(tmp_path / "h.csv")
        with open(path2, "w") as f:
            f.write("1.0,0x10\n")
        assert native_mod.parse_numeric_csv(path2, ",", 0) is None

    def test_empty_and_multibyte_delimiter(self, tmp_path):
        from deeplearning4j_tpu import native as native_mod
        from deeplearning4j_tpu.datasets.records import CSVRecordReader

        empty = str(tmp_path / "e.csv")
        open(empty, "w").close()
        data = str(tmp_path / "d.csv")
        with open(data, "w") as f:
            f.write("1,2\n3,4\n")
        m = CSVRecordReader().initialize([empty, data]).numeric_matrix()
        np.testing.assert_array_equal(m, [[1, 2], [3, 4]])
        assert CSVRecordReader().initialize(empty).numeric_matrix().shape == (0, 0)
        # Multibyte delimiter: documented None, not a ctypes explosion.
        assert native_mod.parse_numeric_csv(data, "é", 0) is None


class TestRecordReaderMultiDataSetIterator:
    """Multi-input/multi-output record bridging (reference:
    `RecordReaderMultiDataSetIterator.java:57` + its Builder)."""

    def _csvs(self, tmp_path, rng, n=24):
        Xa = rng.rand(n, 4).round(4)
        Xb = rng.rand(n, 3).round(4)
        ya = rng.randint(0, 3, n)
        yb = rng.rand(n, 2).round(4)
        pa, pb = str(tmp_path / "a.csv"), str(tmp_path / "b.csv")
        with open(pa, "w") as f:  # features_a + class label in col 4
            for i in range(n):
                f.write(",".join(map(str, list(Xa[i]) + [ya[i]])) + "\n")
        with open(pb, "w") as f:  # features_b + regression targets in 3:5
            for i in range(n):
                f.write(",".join(map(str, list(Xb[i]) + list(yb[i]))) + "\n")
        return pa, pb, Xa, Xb, ya, yb

    def test_batches_and_subsets(self, tmp_path, rng):
        from deeplearning4j_tpu.datasets.records import (
            CSVRecordReader, RecordReaderMultiDataSetIterator,
        )

        pa, pb, Xa, Xb, ya, yb = self._csvs(tmp_path, rng)
        it = (RecordReaderMultiDataSetIterator.builder(batch_size=8)
              .add_reader("a", CSVRecordReader().initialize(pa))
              .add_reader("b", CSVRecordReader().initialize(pb))
              .add_input("a", 0, 3)
              .add_input("b", 0, 2)
              .add_output_one_hot("a", 4, num_classes=3)
              .add_output("b", 3, 4)
              .build())
        batches = list(it)
        assert len(batches) == 3
        mds = batches[0]
        assert [f.shape for f in mds.features] == [(8, 4), (8, 3)]
        assert [l.shape for l in mds.labels] == [(8, 3), (8, 2)]
        np.testing.assert_allclose(mds.features[0], Xa[:8], atol=1e-6)
        np.testing.assert_allclose(mds.features[1], Xb[:8], atol=1e-6)
        np.testing.assert_array_equal(mds.labels[0],
                                      np.eye(3, dtype=np.float32)[ya[:8]])
        np.testing.assert_allclose(mds.labels[1], yb[:8], atol=1e-6)

    def test_two_input_two_output_graph_trains(self, tmp_path, rng):
        """End-to-end: a 2-input/2-output ComputationGraph trains from two
        CSV readers (the verdict's 'Done =' bar for this component)."""
        from deeplearning4j_tpu.datasets.records import (
            CSVRecordReader, RecordReaderMultiDataSetIterator,
        )
        from deeplearning4j_tpu.nn.conf.graph import MergeVertex
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        pa, pb, *_ = self._csvs(tmp_path, rng)

        def make_it():
            return (RecordReaderMultiDataSetIterator.builder(batch_size=8)
                    .add_reader("a", CSVRecordReader().initialize(pa))
                    .add_reader("b", CSVRecordReader().initialize(pb))
                    .add_input("a", 0, 3)
                    .add_input("b", 0, 2)
                    .add_output_one_hot("a", 4, num_classes=3)
                    .add_output("b", 3, 4)
                    .build())

        gb = (NeuralNetConfiguration.builder()
              .seed(7).learning_rate(0.05).updater("adam")
              .graph_builder()
              .add_inputs("ina", "inb")
              .add_layer("da", DenseLayer(n_out=16, activation="relu"), "ina")
              .add_layer("db", DenseLayer(n_out=16, activation="relu"), "inb")
              .add_vertex("m", MergeVertex(), "da", "db")
              .add_layer("cls", OutputLayer(n_out=3, activation="softmax",
                                            loss_function="mcxent"), "m")
              .add_layer("reg", OutputLayer(n_out=2, activation="identity",
                                            loss_function="mse"), "m")
              .set_outputs("cls", "reg"))
        gb.set_input_types(InputType.feed_forward(4), InputType.feed_forward(3))
        cg = ComputationGraph(gb.build()).init()
        first = list(make_it())[0]
        s0 = cg.score(first)
        for _ in range(20):
            cg.fit(make_it())
        assert cg.score(first) < s0

    def test_sequence_alignment(self, tmp_path, rng):
        from deeplearning4j_tpu.datasets.records import (
            CSVSequenceRecordReader, CSVRecordReader,
            RecordReaderMultiDataSetIterator,
        )

        lens = [3, 5, 2, 5]
        for i, t in enumerate(lens):
            with open(tmp_path / f"s{i}.csv", "w") as f:
                for j in range(t):
                    f.write(f"{i}.0,{j}.0\n")
        with open(tmp_path / "lab.csv", "w") as f:
            for i in range(len(lens)):
                f.write(f"{i % 2}\n")
        seq_paths = [str(tmp_path / f"s{i}.csv") for i in range(len(lens))]

        def make(align):
            return (RecordReaderMultiDataSetIterator.builder(batch_size=4)
                    .add_sequence_reader(
                        "s", CSVSequenceRecordReader().initialize(seq_paths))
                    .add_reader("l", CSVRecordReader().initialize(
                        str(tmp_path / "lab.csv")))
                    .add_input("s")
                    .add_output_one_hot("l", 0, num_classes=2)
                    .sequence_alignment_mode(align)
                    .build())

        mds = list(make("start"))[0]
        assert mds.features[0].shape == (4, 5, 2)
        np.testing.assert_array_equal(
            mds.features_masks[0][0], [1, 1, 1, 0, 0])
        mds_end = list(make("end"))[0]
        np.testing.assert_array_equal(
            mds_end.features_masks[0][0], [0, 0, 1, 1, 1])
        np.testing.assert_allclose(mds_end.features[0][0, 2:],
                                   mds.features[0][0, :3])

    def test_mismatched_reader_lengths_raise(self, tmp_path, rng):
        from deeplearning4j_tpu.datasets.records import (
            CSVRecordReader, RecordReaderMultiDataSetIterator,
        )
        for name, n in (("x.csv", 10), ("y.csv", 7)):
            with open(tmp_path / name, "w") as f:
                for i in range(n):
                    f.write(f"{i}.0\n")
        it = (RecordReaderMultiDataSetIterator.builder(batch_size=5)
              .add_reader("x", CSVRecordReader().initialize(str(tmp_path / "x.csv")))
              .add_reader("y", CSVRecordReader().initialize(str(tmp_path / "y.csv")))
              .add_input("x")
              .add_output("y")
              .build())
        with pytest.raises(ValueError, match="ran out of records"):
            list(it)


class TestLFWAndCurves:
    """Reference parity: LFWDataSetIterator + CurvesDataFetcher analogs
    (zero-egress: local archives when present, deterministic synthetic
    fallbacks otherwise)."""

    def test_lfw_iterator_shapes_and_split(self):
        from deeplearning4j_tpu.datasets.records import LFWDataSetIterator

        it = LFWDataSetIterator(batch_size=8, num_examples=40,
                                image_shape=(32, 32, 3), num_labels=4,
                                train=True, split_train_test=0.75)
        batches = list(it)
        assert sum(b.features.shape[0] for b in batches) == 30
        assert batches[0].features.shape[1:] == (32, 32, 3)
        assert batches[0].labels.shape[1] == 4
        assert batches[0].features.min() >= 0.0
        assert batches[0].features.max() <= 1.0
        test_it = LFWDataSetIterator(batch_size=8, num_examples=40,
                                     image_shape=(32, 32, 3), num_labels=4,
                                     train=False, split_train_test=0.75)
        assert test_it.total_examples() == 10

    def test_lfw_trains(self):
        from deeplearning4j_tpu.datasets.records import LFWDataSetIterator
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (
            ConvolutionLayer, OutputLayer, SubsamplingLayer,
        )
        from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        it = LFWDataSetIterator(batch_size=16, num_examples=48,
                                image_shape=(16, 16, 3), num_labels=3)
        conf = (NeuralNetConfiguration.builder()
                .seed(3).learning_rate(0.01).updater("adam")
                .list()
                .layer(ConvolutionLayer(n_out=8, kernel_size=3,
                                        convolution_mode="same",
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel_size=2, stride=2))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss_function="mcxent"))
                .set_input_type(InputType.convolutional(16, 16, 3))
                .build())
        net = MultiLayerNetwork(conf).init()
        first = list(it)[0]
        s0 = net.score(first)
        for _ in range(10):
            net.fit(it)
        assert net.score(first) < s0

    def test_curves_autoencoder_pretrain(self):
        from deeplearning4j_tpu.datasets.records import (
            CurvesDataSetIterator, load_curves,
        )

        ds = load_curves(num_examples=64)
        assert ds.features.shape == (64, 784)
        np.testing.assert_array_equal(ds.features, ds.labels)
        assert 0.01 < ds.features.mean() < 0.5  # sparse curve pixels

        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import AutoEncoder, OutputLayer
        from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        it = CurvesDataSetIterator(batch_size=32, num_examples=64)
        conf = (NeuralNetConfiguration.builder()
                .seed(1).learning_rate(0.1).updater("sgd")
                .list()
                .layer(AutoEncoder(n_out=32, activation="sigmoid"))
                .layer(OutputLayer(n_out=784, activation="sigmoid",
                                   loss_function="mse"))
                .set_input_type(InputType.feed_forward(784))
                .pretrain(True)
                .build())
        net = MultiLayerNetwork(conf).init()
        net.pretrain(it)
        assert np.isfinite(net.score_value)


class TestTsneGuard:
    def test_oversize_raises(self, rng):
        from deeplearning4j_tpu.plot.tsne import BarnesHutTsne

        t = BarnesHutTsne(theta=0.5, max_points=100)
        with pytest.raises(ValueError, match="max_points"):
            t.fit_transform(rng.randn(101, 4))
        # Explicit override runs (tiny budget keeps the test fast).
        t2 = BarnesHutTsne(theta=0.5, max_points=101, max_iter=5)
        Y = t2.fit_transform(rng.randn(101, 4))
        assert Y.shape == (101, 2)


def test_native_quoted_skip_region_falls_back(tmp_path):
    """A quoted header region (logical rows can span physical lines) must
    punt to the Python fallback so both paths start data at the same row."""
    from deeplearning4j_tpu import native as native_mod
    from deeplearning4j_tpu.datasets.records import CSVRecordReader

    if not native_mod.native_available():
        pytest.skip("no toolchain")
    path = str(tmp_path / "q.csv")
    with open(path, "w") as f:
        f.write('"multi\nline header",x\n1,2\n3,4\n')
    # Native path must refuse (quote in the skipped region)...
    assert native_mod.parse_numeric_csv(path, ",", 1) is None
    # ...and the public reader still parses via csv.reader, which counts
    # the quoted header as ONE logical row.
    m = CSVRecordReader(skip_num_lines=1).initialize(path).numeric_matrix()
    np.testing.assert_array_equal(m, [[1, 2], [3, 4]])
