"""Fused ResNet bottleneck kernel (kernels/bottleneck_block.py) tests:
Pallas-vs-XLA parity in interpret mode on CPU (forward float-close, f32
and bf16, identity and projection shortcuts, train and inference),
gradient parity through the `kernels/_diff.py` pairing, the int8-weight
inference variant, and the acceptance bit-identity contract — a graph
built from the fused `BottleneckBlock` layer under `DL4J_TPU_KERNELS=xla`
trains bit-identically to the same graph built from per-layer vertices.
PERF.md §27."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.kernels import bottleneck_block as bb
from deeplearning4j_tpu.kernels import registry
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BottleneckBlock,
    GlobalPoolingLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.models.resnet import _bottleneck, _bottleneck_fused, _conv_bn
from deeplearning4j_tpu.checkpoint import quantize

N_CLASSES = 3

_ENV_VARS = ["DL4J_TPU_KERNELS"] + [
    "DL4J_TPU_KERNEL_" + k.upper() for k in registry.kernel_names()]


@pytest.fixture(autouse=True)
def _clean_kernel_env(monkeypatch):
    for var in _ENV_VARS:
        monkeypatch.delenv(var, raising=False)
    registry.clear_cache()
    yield
    registry.clear_cache()


def _block_inputs(rng, *, b=2, h=6, w=6, filters=2, project=False,
                  stride=(1, 1), dtype="float32"):
    """Random x/params/state for one block. The identity shortcut needs
    cin == 4*filters (the resnet invariant)."""
    dt = jnp.dtype(dtype)
    f1, f3 = filters, 4 * filters
    cin = f3
    x = jnp.asarray(rng.randn(b, h, w, cin), dt)
    shapes = {"W_a": (1, 1, cin, f1), "W_b": (3, 3, f1, f1),
              "W_c": (1, 1, f1, f3)}
    feats = {"a": f1, "b": f1, "c": f3}
    if project:
        shapes["W_proj"] = (1, 1, cin, f3)
        feats["proj"] = f3
    params, state = {}, {}
    for n, f in feats.items():
        params[f"gamma_{n}"] = jnp.asarray(rng.rand(f) + 0.5, dt)
        params[f"beta_{n}"] = jnp.asarray(rng.randn(f) * 0.1, dt)
        state[f"mean_{n}"] = jnp.asarray(rng.randn(f) * 0.1, jnp.float32)
        state[f"var_{n}"] = jnp.asarray(rng.rand(f) + 0.5, jnp.float32)
    for k, s in shapes.items():
        params[k] = jnp.asarray(rng.randn(*s) * 0.2, dt)
    return x, params, state


def _run(monkeypatch, mode, x, params, state, *, stride=(1, 1),
         project=False, train=True):
    monkeypatch.setenv("DL4J_TPU_KERNEL_BOTTLENECK_BLOCK", mode)
    registry.clear_cache()
    return bb.bottleneck_forward(x, params, state, stride=stride,
                                 project=project, eps=1e-5,
                                 activation="relu", train=train)


_TOLS = {"float32": dict(rtol=2e-5, atol=2e-5),
         "bfloat16": dict(rtol=6e-2, atol=6e-2)}


class TestKernelParity:
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    @pytest.mark.parametrize("project,stride", [
        (False, (1, 1)), (True, (1, 1)), (True, (2, 2))])
    def test_train_forward_and_stats(self, monkeypatch, project, stride,
                                     dtype):
        rng = np.random.RandomState(11)
        x, params, state = _block_inputs(rng, project=project, stride=stride,
                                         dtype=dtype)
        yr, sr = _run(monkeypatch, "xla", x, params, state, stride=stride,
                      project=project)
        yp, sp = _run(monkeypatch, "pallas", x, params, state, stride=stride,
                      project=project)
        assert yp.dtype == jnp.dtype(dtype)
        assert set(sp) == set(bb.stat_keys(project))
        np.testing.assert_allclose(np.asarray(yp, np.float32),
                                   np.asarray(yr, np.float32),
                                   **_TOLS[dtype])
        for k in sp:
            np.testing.assert_allclose(np.asarray(sp[k], np.float32),
                                       np.asarray(sr[k], np.float32),
                                       **_TOLS[dtype])

    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    @pytest.mark.parametrize("project", [False, True])
    def test_infer_forward(self, monkeypatch, project, dtype):
        rng = np.random.RandomState(12)
        x, params, state = _block_inputs(rng, project=project, dtype=dtype)
        yr, _ = _run(monkeypatch, "xla", x, params, state, project=project,
                     train=False)
        yp, _ = _run(monkeypatch, "pallas", x, params, state, project=project,
                     train=False)
        assert yp.dtype == jnp.dtype(dtype)
        np.testing.assert_allclose(np.asarray(yp, np.float32),
                                   np.asarray(yr, np.float32),
                                   **_TOLS[dtype])

    def test_grads_match_fallback(self, monkeypatch):
        # pallas_call has no autodiff rule; the block must still sit inside
        # the engines' value_and_grad with the XLA composite's VJP
        # (kernels/_diff.py pairing).
        rng = np.random.RandomState(13)
        x, params, state = _block_inputs(rng, project=True)

        def grads_with(mode):
            monkeypatch.setenv("DL4J_TPU_KERNEL_BOTTLENECK_BLOCK", mode)
            registry.clear_cache()

            def loss(p, xv):
                y, _ = bb.bottleneck_forward(xv, p, state, stride=(1, 1),
                                             project=True, eps=1e-5,
                                             activation="relu", train=True)
                return jnp.sum(y ** 2)

            return jax.grad(loss, argnums=(0, 1))(params, x)

        gp, gr = grads_with("pallas"), grads_with("xla")
        for p, r in zip(jax.tree_util.tree_leaves(gp),
                        jax.tree_util.tree_leaves(gr)):
            np.testing.assert_allclose(np.asarray(p), np.asarray(r),
                                       rtol=2e-4, atol=2e-5)

    def test_int8_inference_parity(self, monkeypatch):
        rng = np.random.RandomState(14)
        x, params, state = _block_inputs(rng, project=True)
        qparams = dict(params)
        for n in ("a", "b", "c", "proj"):
            q, scale = quantize.quantize_array(np.asarray(params[f"W_{n}"]))
            qparams[f"W_{n}"] = jnp.asarray(q)
            qparams[f"W_{n}__scale"] = jnp.asarray(scale)
        yr, _ = _run(monkeypatch, "xla", x, qparams, state, project=True,
                     train=False)
        yp, _ = _run(monkeypatch, "pallas", x, qparams, state, project=True,
                     train=False)
        np.testing.assert_allclose(np.asarray(yp), np.asarray(yr),
                                   rtol=2e-5, atol=2e-5)
        # ... and the quantized block tracks the float one loosely.
        yf, _ = _run(monkeypatch, "xla", x, params, state, project=True,
                     train=False)
        np.testing.assert_allclose(np.asarray(yp), np.asarray(yf),
                                   rtol=0.2, atol=0.2)

    def test_int8_train_refused(self, monkeypatch):
        rng = np.random.RandomState(15)
        x, params, state = _block_inputs(rng)
        for n in ("a", "b", "c"):
            q, scale = quantize.quantize_array(np.asarray(params[f"W_{n}"]))
            params[f"W_{n}"] = jnp.asarray(q)
            params[f"W_{n}__scale"] = jnp.asarray(scale)
        with pytest.raises(ValueError, match="inference-only"):
            bb.bottleneck_forward(x, params, state, stride=(1, 1),
                                  project=False, eps=1e-5, activation="relu",
                                  train=True)

    def test_probe_reports_all_candidates(self):
        selected, rows = registry.probe(
            "bottleneck_block", backend="cpu",
            shapes=(2, 6, 6, 8, 2, 8, 1, 1), dtypes=("float32",),
            meta=(("train", True), ("project", False), ("act", "relu"),
                  ("int8", False)))
        assert selected == "xla"
        by_name = {r["impl"]: r for r in rows}
        assert not by_name["pallas"]["available"]
        assert "TPU backend" in by_name["pallas"]["reason"]
        assert by_name["xla"]["available"]


# --------------------------------------------------------------------------
# Acceptance bit-identity: fused layer vs unfused vertex chain, both under
# DL4J_TPU_KERNELS=xla, with the unfused net's initialization mapped onto
# the fused layer's parameter names.


def _graph_conf(fused: bool, image=6, filters=2):
    b = (NeuralNetConfiguration.builder()
         .seed(21).learning_rate(0.01).updater("nesterovs").momentum(0.9)
         .weight_init("relu").dtype("float32")
         .graph_builder()
         .add_inputs("input"))
    x = _conv_bn(b, "stem", "input", 4 * filters, (1, 1), (1, 1))
    block = _bottleneck_fused if fused else _bottleneck
    x = block(b, "b0", x, filters, (1, 1), project=False)
    x = block(b, "b1", x, filters, (2, 2), project=True)
    b.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
    b.add_layer("fc", OutputLayer(n_out=N_CLASSES, activation="softmax",
                                  loss_function="mcxent",
                                  weight_init="xavier"), "avgpool")
    return (b.set_outputs("fc")
            .set_input_types(InputType.convolutional(image, image, 3))
            .build())


def _cp(a):
    # TRUE copy: jnp.asarray(np.asarray(x)) is zero-copy on CPU, which
    # would alias donated fit buffers and read back recycled memory.
    return jnp.array(np.array(a))


def _map_unfused_to_fused(nu, nf):
    """Copy the unfused net's initialization onto the fused net's
    per-block parameter/state names."""
    pf = {k: {p: _cp(v) for p, v in d.items()} for k, d in nf.params_tree.items()}
    sf = {k: {p: _cp(v) for p, v in d.items()} for k, d in nf.state.items()}
    pu, su = nu.params_tree, nu.state
    for shared in ("stem_conv", "stem_bn", "fc"):
        pf[shared] = {p: _cp(v) for p, v in pu[shared].items()}
    sf["stem_bn"] = {p: _cp(v) for p, v in su["stem_bn"].items()}
    for blk, project in (("b0", False), ("b1", True)):
        branches = ("a", "b", "c") + (("proj",) if project else ())
        dst = f"{blk}_block"
        for n in branches:
            pf[dst][f"W_{n}"] = _cp(pu[f"{blk}_{n}_conv"]["W"])
            pf[dst][f"gamma_{n}"] = _cp(pu[f"{blk}_{n}_bn"]["gamma"])
            pf[dst][f"beta_{n}"] = _cp(pu[f"{blk}_{n}_bn"]["beta"])
            sf[dst][f"mean_{n}"] = _cp(su[f"{blk}_{n}_bn"]["mean"])
            sf[dst][f"var_{n}"] = _cp(su[f"{blk}_{n}_bn"]["var"])
    nf.params_tree, nf.state = pf, sf
    return nf


def _batches(n=3, b=4, image=6):
    rng = np.random.RandomState(33)
    out = []
    for _ in range(n):
        X = rng.randn(b, image, image, 3).astype(np.float32)
        Y = np.eye(N_CLASSES, dtype=np.float32)[rng.randint(0, N_CLASSES, b)]
        out.append(DataSet(X, Y))
    return out


class TestFusedLayerBitIdentity:
    def test_xla_mode_matches_unfused_chain(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_KERNELS", "xla")
        registry.clear_cache()
        nu = ComputationGraph(_graph_conf(fused=False)).init()
        nf = _map_unfused_to_fused(nu, ComputationGraph(_graph_conf(fused=True)).init())

        x0 = np.asarray(_batches(n=1)[0].features)
        np.testing.assert_array_equal(np.asarray(nf.output(x0)),
                                      np.asarray(nu.output(x0)))

        for ds in _batches():
            nu.fit(ds)
            nf.fit(ds)

        pu, pf = nu.params_tree, nf.params_tree
        for blk, project in (("b0", False), ("b1", True)):
            branches = ("a", "b", "c") + (("proj",) if project else ())
            for n in branches:
                np.testing.assert_array_equal(
                    np.asarray(pf[f"{blk}_block"][f"W_{n}"]),
                    np.asarray(pu[f"{blk}_{n}_conv"]["W"]))
                np.testing.assert_array_equal(
                    np.asarray(pf[f"{blk}_block"][f"gamma_{n}"]),
                    np.asarray(pu[f"{blk}_{n}_bn"]["gamma"]))
                np.testing.assert_array_equal(
                    np.asarray(nf.state[f"{blk}_block"][f"mean_{n}"]),
                    np.asarray(nu.state[f"{blk}_{n}_bn"]["mean"]))
                np.testing.assert_array_equal(
                    np.asarray(nf.state[f"{blk}_block"][f"var_{n}"]),
                    np.asarray(nu.state[f"{blk}_{n}_bn"]["var"]))
        for shared in ("stem_conv", "stem_bn", "fc"):
            for p in pu[shared]:
                np.testing.assert_array_equal(np.asarray(pf[shared][p]),
                                              np.asarray(pu[shared][p]))

    def test_forced_pallas_fused_net_trains(self, monkeypatch):
        # The fused layer's Pallas path (interpret on CPU) must survive a
        # real fit loop — value_and_grad through the _diff pairing — and
        # land float-close to the fallback.
        def train(mode):
            monkeypatch.setenv("DL4J_TPU_KERNELS", mode)
            registry.clear_cache()
            net = ComputationGraph(_graph_conf(fused=True)).init()
            for ds in _batches(n=2):
                net.fit(ds)
            return net

        np_, nx = train("pallas"), train("xla")
        for p, r in zip(jax.tree_util.tree_leaves(np_.params_tree),
                        jax.tree_util.tree_leaves(nx.params_tree)):
            np.testing.assert_allclose(np.asarray(p), np.asarray(r),
                                       rtol=1e-3, atol=1e-4)
